"""Replica-parallel serving: a prefix-aware least-loaded router over N
engines.

One :class:`~apex_tpu.serving.Engine` — even tp-sharded, paged,
quantized and pipelined — is a hard ceiling on aggregate tokens/s. The
next multiplier is data parallelism: run N engine replicas (each
optionally ``mesh=``-sharded, so the fleet is a tp × dp grid) behind a
HOST-SIDE router that turns the load gauges, backpressure hints and
fault containment the serving stack already emits into scale-out. The
router is pure host bookkeeping — it owns one
:class:`~apex_tpu.serving.Scheduler` per engine and adds ZERO compiled
programs; every device byte stays inside its replica.

**Routing** (:meth:`Router.submit`) is a two-signal decision over the
live replicas:

1. **Prefix affinity.** Multi-turn and shared-template traffic is
   dominated by prompts whose K/V already lives in SOME replica's
   prefix cache — but only that replica's. The router hashes the
   prompt's rolling block keys ONCE
   (:meth:`PrefixCache.block_keys`) and probes every live replica's
   cache read-only (:meth:`PrefixCache.probe` — no counters, no LRU
   churn on the N-1 losers), preferring the replica holding the
   longest verified prefix: the request lands where its K/V is, turns
   chunk prefill into a copy-on-write page share, and the probe keys
   ride along to the chosen scheduler (``submit(prefix_keys=...)``) so
   the hash is never recomputed.
2. **Least-loaded admission.** Ties — and the no-match majority at
   cold start — fall to load: free slots (desc), queue depth (asc),
   then free pool pages (desc), read from each replica's host-only
   :meth:`Scheduler.load_snapshot` (the same quantities the
   ``serving.pool.*`` / occupancy gauges publish, sampled at routing
   time instead of scraped from telemetry).

**Backpressure composes across replicas**: a chosen replica at queue
capacity is not an error but a *spill* — the router retries the
next-best replica (counted as ``serving.router.spills``) and raises
:class:`~apex_tpu.serving.QueueFull` only when EVERY live replica is
saturated, with ``retry_after_s`` the MAX of the replicas'
data-driven hints (the fleet has space when its slowest-to-free
replica does; replicas with no measured decode EMA contribute None and
never fake a number).

**A dead replica is a routing event, not an outage.** The router-tier
:class:`~apex_tpu.serving.FaultPlan` kind ``"replica_death"``
(consumed by :meth:`FaultPlan.take_replica_deaths` in
:meth:`Router.step`) — or an operator's :meth:`Router.kill_replica` —
drains the victim through :meth:`Scheduler.drain_requests`: every
queued and in-flight request rolls back to a servable queued state
(outputs cleared, paid-compute counters and the original submit clock
kept — the PR 7 quarantine machinery, minus the retry charge: a
replica death is not the request's fault), its slots free their pages
so the dead pool audits leak-free, and the drained requests re-route
onto the survivors through the normal affinity/least-loaded path.
Requests on surviving replicas never notice: greedy decode depends
only on a slot's own K/V lineage, so their tokens stay BITWISE
identical to a fault-free run even as drained refugees join their
batches (pinned by ``tests/L0/test_router.py``).

Telemetry (all host-side, through the shared registry): counters
``serving.router.routed`` / ``affinity_hits`` / ``spills`` /
``replica_deaths`` / ``requeued``, the ``serving.router.replicas_alive``
gauge, and per-replica load gauges namespaced as
``serving.router.replica<i>.{queue_depth, slots_busy, pages_free,
host_bytes_free}`` (the last only on hierarchical-KV replicas — the
swap arena's remaining headroom, the least-loaded tie-break's newest
input) so N replicas sharing one registry never clobber each other's
pool gauges. Replica-internal metrics (TTFT, step latencies, prefix
counters, fault counters) flow into the SAME shared registry as
fleet-wide aggregates — which is what a capacity dashboard wants —
while per-replica prefix accounting uses
:meth:`PrefixCache.stats_since` deltas, immune to the counters'
cumulative-across-reset semantics.

**Disaggregated serving** (``roles=[...]``): replica role is a
first-class routing policy. A ``"prefill"`` replica ingests prompts
through chunk prefill and — at ingestion completion — exports the
finished block-aligned prefix into the fleet's SHARED
:class:`~apex_tpu.serving.HostTier` arena (``shared=True``, one
instance co-owned by every engine) via the async per-shard-CRC'd
swap-out; it never decodes a token. The router collects the ready
hand-over (:meth:`Scheduler.take_handoffs` — the record's swap-out has
completed, so an importer can never race the CRC), transfers record
ownership (the exporter's cache entry stands down, the arena record
survives), registers the record as a born-swapped prefix on the best
``"decode"``-capable replica and re-submits the request there
(``_handoff=True``). The decode replica's ordinary admission path —
prefix match, CRC-verified swap-in scatter, copy-on-write page share —
resumes prefill at the exact committed offset and samples the first
token bitwise-identically to a single-replica run: zero re-prefill on
the happy path. A corrupt, evicted or failed record degrades per the
hierarchical-KV contract to a VERIFIED MISS (the decode side
re-prefills cold, counted as ``serving.disagg.reprefills``), never a
wrong token. ``roles=None`` (every replica ``"both"``) is the
verbatim default and leaves every code path above untouched. In a
mixed fleet quarantine requeues also flow back through the router
(:class:`Scheduler` ``on_requeue``), so a re-routed request re-probes
the LIVE replicas and the arena at re-route time instead of being
pinned to its first home.

CPU-regime note (same shape as every serving PR): replicas on this
box's CPU backend share cores, so N-replica tokens/s is NOT a scaling
measurement here — the CPU-honest columns are prefix-affinity hit rate
vs the random-routing control, bitwise parity across replica counts,
and leak-free drains; the aggregate-throughput scaling claim is
silicon's (``bench_serving.py --replica-router`` prints both with the
caveat attached). For ``roles`` fleets the CPU-honest columns are
decode-beat isolation (``serving.disagg.decode_isolation``) and the
handoff byte/latency histograms — not tokens/s.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.log_util import get_logger

from .routing_policy import (ROUTE_POLICIES, fleet_retry_hint,
                             note_placement, random_order,
                             rank_replicas)
from .scheduler import QueueFull, Request, Scheduler
from .slo import TenantLedger

__all__ = ["Router"]

_logger = get_logger("serving")

# The decision core lives in routing_policy (shared with the
# process-level FleetController — both fronts provably rank, spill
# and hint through the SAME functions); these aliases keep the
# router's historical names importable.
_ROUTE_POLICIES = ROUTE_POLICIES


class Router:
    """N ``Scheduler``+``Engine`` replicas behind one prefix-aware
    least-loaded ``submit()`` (see module docstring).

    Parameters
    ----------
    engines:
        The replica engines, pre-built by the caller (so tp meshes,
        quantized tiers and pool geometry compose per replica exactly
        as on a single engine). Serving geometry (``slots`` /
        ``max_len`` / ``prefill_len`` / ``chunk_len``) must agree
        across replicas — routing treats them as interchangeable — and
        with ``retain_prefixes=True`` so must the prefix block length.
    registry:
        Shared :class:`~apex_tpu.telemetry.MetricsRegistry`: the router
        emits ``serving.router.*`` and hands the SAME registry to every
        replica scheduler (counters and histograms aggregate
        fleet-wide; per-replica load gauges are namespaced — see
        module docstring).
    route_policy:
        ``"affinity"`` (default): longest probed prefix first, load as
        the tie-break — degrades to pure least-loaded when retention
        is off (nothing to probe). ``"least_loaded"``: gauges only.
        ``"random"``: seeded uniform routing — the bench's control row,
        not a production mode.
    seed:
        The ``"random"`` policy's RNG seed (unused otherwise).
    fault_plan:
        A ROUTER-TIER :class:`~apex_tpu.serving.FaultPlan`: only its
        ``"replica_death"`` specs are consumed here (per-replica chaos
        belongs in ``replica_plans``). Ticks are router steps.
    replica_plans:
        Optional per-replica scheduler fault plans (length N), passed
        through to each :class:`~apex_tpu.serving.Scheduler` — replica-
        tier chaos composes with router-tier deaths.
    tracer:
        Optional :class:`~apex_tpu.telemetry.Tracer`: request-level
        lifecycle tracing. The router emits one ``route`` span per
        submitted request (chosen replica, probed affinity length,
        spill count) and hands each replica a ``for_replica(i)`` view
        so every downstream span carries the replica index as its
        Chrome ``pid``. ``None`` (default) is the zero-cost off
        switch — no span objects exist and token streams are bitwise
        unchanged.
    **scheduler_kw:
        Everything else a :class:`~apex_tpu.serving.Scheduler` takes
        (``max_queue`` — PER REPLICA — ``eos_id``, ``chunked``,
        ``retain_prefixes``, ``speculative``, ``pipeline_depth``,
        ``fault_policy``, ...), applied uniformly to every replica.
    """

    def __init__(self, engines: Sequence, *, registry=None,
                 route_policy: str = "affinity", seed: int = 0,
                 roles: Optional[Sequence[str]] = None,
                 fault_plan=None, replica_plans=None, tracer=None,
                 **scheduler_kw):
        engines = list(engines)
        if not engines:
            raise ValueError("Router needs at least one engine")
        if route_policy not in _ROUTE_POLICIES:
            raise ValueError(f"route_policy {route_policy!r} not in "
                             f"{_ROUTE_POLICIES}")
        for fleet_kw in ("role", "on_requeue"):
            if fleet_kw in scheduler_kw:
                raise ValueError(
                    f"{fleet_kw!r} is fleet policy — pass "
                    "Router(roles=[...]) instead of a per-scheduler "
                    "keyword")
        self.roles: List[str] = [str(r) for r in roles] \
            if roles is not None else ["both"] * len(engines)
        if len(self.roles) != len(engines):
            raise ValueError(
                f"roles has {len(self.roles)} entries for "
                f"{len(engines)} replicas")
        self._mixed = any(r != "both" for r in self.roles)
        self._tier = None
        if self._mixed:
            # a split fleet is only a fleet if BOTH halves exist: an
            # all-prefill fleet can never emit a token, an all-decode
            # fleet can never accept a prompt — both are configuration
            # errors, not degraded modes
            if not any(r in ("prefill", "both") for r in self.roles):
                raise ValueError(
                    f"roles {self.roles} has no prefill-capable "
                    "replica ('prefill' or 'both'): nothing can "
                    "ingest a prompt")
            if not any(r in ("decode", "both") for r in self.roles):
                raise ValueError(
                    f"roles {self.roles} has no decode-capable "
                    "replica ('decode' or 'both'): nothing can emit "
                    "a token")
            tiers = {id(getattr(e, "host_tier", None)) for e in engines}
            tier0 = getattr(engines[0], "host_tier", None)
            if tier0 is None or len(tiers) != 1:
                raise ValueError(
                    "a roles fleet hands K/V over through ONE shared "
                    "host arena: build every engine with the same "
                    "HostTier(shared=True) instance "
                    "(host_tier=tier on each Engine)")
            if not getattr(tier0, "shared", False):
                raise ValueError(
                    "the fleet's common HostTier must be built with "
                    "shared=True: per-engine audits and resets must "
                    "know the arena is co-owned")
            self._tier = tier0
        geo0 = self._geometry(engines[0])
        for i, e in enumerate(engines[1:], 1):
            if self._geometry(e) != geo0:
                raise ValueError(
                    f"replica {i} serving geometry {self._geometry(e)} "
                    f"differs from replica 0's {geo0} — the router "
                    "routes any request to any replica, so slots/"
                    "max_len/prefill_len/chunk_len must agree")
        if replica_plans is not None \
                and len(replica_plans) != len(engines):
            raise ValueError(
                f"replica_plans has {len(replica_plans)} entries for "
                f"{len(engines)} replicas")
        self.registry = registry
        self.route_policy = route_policy
        self.fault_plan = fault_plan
        self.tracer = tracer
        self._rng = np.random.default_rng(seed)
        # one SLO policy governs the whole in-process fleet: routing
        # reads base_priority from it (SLO-aware rank order), and all
        # replicas share ONE TenantLedger so weighted-fair accounting
        # is fleet-wide, not per-replica (the process fleet can't share
        # a lock across processes — its workers each build their own;
        # see docs/serving.md "Overload & SLO")
        self._slo = scheduler_kw.get("slo")
        if self._slo is not None \
                and scheduler_kw.get("tenant_ledger") is None:
            scheduler_kw = dict(scheduler_kw)
            scheduler_kw["tenant_ledger"] = TenantLedger(
                self._slo.tenant_weights)
        # each replica gets a for_replica(i) view of the tracer, so
        # every span its scheduler/engine/workers emit lands under
        # Chrome process i without threading pid through call sites
        self.replicas: List[Scheduler] = [
            Scheduler(e, registry=registry,
                      role=self.roles[i],
                      on_requeue=self._requeue if self._mixed
                      else None,
                      fault_plan=replica_plans[i]
                      if replica_plans is not None else None,
                      tracer=tracer.for_replica(i)
                      if tracer is not None else None,
                      **scheduler_kw)
            for i, e in enumerate(engines)]
        for i, s in enumerate(self.replicas):
            s.replica_index = i     # stamps completion records
        self.alive: List[bool] = [True] * len(self.replicas)
        # affinity needs something to probe: with retention off the
        # caches stay empty, so the policy honestly degrades to pure
        # least-loaded instead of paying N no-op probes per request
        self.affinity_enabled = (
            route_policy == "affinity"
            and all(s.retain_prefixes for s in self.replicas))
        if self.affinity_enabled:
            blocks = {s.engine.prefix_cache.block_len
                      for s in self.replicas}
            if len(blocks) > 1:
                raise ValueError(
                    f"prefix block_len differs across replicas "
                    f"({sorted(blocks)}): one set of rolling hashes "
                    "must probe every cache")
        # uid -> replica index of the CURRENT placement (rewritten when
        # a drain re-routes; tests and the bench read it). Bounded:
        # routing never reads it back, so it is observability state —
        # a long-running router must not grow one entry per request
        # forever (oldest placements age out past the cap)
        self.placements: Dict[int, int] = {}
        # requests drained from a dead replica that no survivor could
        # take yet (all queues full at drain time): re-routed at the
        # top of every step, ahead of new admissions
        self._overflow: collections.deque = collections.deque()
        # ready hand-overs no decode-capable replica could queue yet
        # (record ownership already transferred): retried every beat
        self._handoff_overflow: collections.deque = collections.deque()
        self._tick = 0              # router step index (FaultPlan clock)
        self._closed = False

    @staticmethod
    def _geometry(engine) -> tuple:
        return (engine.slots, engine.max_len, engine.prefill_len,
                engine.chunk_len)

    # ------------------------------------------------------------- routing
    def _alive_indices(self) -> List[int]:
        idx = [i for i, a in enumerate(self.alive) if a]
        if not idx:
            raise RuntimeError(
                "no live replicas — the fleet is an outage, not a "
                "routing event")
        return idx

    def _capable_indices(self, capability: Optional[str]) -> List[int]:
        """Live replicas eligible for ``capability`` (``"prefill"`` /
        ``"decode"`` / None for any). On the all-``"both"`` default
        fleet this is exactly :meth:`_alive_indices` — role filtering
        only exists once ``roles`` made the fleet mixed."""
        idx = self._alive_indices()
        if capability is None or not self._mixed:
            return idx
        want = ("prefill", "both") if capability == "prefill" \
            else ("decode", "both")
        idx = [i for i in idx if self.roles[i] in want]
        if not idx:
            raise RuntimeError(
                f"no live {capability}-capable replica — the fleet "
                "lost a whole role tier (outage, not a routing event)")
        return idx

    def _probe_keys(self, request: Request):
        """The prompt's rolling block keys, computed ONCE per routed
        request (every replica's cache hashes identically — block_len
        agreement is enforced at construction)."""
        pcache = self.replicas[self._alive_indices()[0]] \
            .engine.prefix_cache
        prompt = tuple(request.prompt)
        return pcache.block_keys(prompt,
                                 len(prompt) // pcache.block_len)

    def _route_order(self, request: Request,
                     capability: Optional[str] = None):
        """``(keys, ordered_replicas, match_lens)``: live (and, in a
        mixed-roles fleet, ``capability``-eligible) replicas
        best-first. Affinity ranks by probed prefix length, then load;
        least-loaded by load alone; random by a seeded shuffle."""
        alive = self._capable_indices(capability)
        if self.route_policy == "random":
            return None, random_order(alive, self._rng), \
                {i: 0 for i in alive}
        keys = None
        lens = {i: 0 for i in alive}
        if self.affinity_enabled:
            pc0 = self.replicas[alive[0]].engine.prefix_cache
            if len(request.prompt) < pc0.block_len:
                # a sub-block prompt can never match a cache entry:
                # skip the hash walk AND the N probes ([] is exactly
                # what block_keys returns for zero full blocks, so
                # downstream consumers see identical values)
                keys = []
            else:
                keys = self._probe_keys(request)
                for i in alive:
                    lens[i] = \
                        self.replicas[i].engine.prefix_cache.probe(
                            request.prompt, keys=keys)
        snaps = {i: self.replicas[i].load_snapshot() for i in alive}
        # static base priority only (no aging clock): deterministic
        # arithmetic both routing fronts reproduce identically
        pri = self._slo.base_priority(request) \
            if self._slo is not None else 0
        # LoRA adapter affinity: a replica whose device arena already
        # holds the request's adapter serves a bind as a hit, not a
        # swap-in — ranked right after the prefix match
        hits = None
        if request.adapter is not None:
            hits = {i: int(request.adapter
                           in (snaps[i].get("resident_adapters") or ()))
                    for i in alive}
        return keys, rank_replicas(alive, lens, snaps, priority=pri,
                                   adapter_hits=hits), lens

    def submit(self, request: Request) -> Request:
        """Route ``request`` to the best live replica (see module
        docstring). Raises :class:`~apex_tpu.serving.QueueFull` only
        when EVERY live replica's queue is at capacity —
        ``retry_after_s`` is then the max of the replicas' measured
        hints (None when no replica has measured a decode step yet)."""
        t_route = self.tracer.now() if self.tracer is not None else 0.0
        # a NEW prompt needs ingestion: in a mixed fleet only
        # prefill-capable replicas are candidates (decode-role
        # replicas serve router hand-overs, routed in step())
        keys, order, lens = self._route_order(request, "prefill")
        hints: List[Optional[float]] = []
        for n_spilled, i in enumerate(order):
            try:
                # count_rejection=False: a full replica here is a
                # SPILL candidate, not a caller-visible rejection —
                # the fleet-level raise below counts the real one
                self.replicas[i].submit(request, prefix_keys=keys,
                                        count_rejection=False)
            except QueueFull as e:
                hints.append(e.retry_after_s)
                continue
            note_placement(self.placements, request.uid, i)
            if self.registry is not None:
                self.registry.counter_inc("serving.router.routed")
                if lens[i] > 0:
                    self.registry.counter_inc(
                        "serving.router.affinity_hits")
                if n_spilled:
                    self.registry.counter_inc("serving.router.spills",
                                              n_spilled)
            if self.tracer is not None:
                # the routing decision, on the chosen replica's lane:
                # probed affinity length, spill count, policy
                self.tracer.event(request.uid, "route", t0=t_route,
                                  dur=self.tracer.now() - t_route,
                                  pid=i, replica=i,
                                  policy=self.route_policy,
                                  affinity_len=lens[i],
                                  spills=n_spilled)
            return request
        hint = fleet_retry_hint(hints)
        if self.registry is not None:
            # ONE caller-visible rejection (the per-replica probes
            # above were suppressed — spills are not rejections)
            self.registry.counter_inc("serving.requests.rejected")
        suffix = f" (retry_after_s~{hint:.3f})" if hint else ""
        raise QueueFull(
            f"all {len(order)} live replica queues at capacity; retry "
            f"after a step() or shed load{suffix}", retry_after_s=hint)

    # ------------------------------------------------------------- stepping
    def step(self) -> bool:
        """One router beat: consume scheduled replica deaths, re-route
        any drained overflow, then run one heartbeat on every live
        replica. Returns True if anything made progress (a replica
        beat did work, or an overflow request found a home)."""
        tick = self._tick
        self._tick += 1
        if self.fault_plan is not None:
            for victim in self.fault_plan.take_replica_deaths(tick):
                self.kill_replica(victim, tick=tick)
        progress = self._drain_overflow()
        for i in self._alive_indices():
            progress = self.replicas[i].step() or progress
        if self._mixed:
            progress = self._collect_handoffs() or progress
        self._emit_gauges()
        return progress

    # ------------------------------------------------------------ handoffs
    def _requeue(self, request: Request) -> bool:
        """Scheduler ``on_requeue`` seam (mixed-roles fleets): a
        quarantined request re-routes through the router — re-probing
        every LIVE replica's cache and load at re-route time — instead
        of being pinned to the replica that faulted. False (the
        replica keeps it queued locally) only when every eligible
        queue is full."""
        try:
            self.submit(request)
        except QueueFull:
            return False
        if self.registry is not None:
            self.registry.counter_inc("serving.router.requeued")
        return True

    def _collect_handoffs(self) -> bool:
        """Collect READY hand-overs from prefill-role replicas and
        re-route each to a decode-capable replica. Ownership of the
        arena record transfers here: the exporter's cache entry is
        dropped (:meth:`PrefixCache.drop` on a swapped entry leaves
        the arena bytes alone), then the record is re-registered as a
        born-swapped prefix on the importer. A record the arena
        evicted in flight degrades to a key-less handoff — the decode
        side re-prefills cold (the verified-miss contract), the
        request never faults."""
        ready = list(self._handoff_overflow)
        self._handoff_overflow.clear()
        for i in self._alive_indices():
            if self.roles[i] != "prefill":
                continue
            src_pc = self.replicas[i].engine.prefix_cache
            for r, key, keys in self.replicas[i].take_handoffs():
                if key is not None:
                    src_pc.drop(key)
                    if not self._tier.contains(key):
                        key = None      # evicted mid-flight
                ready.append((r, key, keys))
        placed = False
        for r, key, keys in ready:
            placed = self._dispatch_handoff(r, key, keys) or placed
        return placed

    def _dispatch_handoff(self, r: Request, key: Optional[int],
                          keys) -> bool:
        """Home one hand-over on the best decode-capable replica:
        queue the request (``_handoff=True`` — the decode-role submit
        gate admits router hand-overs only), then register the arena
        record as a born-swapped prefix under the request's uid and
        note the pairing so admission resolves it (swap-in + COW share
        on the happy path, counted re-prefill on a verified miss).
        All queues full → the hand-over waits in the router's overflow
        for the next beat, record intact."""
        if key is not None and not self._tier.contains(key):
            key = None                  # evicted while waiting
        t_route = self.tracer.now() if self.tracer is not None else 0.0
        _keys, order, lens = self._route_order(r, "decode")
        for n_spilled, i in enumerate(order):
            sched = self.replicas[i]
            try:
                sched.submit(r, prefix_keys=keys,
                             count_rejection=False, _handoff=True)
            except QueueFull:
                continue
            if key is not None:
                eng = sched.engine
                cap = ((len(r.prompt) - 1) // eng.chunk_len) \
                    * eng.chunk_len
                outcome = eng.prefix_cache.register_handoff(
                    key, r.prompt[:cap], n_pages=cap // eng.page_len,
                    keys=keys)
                if outcome == "registered":
                    sched.note_handoff(r.uid, key)
                else:
                    # unreachable for an aligned >=1-block prefix;
                    # never strand arena bytes on a defensive edge
                    self._tier.discard(key)
            note_placement(self.placements, r.uid, i)
            if self.registry is not None and n_spilled:
                self.registry.counter_inc("serving.router.spills",
                                          n_spilled)
            if self.tracer is not None:
                self.tracer.event(r.uid, "route", t0=t_route,
                                  dur=self.tracer.now() - t_route,
                                  pid=i, replica=i,
                                  policy=self.route_policy,
                                  affinity_len=lens[i],
                                  spills=n_spilled, handoff=True)
            return True
        self._handoff_overflow.append((r, key, keys))
        return False

    def _drain_overflow(self) -> bool:
        """Re-route requests stranded by a replica death; those the
        fleet still cannot queue stay for the next beat (replica
        heartbeats free queue space)."""
        placed = False
        for _ in range(len(self._overflow)):
            r = self._overflow.popleft()
            try:
                self.submit(r)
                placed = True
            except QueueFull:
                self._overflow.append(r)
        return placed

    def kill_replica(self, index: int, *,
                     tick: Optional[int] = None) -> List[Request]:
        """Take replica ``index`` out of service NOW — the router-tier
        containment boundary (chaos injection calls this from
        :meth:`step`, passing the beat's ``tick`` so the log line
        matches the :class:`FaultSpec` that fired; operators may call
        it directly for a real dead
        backend). Its queued and in-flight requests drain
        (:meth:`Scheduler.drain_requests`: transient state rolled
        back, pages freed, submit clocks kept) and re-route onto the
        survivors; its worker thread stops. Killing an already-dead
        replica is a no-op; killing the LAST live replica raises —
        that is an outage, and silently absorbing it would strand
        every drained request. Returns the drained requests."""
        index = int(index)
        if not 0 <= index < len(self.replicas):
            raise ValueError(f"replica {index} out of range "
                             f"[0, {len(self.replicas)})")
        if not self.alive[index]:
            return []
        if sum(self.alive) == 1:
            raise RuntimeError(
                f"replica {index} is the last one alive — a fleet of "
                "zero cannot absorb its requests (outage, not a "
                "routing event)")
        self.alive[index] = False
        sched = self.replicas[index]
        drained = sched.drain_requests()
        sched.close()
        # drain the victim's swap worker too: swap-outs queued at kill
        # time COMPLETE their arena puts (bytes already snapshotted at
        # dispatch), so the dead replica's cross-tier audit reconciles
        # — no dangling swapped entries, no leaked host bytes
        if hasattr(sched.engine, "close"):
            sched.engine.close()
        if self.registry is not None:
            self.registry.counter_inc("serving.router.replica_deaths")
            if drained:
                self.registry.counter_inc("serving.router.requeued",
                                          len(drained))
            # retire the dead replica's load gauges NOW — _emit_gauges
            # skips dead replicas, so without this a dashboard would
            # read its last pre-death load (phantom queue depth on an
            # empty corpse) forever. Zero is the honest reading: the
            # drain emptied it, and a dead pool has no capacity.
            prefix = f"serving.router.replica{index}."
            for gauge in ("queue_depth", "slots_busy", "pages_free",
                          "host_bytes_free"):
                self.registry.gauge_set(prefix + gauge, 0.0)
        _logger.warning(
            "replica %d died at router tick %d: %d request(s) drained "
            "onto %d survivor(s)", index,
            self._tick if tick is None else tick, len(drained),
            sum(self.alive))
        self._overflow.extend(drained)
        self._drain_overflow()
        return drained

    def _emit_gauges(self) -> None:
        """Fleet + per-replica load gauges. Replica gauges are
        NAMESPACED (``serving.router.replica<i>.<gauge>``) because N
        replicas share one registry — un-namespaced pool gauges would
        be last-writer-wins noise."""
        if self.registry is None:
            return
        self.registry.gauge_set("serving.router.replicas_alive",
                                float(sum(self.alive)))
        if self._mixed:
            # the tentpole's CPU-measurable claim: the fraction of
            # decode-role heartbeats that ran NO chunk prefill. On a
            # "both" fleet long prompts steal every replica's beats;
            # here only verified-miss re-prefills and the resumed
            # final chunk may dent it
            bt = bp = 0
            for i, role in enumerate(self.roles):
                if role == "decode":
                    bt += self.replicas[i].beats_total
                    bp += self.replicas[i].beats_with_prefill
            if bt:
                self.registry.gauge_set(
                    "serving.disagg.decode_isolation", 1.0 - bp / bt)
        for i, sched in enumerate(self.replicas):
            if not self.alive[i]:
                continue
            snap = sched.load_snapshot()
            prefix = f"serving.router.replica{i}."
            self.registry.gauge_set(prefix + "queue_depth",
                                    float(snap["queue_depth"]))
            self.registry.gauge_set(prefix + "slots_busy",
                                    float(snap["slots_busy"]))
            if snap["pages_free"] is not None:
                self.registry.gauge_set(prefix + "pages_free",
                                        float(snap["pages_free"]))
            if snap["host_bytes_free"] is not None:
                # arena headroom rides the same namespace so the
                # least-loaded tie-break's input is dashboard-visible
                self.registry.gauge_set(prefix + "host_bytes_free",
                                        float(snap["host_bytes_free"]))

    # ---------------------------------------------------------------- runs
    @property
    def pending(self) -> int:
        """Requests the fleet still owes: overflow awaiting a home plus
        every live replica's queued/running/in-flight count (a drained
        dead replica reads zero by construction)."""
        return len(self._overflow) + len(self._handoff_overflow) + sum(
            s.pending for i, s in enumerate(self.replicas)
            if self.alive[i])

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100000) -> List[Request]:
        """Submit ``requests`` (stepping the fleet through
        :class:`QueueFull` backpressure rather than surfacing it) and
        step until every request reaches a terminal state. Returns the
        SUBMITTED list (in submission order — completion order
        interleaves across replicas, so compare by request, never by
        position in a completion stream) and records the fleet's
        aggregate ``serving.tokens_per_s``."""
        requests = list(requests)
        t0 = time.perf_counter()
        tok0 = sum(s.engine.tokens_generated for s in self.replicas)
        for r in requests:
            while True:
                try:
                    self.submit(r)
                    break
                except QueueFull:
                    if not self.step():
                        time.sleep(0.002)   # everything is backing off
        steps = 0
        while self.pending and steps < max_steps:
            if not self.step():
                time.sleep(0.002)
            steps += 1
        dt = time.perf_counter() - t0
        toks = sum(s.engine.tokens_generated
                   for s in self.replicas) - tok0
        if self.registry is not None and dt > 0:
            self.registry.gauge_set("serving.tokens_per_s", toks / dt)
        _logger.info(
            "router served %d request(s) over %d/%d live replica(s): "
            "%d tokens in %.3fs (%.1f tok/s)", len(requests),
            sum(self.alive), len(self.replicas), toks, dt,
            toks / dt if dt > 0 else float("inf"))
        return requests

    def close(self) -> None:
        """Stop every replica's worker threads — the scheduler's
        :class:`~apex_tpu.serving.DraftWorker` and the engine's
        :class:`~apex_tpu.serving.SwapWorker` (which drains queued
        swap-outs first, so arenas reconcile). Idempotent — safe after
        a partial kill, safe twice; each worker's own weakref
        finalizer covers the forgotten-router case."""
        if self._closed:
            return
        self._closed = True
        for sched in self.replicas:
            sched.close()
            if hasattr(sched.engine, "close"):
                sched.engine.close()
