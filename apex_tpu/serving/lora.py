"""Multi-tenant LoRA serving: a stacked adapter arena gathered in the
GEMM epilogue.

The apex surface this repo reproduces keeps auxiliary math in a
kernel's *epilogue* instead of multiplying executables — int8 dequant
(PR 14) is a per-channel scale on the accumulator, not a second weight
matrix. Multi-tenant fine-tuning gets the same treatment: a LoRA
adapter is the low-rank residual ``y += (x @ A) @ B * alpha``, and the
whole fleet of adapters lives in ONE stacked **device arena** per GEMM
site — ``A`` stacked ``[layers, rows, in, rank]``, ``B`` stacked
``[layers, rows, rank, out]`` — indexed by a **traced per-slot
adapter-index operand**. One compiled decode/chunk/verify invocation
gathers each batch row's ``[rank]`` slices (``A[ids]``/``B[ids]`` —
the Punica/S-LoRA gathered-BGMV shape), so heterogeneous adapters
decode in one batch and the adapter id is *data*, never a trace key:
ZERO new compiled programs per adapter, and the engine's program-count
pins do not move.

Arena row 0 is the **zero adapter**: all-zero A/B, ``alpha[0] == 0``.
A slot with no adapter binds row 0, its epilogue term is exactly
``+0.0`` on every element, and fp32 addition of +0.0 is
value-identical — the same pin that keeps the chaos tier's
``fault_bias`` operand honest. That is why ``adapter=None`` requests
on a LoRA-enabled engine are BITWISE the base engine.

Above the device arena sits a :class:`~apex_tpu.serving.host_tier
.HostTier`-style bounded **host store**: every registered adapter's
pristine fp32 A/B matrices at rest under one CRC32, LRU-evicted under
byte pressure — except that residency and live slot bindings
*refcount-pin* a record (an adapter a running request gathers from can
never be evicted out from under it). Swap-in (host → device row)
re-verifies the CRC; a mismatch drops the record and raises loudly —
the scheduler fails the request with a re-register hint, NEVER serves
wrong tokens. A full arena with every row pinned degrades gracefully:
:meth:`LoRAManager.acquire` returns None and the scheduler simply
holds the request in queue until a binding releases.

**Tensor parallelism** rides the PR 9 rule table unchanged. At the
column-parallel sites (qkv, mlp_in) ``x`` and ``A`` stay replicated
and ``B`` splits on its OUTPUT axis — each shard's epilogue term lands
exactly on its local slice of the base GEMM's output (the qkv arena
pre-applies the same head-group column permutation
:func:`~apex_tpu.serving.sharding._group_qkv_kernel` applies to the
base kernel, so the contiguous shard slice is the right one). At the
row-parallel sites (proj, mlp_out) ``A`` splits on its INPUT axis —
matching the shard-local activations — and ``B`` stays replicated, so
the term is a partial sum the EXISTING post-proj/post-mlp psums
restore: zero new collectives.

Telemetry (all five lint-pinned to docs/serving.md):
``serving.lora.loads`` (host→device swap-ins, CRC-verified),
``serving.lora.hits`` (acquire satisfied by an already-resident row),
``serving.lora.evictions`` (host or device rows evicted),
``serving.lora.arena_bytes`` (host-store bytes at rest, gauge),
``serving.lora.active_adapters`` (device-resident adapters, gauge).

No ``decode.*`` tuned keys are introduced here: the epilogue runs
inside the existing GEMM programs and inherits their knobs — pinned by
the tuned-keys lint.
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.log_util import get_logger

__all__ = ["LoRAConfig", "LoRAManager", "SITES", "lora_spec_tree"]

_logger = get_logger("serving")

#: The four GEMM sites an adapter may patch, in canonical (CRC) order.
#: in/out dims as multiples of the model hidden size H:
#: qkv H->3H (column-parallel), proj H->H (row-parallel),
#: mlp_in H->ratio*H (column-parallel), mlp_out ratio*H->H
#: (row-parallel).
SITES = ("qkv", "proj", "mlp_in", "mlp_out")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """Geometry of the LoRA tier: one fixed ``rank`` for every
    adapter (the arena is a dense stack — rows must agree), the number
    of device-resident ``arena_slots`` (+1 hidden zero row), and the
    bounded host store's byte capacity."""

    rank: int = 8
    arena_slots: int = 4
    host_bytes: int = 64 << 20

    def __post_init__(self):
        if int(self.rank) < 1:
            raise ValueError("rank must be >= 1")
        if int(self.arena_slots) < 1:
            raise ValueError("arena_slots must be >= 1")
        if int(self.host_bytes) < 1:
            raise ValueError("host_bytes must be >= 1")


def lora_spec_tree(axis: str):
    """The shard_map in_specs pytree for the arena operand under a 1-D
    ``axis`` mesh — the PR 9 split restated per stacked array (leading
    axes are [layers, rows, ...]):

    - ``qkv_b`` / ``mlp_in_b``: OUTPUT-axis split (column-parallel B —
      the local slice of the local base output);
    - ``proj_a`` / ``mlp_out_a``: INPUT-axis split (row-parallel A —
      matching the shard-local activations; the existing psum restores
      the sum);
    - everything else (replicated A, replicated B, ``alpha``): ``P()``.
    """
    from jax.sharding import PartitionSpec as P
    return {
        "qkv_a": P(), "qkv_b": P(None, None, None, axis),
        "proj_a": P(None, None, axis, None), "proj_b": P(),
        "mlp_in_a": P(), "mlp_in_b": P(None, None, None, axis),
        "mlp_out_a": P(None, None, axis, None), "mlp_out_b": P(),
        "alpha": P(),
    }


def _group_qkv_cols(b: np.ndarray, tp: int) -> np.ndarray:
    """Permute a stacked qkv-site B ``[layers, rank, 3*H]`` from the
    natural ``(3, heads, d)`` column layout to the head-grouped
    ``(tp, 3, heads/tp, d)`` layout — the same permutation
    :func:`~apex_tpu.serving.sharding._group_qkv_kernel` applies to
    the base qkv kernel, so a contiguous output-axis shard slice of
    the arena lines up with the shard's local qkv output. Identity at
    ``tp == 1``."""
    if tp <= 1:
        return b
    L, r, out = b.shape
    x = b.reshape(L, r, 3, tp, out // (3 * tp))
    x = np.moveaxis(x, 2, 3)                    # [L, r, tp, 3, hl*d]
    return np.ascontiguousarray(x.reshape(L, r, out))


def _adapter_crc(sites: Dict[str, Tuple[np.ndarray, np.ndarray]]) -> int:
    """One CRC32 chained over every site's A then B in canonical
    order — strong enough that a corrupt swap-in can only read as a
    loud reload, never as silently-wrong epilogue math."""
    crc = 0
    for site in SITES:
        a, b = sites[site]
        crc = zlib.crc32(np.ascontiguousarray(b),
                         zlib.crc32(np.ascontiguousarray(a), crc))
    return crc


@dataclasses.dataclass
class _AdapterRecord:
    """One registered adapter at rest in the host store."""

    name: str
    sites: Dict[str, Tuple[np.ndarray, np.ndarray]]
    alpha: float
    nbytes: int
    crc: int
    last_used: int = 0
    row: int = 0            # device arena row while resident; 0 = cold
    refcount: int = 0       # live slot bindings


class LoRAManager:
    """The LoRA tier: bounded host store + stacked device arena + the
    traced gather operand (see module docstring). Owned by the engine
    (``Engine(lora=LoRAConfig(...))``), driven by the scheduler through
    ``engine.lora_bind/lora_unbind``; single-threaded like the engine
    itself (the scheduler thread is the only caller).

    ``hidden``/``num_heads``/``num_layers``/``mlp_ratio`` fix the site
    shapes; ``tp``/``mesh`` fix the arena's device sharding (a 1-D
    ``tp`` mesh splits exactly the axes :func:`lora_spec_tree` names).
    """

    def __init__(self, config: LoRAConfig, *, hidden: int,
                 num_heads: int, num_layers: int, mlp_ratio: int = 4,
                 tp: int = 1, mesh=None, tp_axis: str = "tp",
                 registry=None):
        if not isinstance(config, LoRAConfig):
            raise TypeError(f"config must be a LoRAConfig, got "
                            f"{type(config).__name__}")
        self.config = config
        self.hidden = int(hidden)
        self.num_heads = int(num_heads)
        self.num_layers = int(num_layers)
        self.mlp_ratio = int(mlp_ratio)
        self.tp = max(int(tp), 1)
        self._mesh = mesh
        self._tp_axis = tp_axis
        self._registry = registry
        r, H = config.rank, self.hidden
        #: full (unsharded) per-layer site shapes: site -> (in, out)
        self.site_dims: Dict[str, Tuple[int, int]] = {
            "qkv": (H, 3 * H), "proj": (H, H),
            "mlp_in": (H, self.mlp_ratio * H),
            "mlp_out": (self.mlp_ratio * H, H),
        }
        self.rows = int(config.arena_slots) + 1   # +1: the zero row
        L, cap = self.num_layers, self.rows
        #: host mirror of the device arena (row 0 stays all-zero)
        self._mirror: Dict[str, np.ndarray] = {}
        for site in SITES:
            din, dout = self.site_dims[site]
            self._mirror[f"{site}_a"] = np.zeros((L, cap, din, r),
                                                 np.float32)
            self._mirror[f"{site}_b"] = np.zeros((L, cap, r, dout),
                                                 np.float32)
        self._mirror["alpha"] = np.zeros((cap,), np.float32)
        #: the traced arena operand — jnp leaves, re-placed on every
        #: hot-load (same shapes/dtypes, so never a retrace)
        self.arena = {k: self._place(k, v)
                      for k, v in self._mirror.items()}
        #: device row -> resident adapter name (index 0 unused)
        self._row_names: List[Optional[str]] = [None] * cap
        self._adapters: Dict[str, _AdapterRecord] = {}
        self._bytes_used = 0
        self._clock = itertools.count(1)
        # raw counters (mirrored into serving.lora.* when a registry
        # is attached; the class stays importable bare, HostTier-style)
        self.loads = 0
        self.hits = 0
        self.evictions = 0
        self.corruptions_detected = 0

    # ------------------------------------------------------------ device side
    def _place(self, key: str, host: np.ndarray):
        """Device-place one arena leaf — under a mesh, with the
        :func:`lora_spec_tree` sharding so the jitted programs never
        reshard it."""
        import jax
        if self._mesh is None:
            return jax.numpy.asarray(host)
        from jax.sharding import NamedSharding
        spec = lora_spec_tree(self._tp_axis)[key]
        return jax.device_put(host, NamedSharding(self._mesh, spec))

    @property
    def arena_nbytes(self) -> int:
        """Device arena bytes (all rows, zero row included)."""
        return sum(v.nbytes for v in self._mirror.values())

    def spec_tree(self):
        """shard_map in_specs for the arena operand (mesh engines)."""
        return lora_spec_tree(self._tp_axis)

    # -------------------------------------------------------------- host side
    @property
    def bytes_used(self) -> int:
        """Host-store bytes at rest (the bounded capacity's ledger —
        :meth:`audit` re-derives it from the records and raises on
        drift)."""
        return self._bytes_used

    def keys(self) -> List[str]:
        """Registered adapter names (the chaos harness's corruption
        target list — the :meth:`HostTier.keys` protocol)."""
        return list(self._adapters)

    def resident_names(self) -> List[str]:
        """Device-resident adapter names, row order — the scheduler's
        ``resident_adapters`` snapshot column (adapter affinity ranks
        replicas by membership here)."""
        return [n for n in self._row_names if n is not None]

    def contains(self, name: str) -> bool:
        return name in self._adapters

    def _site_shapes(self, site: str) -> Tuple[Tuple[int, ...],
                                               Tuple[int, ...]]:
        din, dout = self.site_dims[site]
        r = self.config.rank
        return ((self.num_layers, din, r), (self.num_layers, r, dout))

    def register(self, name: str,
                 sites: Dict[str, Tuple[np.ndarray, np.ndarray]], *,
                 alpha: float = 1.0) -> None:
        """Admit adapter ``name`` into the host store: fp32-normalise
        each site's stacked ``(A [layers, in, rank], B [layers, rank,
        out])`` pair, CRC the lot, LRU-evict unpinned records under
        byte pressure. Loud ``ValueError`` when the adapter alone
        exceeds the store or every resident byte is pinned; loud on a
        shape mismatch (the arena is a dense stack — geometry must
        agree). Re-registering a live name replaces it only when
        unpinned."""
        name = str(name)
        norm: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for site in SITES:
            if site not in sites:
                raise ValueError(f"adapter {name!r} is missing site "
                                 f"{site!r} (all of {SITES} required)")
            a, b = sites[site]
            a = np.ascontiguousarray(np.asarray(a, np.float32))
            b = np.ascontiguousarray(np.asarray(b, np.float32))
            want_a, want_b = self._site_shapes(site)
            if a.shape != want_a or b.shape != want_b:
                raise ValueError(
                    f"adapter {name!r} site {site!r} shapes "
                    f"{a.shape}/{b.shape} do not match the arena's "
                    f"{want_a}/{want_b} (rank={self.config.rank})")
            norm[site] = (a, b)
        old = self._adapters.get(name)
        if old is not None:
            if old.refcount or old.row:
                raise ValueError(
                    f"adapter {name!r} is pinned (resident or bound) "
                    "— evict its bindings before re-registering")
            self._drop(old)
        nbytes = sum(a.nbytes + b.nbytes for a, b in norm.values())
        if nbytes > self.config.host_bytes:
            raise ValueError(
                f"adapter {name!r} ({nbytes} bytes) exceeds the host "
                f"store ({self.config.host_bytes} bytes)")
        while self._bytes_used + nbytes > self.config.host_bytes:
            if not self._evict_host_lru():
                raise ValueError(
                    f"host store full registering {name!r}: every "
                    f"resident adapter is pinned by a live binding")
        self._adapters[name] = _AdapterRecord(
            name=name, sites=norm, alpha=float(alpha), nbytes=nbytes,
            crc=_adapter_crc(norm), last_used=next(self._clock))
        self._bytes_used += nbytes
        self._emit_gauges()

    def _drop(self, rec: _AdapterRecord) -> None:
        """Remove ``rec`` from the store (and its arena row name, if
        resident) — accounting only, no counters."""
        if rec.row:
            self._row_names[rec.row] = None
            rec.row = 0
        del self._adapters[rec.name]
        self._bytes_used -= rec.nbytes

    def _evict_host_lru(self) -> bool:
        """Evict the least-recently-used UNPINNED record from the host
        store (a resident-but-unbound adapter loses its row too).
        False when everything is pinned."""
        victims = [r for r in self._adapters.values()
                   if r.refcount == 0]
        if not victims:
            return False
        victim = min(victims, key=lambda r: r.last_used)
        self._drop(victim)
        self.evictions += 1
        if self._registry is not None:
            self._registry.counter_inc("serving.lora.evictions")
        _logger.debug("lora host store evicted adapter %r "
                      "(capacity pressure)", victim.name)
        self._emit_gauges()
        return True

    # ------------------------------------------------------------ swap in/out
    def acquire(self, name: str) -> Optional[int]:
        """Pin adapter ``name`` for one slot binding and return its
        arena row. Already-resident → a hit (refcount++). Cold → swap
        in: CRC-verify the host bytes (a mismatch DROPS the record and
        raises ``KeyError`` with a re-register hint — the loud-reload
        contract), claim a free row or evict the LRU unbound resident,
        write the row. Returns None — pinning nothing — when every row
        holds a bound adapter (pool-full graceful degradation: the
        caller holds the request queued)."""
        rec = self._adapters.get(str(name))
        if rec is None:
            raise KeyError(f"adapter {name!r} is not registered")
        rec.last_used = next(self._clock)
        if rec.row:
            rec.refcount += 1
            self.hits += 1
            if self._registry is not None:
                self._registry.counter_inc("serving.lora.hits")
            return rec.row
        if _adapter_crc(rec.sites) != rec.crc:
            self.corruptions_detected += 1
            self._drop(rec)
            self._emit_gauges()
            _logger.warning(
                "lora adapter %r failed its swap-in checksum — record "
                "dropped; re-register to reload", name)
            raise KeyError(
                f"adapter {name!r} failed its swap-in checksum — the "
                "record was dropped; re-register it to reload")
        row = self._claim_row()
        if row is None:
            return None
        self._write_row(row, rec)
        rec.row, rec.refcount = row, rec.refcount + 1
        self._row_names[row] = rec.name
        self.loads += 1
        if self._registry is not None:
            self._registry.counter_inc("serving.lora.loads")
        self._emit_gauges()
        return row

    def release(self, row: int) -> None:
        """Drop one slot binding on arena row ``row``. The adapter
        STAYS resident at refcount 0 (that is the cache — the next
        acquire is a hit); only a later swap-in or host eviction
        reclaims the row."""
        row = int(row)
        name = self._row_names[row] if 0 < row < self.rows else None
        if name is None:
            raise ValueError(f"arena row {row} holds no adapter")
        rec = self._adapters[name]
        if rec.refcount <= 0:
            raise ValueError(f"adapter {name!r} released below zero")
        rec.refcount -= 1

    def release_all(self) -> None:
        """Zero every binding (engine reset) — residency survives."""
        for rec in self._adapters.values():
            rec.refcount = 0

    def _claim_row(self) -> Optional[int]:
        """A free arena row, evicting the LRU resident-but-unbound
        adapter if none is free; None when every row is bound."""
        for row in range(1, self.rows):
            if self._row_names[row] is None:
                return row
        victims = [self._adapters[n] for n in self._row_names[1:]
                   if n is not None
                   and self._adapters[n].refcount == 0]
        if not victims:
            return None
        victim = min(victims, key=lambda r: r.last_used)
        row = victim.row
        self._row_names[row] = None
        victim.row = 0
        self.evictions += 1
        if self._registry is not None:
            self._registry.counter_inc("serving.lora.evictions")
        _logger.debug("lora arena evicted adapter %r from row %d",
                      victim.name, row)
        return row

    def _write_row(self, row: int, rec: _AdapterRecord) -> None:
        """Write ``rec``'s site matrices into arena row ``row`` (host
        mirror + device re-place — eager data movement, no counted
        program bodies, so the engine's program-count pins cannot
        move). The qkv B block is stored head-group-permuted so a
        contiguous tp shard slice is the correct one."""
        for site in SITES:
            a, b = rec.sites[site]
            if site == "qkv":
                b = _group_qkv_cols(b, self.tp)
            self._mirror[f"{site}_a"][:, row] = a
            self._mirror[f"{site}_b"][:, row] = b
        self._mirror["alpha"][row] = rec.alpha
        for site in SITES:
            for half in ("a", "b"):
                key = f"{site}_{half}"
                self.arena[key] = self._place(key, self._mirror[key])
        self.arena["alpha"] = self._place("alpha",
                                          self._mirror["alpha"])

    # ----------------------------------------------------------- chaos / audit
    def corrupt_entry(self, name: str, *, byte_index: int = 0) -> None:
        """CHAOS/DEBUG ONLY: flip one byte of the stored first-site A
        block so the next cold :meth:`acquire` fails its checksum —
        the ``swap_corruption`` injection primitive for adapter
        records (the :meth:`HostTier.corrupt_entry` protocol). Raises
        KeyError when absent."""
        rec = self._adapters[str(name)]
        flat = rec.sites[SITES[0]][0].reshape(-1).view(np.uint8)
        flat[int(byte_index) % flat.size] ^= 0xFF

    def audit(self, bound_rows: Optional[Dict[int, int]] = None) -> dict:
        """The arena's refcount audit: re-derive the host-store byte
        ledger from the records, cross-check row<->record residency
        both ways, and — when the engine passes its live slot bindings
        as ``bound_rows`` (row -> binding count) — demand the
        refcounts match exactly. Raises ``RuntimeError`` on any drift;
        returns the reconciled stats dict."""
        derived = sum(r.nbytes for r in self._adapters.values())
        if derived != self._bytes_used:
            raise RuntimeError(
                f"lora host-store byte ledger drifted: derived "
                f"{derived}, ledger {self._bytes_used}")
        for row, name in enumerate(self._row_names):
            if name is None:
                continue
            rec = self._adapters.get(name)
            if rec is None or rec.row != row:
                raise RuntimeError(
                    f"lora arena row {row} names {name!r} but the "
                    "record disagrees")
        for rec in self._adapters.values():
            if rec.row and self._row_names[rec.row] != rec.name:
                raise RuntimeError(
                    f"adapter {rec.name!r} claims row {rec.row} but "
                    "the row disagrees")
            if rec.refcount and not rec.row:
                raise RuntimeError(
                    f"adapter {rec.name!r} has {rec.refcount} "
                    "bindings but no arena row")
        if bound_rows is not None:
            for rec in self._adapters.values():
                want = int(bound_rows.get(rec.row, 0)) if rec.row \
                    else 0
                if rec.refcount != want:
                    raise RuntimeError(
                        f"adapter {rec.name!r} refcount "
                        f"{rec.refcount} != {want} live slot "
                        "bindings")
            extra = set(bound_rows) - {r.row for r in
                                       self._adapters.values() if r.row}
            if extra:
                raise RuntimeError(
                    f"slots bound to arena rows {sorted(extra)} that "
                    "hold no adapter")
        return self.stats()

    def stats(self) -> dict:
        return {
            "adapters": len(self._adapters),
            "resident": len(self.resident_names()),
            "bytes_used": self._bytes_used,
            "host_bytes": self.config.host_bytes,
            "arena_nbytes": self.arena_nbytes,
            "loads": self.loads,
            "hits": self.hits,
            "evictions": self.evictions,
            "corruptions_detected": self.corruptions_detected,
        }

    def _emit_gauges(self) -> None:
        if self._registry is None:
            return
        self._registry.gauge_set("serving.lora.arena_bytes",
                                 float(self._bytes_used))
        self._registry.gauge_set("serving.lora.active_adapters",
                                 float(len(self.resident_names())))

    def set_registry(self, registry) -> None:
        """(Re)attach a metrics registry (the engine's
        ``set_registry`` pass-through) and refresh the gauges."""
        self._registry = registry
        self._emit_gauges()
