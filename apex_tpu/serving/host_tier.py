"""Host-DRAM second tier for the paged KV pool: the swap arena.

Device HBM caps the prefix cache at a few dozen retained prompts;
host RAM is ~100x larger and a prefix page is pure *content* — written
once at registration, shared copy-on-write ever after, never mutated.
That makes cold prefix pages the perfect spill candidate: only bytes
need to move, because the hashing, token verification and refcount
machinery already live host-side (:mod:`~apex_tpu.serving
.prefix_cache`).

:class:`HostTier` is that spill target — a **bounded numpy arena** of
swapped-out prefix page blocks, keyed by the owning prefix-cache
entry's synthetic key:

- **put** (swap-out): the engine copies an evicted entry's page bytes
  device→host (``[layers, m, heads, page_len, head_dim]`` K and V, in
  the pool's storage dtype — int8 under the ``kv_quant`` tier, which
  halves the transfer bytes for free) and the arena stores them with a
  CRC32 checksum. Capacity is enforced at insert: least-recently-put
  entries are evicted (the ``on_evict`` hook tells the owner to drop
  the now-backingless index entry), and an entry larger than the whole
  arena is *declined* — the caller falls back to plain destruction.
- **take** (swap-in): pops the entry and re-verifies the checksum.
  A mismatch (bit rot, or the chaos harness's ``swap_corruption``
  injection) returns ``valid=False`` — the engine degrades the hit to
  a **verified miss** (drop + re-prefill), never a wrong token. The
  checksum guards the *bytes*; the prefix cache's token-for-token
  verification continues to guard the *identity*, so the two layers
  together keep the hierarchical cache exact.
- **contains** is the read-only existence probe the prefix cache's
  match/probe walk uses (no LRU touch, no counters — the router's
  affinity probe rides it N times per request).

Everything here is pure host numpy/python: no device work, no compiled
programs, no jax import. The engine owns all telemetry
(``serving.swap.*``) and all device-side data movement; the arena owns
bytes, bounds and checksums.
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.log_util import get_logger

__all__ = ["HostTier", "HostTierRecord"]

_logger = get_logger("serving")


def _checksum(k: np.ndarray, v: np.ndarray) -> int:
    """CRC32 over the K then V bytes — the swap-in exactness guard.
    Cheap (~GB/s, stdlib C) relative to the device→host copy it
    protects, and strong enough that a corrupt swap-in can only read
    as a verified miss, never as silently-wrong K/V."""
    return zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))


@dataclasses.dataclass
class HostTierRecord:
    """One swapped-out prefix: the page-block K/V bytes (numpy, in the
    pool's storage dtype), their byte count, the CRC32 computed at
    swap-out, and the validity verdict :meth:`HostTier.take` fills in
    when it re-verifies the checksum at swap-in."""

    k: np.ndarray           # [layers, m, heads, page_len, head_dim]
    v: np.ndarray
    nbytes: int
    crc: int
    last_used: int = 0
    valid: bool = True


class HostTier:
    """Bounded host-DRAM arena for swapped-out prefix pages (see
    module docstring). ``capacity_bytes`` bounds the K+V bytes held;
    ``on_evict(key)`` fires AFTER a capacity eviction removes an entry
    (the engine wires it to drop the matching swapped prefix-cache
    entry, so a prefix is never indexed without backing bytes)."""

    def __init__(self, capacity_bytes: int, *,
                 on_evict: Optional[Callable[[int], None]] = None):
        capacity_bytes = int(capacity_bytes)
        if capacity_bytes < 1:
            raise ValueError("capacity_bytes must be >= 1")
        self.capacity_bytes = capacity_bytes
        self.on_evict = on_evict
        self._entries: Dict[int, HostTierRecord] = {}
        self._bytes_used = 0        # maintained incrementally: the
        # auditor re-derives the sum from the stored arrays and raises
        # on drift, so the two must be independent quantities
        self._clock = itertools.count(1)
        # raw counters (the engine mirrors the interesting ones into
        # serving.swap.*; these keep the class importable bare)
        self.puts = 0
        self.takes = 0
        self.evictions = 0
        self.declined = 0
        self.corruptions_detected = 0

    # ------------------------------------------------------------- geometry
    @property
    def bytes_used(self) -> int:
        """K+V bytes currently held (incremental accounting; the
        :class:`~apex_tpu.serving.PoolAuditor` re-derives it from the
        stored arrays and raises on drift)."""
        return self._bytes_used

    @property
    def size(self) -> int:
        return len(self._entries)

    def keys(self) -> List[int]:
        """The resident entry keys (the auditor's reconciliation view
        against :meth:`PrefixCache.swapped_keys`)."""
        return list(self._entries)

    def contains(self, key: int) -> bool:
        """Read-only existence probe — touches NOTHING (no LRU
        refresh, no counters): the prefix cache's match AND probe
        walks both ride it, and probe must stay side-effect-free."""
        return int(key) in self._entries

    def nbytes_of(self, key: int) -> int:
        """Stored K+V bytes of one entry (0 when absent) — the
        auditor's per-entry accounting probe."""
        rec = self._entries.get(int(key))
        return 0 if rec is None else rec.nbytes

    @staticmethod
    def _own(arr: np.ndarray) -> np.ndarray:
        """A contiguous, writable, arena-owned copy of ``arr`` when it
        is not one already (``np.asarray`` of a device buffer hands
        back a READ-ONLY view — the arena must own mutable bytes so
        checksums, capacity accounting and the chaos harness's
        ``corrupt_entry`` all operate on its own storage)."""
        arr = np.asarray(arr)
        if arr.flags.owndata and arr.flags.writeable \
                and arr.flags.c_contiguous:
            return arr
        return np.array(arr, copy=True)

    # ------------------------------------------------------------ transfers
    def put(self, key: int, k_pages: np.ndarray,
            v_pages: np.ndarray) -> bool:
        """Store one swapped-out prefix's page bytes under ``key``.
        Returns False — and stores nothing — when the entry alone
        exceeds the arena (the caller destroys instead, exactly the
        pre-tier behaviour); otherwise evicts least-recently-put
        entries until the entry fits, firing ``on_evict`` per victim.
        The arrays are defensively copied (``np.asarray`` of a device
        buffer already owns its bytes, but a caller-held view must not
        alias the arena) and checksummed at rest."""
        key = int(key)
        k_pages = self._own(k_pages)
        v_pages = self._own(v_pages)
        nbytes = int(k_pages.nbytes + v_pages.nbytes)
        if nbytes > self.capacity_bytes:
            self.declined += 1
            _logger.debug("host tier declined %d-byte entry (capacity "
                          "%d)", nbytes, self.capacity_bytes)
            return False
        old = self._entries.pop(key, None)      # replace, never double-count
        if old is not None:
            self._bytes_used -= old.nbytes
        while self._bytes_used + nbytes > self.capacity_bytes:
            self._evict_lru()
        self._entries[key] = HostTierRecord(
            k=k_pages, v=v_pages, nbytes=nbytes,
            crc=_checksum(k_pages, v_pages), last_used=next(self._clock))
        self._bytes_used += nbytes
        self.puts += 1
        if old is not None:
            _logger.debug("host tier replaced entry %d", key)
        return True

    def take(self, key: int) -> Optional[HostTierRecord]:
        """POP the entry for ``key`` and re-verify its checksum:
        ``record.valid`` is False when the stored bytes no longer
        match the swap-out CRC (corruption — the engine must degrade
        the hit to a verified miss). None when the key is absent
        (e.g. evicted by capacity pressure since the match walk)."""
        rec = self._entries.pop(int(key), None)
        if rec is None:
            return None
        self._bytes_used -= rec.nbytes
        self.takes += 1
        rec.valid = _checksum(rec.k, rec.v) == rec.crc
        if not rec.valid:
            self.corruptions_detected += 1
            _logger.warning("host tier entry %d failed its swap-in "
                            "checksum — degrading to a verified miss",
                            key)
        return rec

    def _evict_lru(self) -> None:
        key, rec = min(self._entries.items(),
                       key=lambda kv: kv[1].last_used)
        del self._entries[key]
        self._bytes_used -= rec.nbytes
        self.evictions += 1
        _logger.debug("host tier evicted entry %d (capacity pressure)",
                      key)
        if self.on_evict is not None:
            self.on_evict(key)

    # ------------------------------------------------------------ lifecycle
    def corrupt_entry(self, key: int, *, byte_index: int = 0) -> None:
        """CHAOS/DEBUG ONLY: flip one byte of the stored K block so the
        next :meth:`take` fails its checksum — the
        ``swap_corruption`` fault kind's injection primitive (proving
        the verified-miss degradation, exactly as
        ``corrupt_page_table`` proves the auditor's sensitivity).
        Raises KeyError when the key is absent."""
        rec = self._entries[int(key)]
        flat = rec.k.reshape(-1).view(np.uint8)
        flat[int(byte_index) % flat.size] ^= 0xFF

    def clear(self) -> None:
        """Drop every entry (counters survive — run-scoped, like the
        prefix cache's). No ``on_evict`` callbacks: clear is the
        engine-driven teardown half of ``reset(clear_prefixes=True)``,
        where the index entries are being dropped anyway."""
        self._entries.clear()
        self._bytes_used = 0

    def stats(self) -> dict:
        """Host-side snapshot (the bench's host-tier honesty row)."""
        return {
            "entries": self.size,
            "bytes_used": self.bytes_used,
            "capacity_bytes": self.capacity_bytes,
            "puts": self.puts,
            "takes": self.takes,
            "evictions": self.evictions,
            "declined": self.declined,
            "corruptions_detected": self.corruptions_detected,
        }
