"""Host-DRAM second tier for the paged KV pool: the swap arena.

Device HBM caps the prefix cache at a few dozen retained prompts;
host RAM is ~100x larger and a prefix page is pure *content* — written
once at registration, shared copy-on-write ever after, never mutated.
That makes cold prefix pages the perfect spill candidate: only bytes
need to move, because the hashing, token verification and refcount
machinery already live host-side (:mod:`~apex_tpu.serving
.prefix_cache`).

:class:`HostTier` is that spill target — a **bounded numpy arena** of
swapped-out prefix page blocks, keyed by the owning prefix-cache
entry's synthetic key:

- **put** (swap-out): the engine copies an evicted entry's page bytes
  device→host (``[layers, m, heads, page_len, head_dim]`` K and V, in
  the pool's storage dtype — int8 under the ``kv_quant`` tier, which
  halves the transfer bytes for free) and the arena stores them with a
  per-shard CRC32 checksum (one CRC per tensor-parallel shard of the
  heads axis; ``shards=1`` on a single-chip engine degenerates to the
  one whole-array CRC). Capacity is enforced at insert:
  least-recently-put entries are evicted (the ``on_evict`` hook tells
  the owner to drop the now-backingless index entry), and an entry
  larger than the whole arena is *declined* — the caller falls back to
  plain destruction.
- **put_pending / complete** (async swap-out): the admission-path half
  of an asynchronous swap RESERVES the entry's bytes synchronously
  (:meth:`put_pending` — capacity eviction and the LRU stamp happen
  NOW, on the caller's thread, so async and sync arena states evolve
  identically), and the :class:`SwapWorker` thread fills the bytes in
  later (:meth:`complete` — the forced device read, the defensive
  copy, the CRC). A pending record is the *swapping* state: it counts
  toward capacity, answers :meth:`contains` (the entry stays
  matchable mid-flight), and a capacity eviction can drop it (the
  worker's late ``complete`` then discards silently — the index entry
  was already dropped through ``on_evict``).
- **take** (swap-in): pops the entry and re-verifies every shard's
  checksum. A mismatch (bit rot, or the chaos harness's
  ``swap_corruption`` injection) returns ``valid=False`` — the engine
  degrades the hit to a **verified miss** (drop + re-prefill), never a
  wrong token. A still-pending record (the worker job died before
  completing) returns None, the same degradation. The checksum guards
  the *bytes*; the prefix cache's token-for-token verification
  continues to guard the *identity*, so the two layers together keep
  the hierarchical cache exact.
- **contains** is the read-only existence probe the prefix cache's
  match/probe walk uses (no LRU touch, no counters — the router's
  affinity probe rides it N times per request).

The arena is **thread-safe** (one re-entrant lock around every public
method): the :class:`SwapWorker` completes records from its own thread
while the scheduler thread matches, takes and audits. Structural
mutations that fire ``on_evict`` (put/put_pending capacity evictions)
only ever run on the caller's thread — :meth:`complete` fills bytes
into an existing record and never calls out — so the prefix-cache
index is only ever mutated from the scheduler thread.

Everything here is pure host numpy/python: no device work, no compiled
programs, no jax import. The engine owns all telemetry
(``serving.swap.*``) and all device-side data movement; the arena owns
bytes, bounds and checksums.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.log_util import get_logger

__all__ = ["HostTier", "HostTierRecord", "SwapWorker",
           "record_from_wire", "record_to_wire"]

_logger = get_logger("serving")


def _shard_checksums(k: np.ndarray, v: np.ndarray,
                     shards: int) -> Tuple[int, ...]:
    """Per-shard CRC32s over the HEADS axis (axis 2 of
    ``[layers, m, heads, page_len, head_dim]``): shard ``t`` covers
    heads ``[t*h/tp, (t+1)*h/tp)`` of K then V — exactly the slice a
    tensor-parallel shard owns, so a mesh engine's arena records carry
    one verifiable checksum per shard. ``shards=1`` is the classic
    whole-array CRC (same value bit-for-bit). Cheap (~GB/s, stdlib C)
    relative to the device→host copy it protects, and strong enough
    that a corrupt swap-in can only read as a verified miss, never as
    silently-wrong K/V. ``shards`` must divide the heads axis —
    otherwise the trailing heads would sit in NO shard's CRC and a
    bit flip there would verify clean, exactly the silent wrongness
    the checksum exists to forbid (the engine's tp geometry
    validation guarantees this; direct callers are checked loudly
    here)."""
    shards = max(int(shards), 1)
    heads = k.shape[2]
    if heads % shards:
        raise ValueError(
            f"shards={shards} must divide the heads axis ({heads}): a "
            "ragged split would leave the trailing heads outside every "
            "shard's checksum")
    hl = heads // shards
    out = []
    for t in range(shards):
        # crc32 reads the contiguous buffers directly — no tobytes
        # copy, and at shards=1 over the (already-contiguous) stored
        # arrays ascontiguousarray is a no-op view too, so the
        # single-chip checksum path is copy-free
        ks = np.ascontiguousarray(k[:, :, t * hl:(t + 1) * hl])
        vs = np.ascontiguousarray(v[:, :, t * hl:(t + 1) * hl])
        out.append(zlib.crc32(vs, zlib.crc32(ks)))
    return tuple(out)


@dataclasses.dataclass
class HostTierRecord:
    """One swapped-out prefix: the page-block K/V bytes (numpy, in the
    pool's storage dtype — None while the record is *pending*, i.e.
    the swap-out bytes are still in flight on the
    :class:`SwapWorker`), their byte count, the per-shard CRC32s
    computed at swap-out (``shards`` entries — one per tensor-parallel
    shard of the heads axis), and the validity verdict
    :meth:`HostTier.take` fills in when it re-verifies the checksums
    at swap-in."""

    k: Optional[np.ndarray]  # [layers, m, heads, page_len, head_dim]
    v: Optional[np.ndarray]
    nbytes: int
    crc: Tuple[int, ...]
    shards: int = 1
    last_used: int = 0
    valid: bool = True
    pending: bool = False
    # chaos racing an in-flight swap: corrupt_entry on a pending
    # record arms this flag; complete() flips a stored byte AFTER
    # computing the CRCs, so the next take fails verification exactly
    # as a post-completion corruption would
    corrupt_on_complete: bool = False


# --------------------------------------------------------------- wire forms
#
# The disaggregated handoff's arena record, addressable ACROSS
# processes: a prefill-role fleet worker exports the finished prefix's
# record as a versioned dict (raw bytes + dtype/shape + the swap-out
# CRCs), the controller ships it over the fleet transport, and the
# decode-role worker imports it into its OWN arena. The CRCs travel
# with the bytes and are re-verified by the importing side's ordinary
# :meth:`HostTier.take` at swap-in — so corruption anywhere along the
# journey degrades to the same VERIFIED MISS a local corruption would,
# never a wrong token. Versioned like the scheduler wire forms: a
# mismatched build fails loudly, never deserializes garbage.

RECORD_WIRE_VERSION = 1


def record_to_wire(key: int, record: HostTierRecord) -> dict:
    """``record`` (resident — a pending record has no bytes to ship)
    as its versioned dict wire form under arena key ``key``."""
    if record.pending or record.k is None or record.v is None:
        raise ValueError(
            f"arena record {key} is still pending — an in-flight "
            "swap-out has no bytes to put on the wire")
    return {
        "v": RECORD_WIRE_VERSION,
        "key": int(key),
        "nbytes": int(record.nbytes),
        "crc": [int(c) for c in record.crc],
        "shards": int(record.shards),
        "k_bytes": record.k.tobytes(),
        "k_dtype": str(record.k.dtype),
        "k_shape": [int(d) for d in record.k.shape],
        "v_bytes": record.v.tobytes(),
        "v_dtype": str(record.v.dtype),
        "v_shape": [int(d) for d in record.v.shape],
    }


def record_from_wire(wire: dict) -> Tuple[int, HostTierRecord]:
    """``(key, record)`` from a record wire form — the arrays rebuilt
    as owned, writable host copies (the arena must own mutable bytes).
    Loud ``ValueError`` on an unknown version, ``KeyError`` on a
    missing field."""
    v = wire.get("v")
    if v != RECORD_WIRE_VERSION:
        raise ValueError(
            f"unknown arena-record wire version {v!r} (this build "
            f"speaks {RECORD_WIRE_VERSION}) — controller and workers "
            "must run the same tree")
    k = np.frombuffer(wire["k_bytes"], dtype=wire["k_dtype"]) \
        .reshape(wire["k_shape"]).copy()
    vv = np.frombuffer(wire["v_bytes"], dtype=wire["v_dtype"]) \
        .reshape(wire["v_shape"]).copy()
    return int(wire["key"]), HostTierRecord(
        k=k, v=vv, nbytes=int(wire["nbytes"]),
        crc=tuple(int(c) for c in wire["crc"]),
        shards=int(wire["shards"]))


class HostTier:
    """Bounded host-DRAM arena for swapped-out prefix pages (see
    module docstring). ``capacity_bytes`` bounds the K+V bytes held
    (pending reservations included); ``on_evict(key)`` fires AFTER a
    capacity eviction removes an entry (the engine wires it to drop
    the matching swapped prefix-cache entry, so a prefix is never
    indexed without backing bytes).

    ``shared=True`` marks the arena as EXTERNALLY OWNED by several
    engines at once (the disaggregated-serving handoff bus): each
    engine then registers its drop-hook through :meth:`add_on_evict`
    instead of overwriting ``on_evict``, a capacity eviction notifies
    every registered engine (each drops the key from its OWN prefix
    index — :meth:`PrefixCache.drop` is a no-op for keys it never
    held), and the engines scope their cross-tier audits to the keys
    they own (an arena record owned by a sibling engine is not an
    orphan). A shared arena also survives any single engine's
    ``reset()`` — teardown belongs to whoever built it."""

    def __init__(self, capacity_bytes: int, *,
                 on_evict: Optional[Callable[[int], None]] = None,
                 shared: bool = False):
        capacity_bytes = int(capacity_bytes)
        if capacity_bytes < 1:
            raise ValueError("capacity_bytes must be >= 1")
        self.capacity_bytes = capacity_bytes
        self.on_evict = on_evict
        self.shared = bool(shared)
        # extra eviction listeners (shared-arena mode: one per engine);
        # fired after on_evict, caller's thread only, like on_evict
        self._evict_listeners: List[Callable[[int], None]] = []
        self._lock = threading.RLock()
        self._entries: Dict[int, HostTierRecord] = {}
        self._bytes_used = 0        # maintained incrementally: the
        # auditor re-derives the sum from the stored records and raises
        # on drift, so the two must be independent quantities
        self._clock = itertools.count(1)
        # raw counters (the engine mirrors the interesting ones into
        # serving.swap.*; these keep the class importable bare)
        self.puts = 0
        self.takes = 0
        self.evictions = 0
        self.declined = 0
        self.corruptions_detected = 0

    # ------------------------------------------------------------- geometry
    @property
    def bytes_used(self) -> int:
        """K+V bytes currently held or reserved by pending swaps
        (incremental accounting; the :class:`~apex_tpu.serving
        .PoolAuditor` re-derives it from the stored records and raises
        on drift)."""
        return self._bytes_used

    @property
    def size(self) -> int:
        return len(self._entries)

    def keys(self) -> List[int]:
        """The resident AND pending entry keys (the auditor's
        reconciliation view against :meth:`PrefixCache.swapped_keys` —
        a mid-flight swap is already swapped state on both sides)."""
        with self._lock:
            return list(self._entries)

    def pending_keys(self) -> List[int]:
        """Keys whose swap-out bytes are still in flight (the
        *swapping* state — reserved, matchable, not yet verifiable)."""
        with self._lock:
            return [k for k, r in self._entries.items() if r.pending]

    def contains(self, key: int) -> bool:
        """Read-only existence probe — touches NOTHING (no LRU
        refresh, no counters): the prefix cache's match AND probe
        walks both ride it, and probe must stay side-effect-free.
        Pending (in-flight) entries count: a hit on one joins the
        copy at swap-in time instead of missing."""
        with self._lock:
            return int(key) in self._entries

    def nbytes_of(self, key: int) -> int:
        """Stored (or pending-reserved) K+V bytes of one entry (0 when
        absent) — the auditor's per-entry accounting probe."""
        with self._lock:
            rec = self._entries.get(int(key))
            return 0 if rec is None else rec.nbytes

    @staticmethod
    def _own(arr: np.ndarray) -> np.ndarray:
        """A contiguous, writable, arena-owned copy of ``arr`` when it
        is not one already (``np.asarray`` of a device buffer hands
        back a READ-ONLY view — the arena must own mutable bytes so
        checksums, capacity accounting and the chaos harness's
        ``corrupt_entry`` all operate on its own storage)."""
        arr = np.asarray(arr)
        if arr.flags.owndata and arr.flags.writeable \
                and arr.flags.c_contiguous:
            return arr
        return np.array(arr, copy=True)

    # ------------------------------------------------------------ transfers
    def put_pending(self, key: int, nbytes: int, *,
                    shards: int = 1) -> bool:
        """Reserve arena space for an in-flight swap-out of ``key``
        (the asynchronous path's admission-side half — capacity
        eviction, the decline decision and the LRU stamp all happen
        HERE, on the caller's thread, so async and sync arenas evolve
        identically). Returns False — and reserves nothing — when
        ``nbytes`` alone exceeds the arena (the caller destroys
        instead, exactly the pre-tier behaviour). The
        :class:`SwapWorker` fills the bytes in via :meth:`complete`."""
        key, nbytes = int(key), int(nbytes)
        with self._lock:
            if nbytes > self.capacity_bytes:
                self.declined += 1
                _logger.debug("host tier declined %d-byte entry "
                              "(capacity %d)", nbytes,
                              self.capacity_bytes)
                return False
            old = self._entries.pop(key, None)  # replace, never double-count
            if old is not None:
                self._bytes_used -= old.nbytes
            while self._bytes_used + nbytes > self.capacity_bytes:
                self._evict_lru()
            self._entries[key] = HostTierRecord(
                k=None, v=None, nbytes=nbytes, crc=(),
                shards=max(int(shards), 1),
                last_used=next(self._clock), pending=True)
            self._bytes_used += nbytes
            if old is not None:
                _logger.debug("host tier replaced entry %d", key)
            return True

    def complete(self, key: int, k_pages: np.ndarray,
                 v_pages: np.ndarray) -> bool:
        """Fill a pending record's bytes in (the :class:`SwapWorker`'s
        half of an async swap-out): defensively copy, checksum per
        shard, flip pending→resident. False — and nothing stored —
        when the record was evicted (or the arena cleared) while the
        bytes were in flight: the index entry is already gone, so the
        late bytes are simply discarded. Never evicts and never fires
        ``on_evict`` — structural mutations stay on the scheduler
        thread. The heavy half (defensive copy + CRC) runs OUTSIDE
        the arena lock: an admission-path ``put_pending`` must never
        wait out a worker mid-checksum — that wait would be exactly
        the stall the async tier removes, smuggled back in through
        lock contention."""
        key = int(key)
        with self._lock:
            rec = self._entries.get(key)
            if rec is None or not rec.pending:
                return False
            shards = rec.shards
        k_pages = self._own(k_pages)        # heavy: outside the lock
        v_pages = self._own(v_pages)
        crc = _shard_checksums(k_pages, v_pages, shards)
        with self._lock:
            rec = self._entries.get(key)
            if rec is None or not rec.pending:
                return False        # evicted while we were checksumming
            actual = int(k_pages.nbytes + v_pages.nbytes)
            if actual != rec.nbytes:
                # the reservation was computed from shapes; drift means
                # the caller's arithmetic was wrong — keep the ledger
                # honest rather than letting the auditor trip later
                self._bytes_used += actual - rec.nbytes
                rec.nbytes = actual
            rec.k, rec.v = k_pages, v_pages
            rec.crc = crc
            rec.pending = False
            if rec.corrupt_on_complete:
                # chaos raced this in-flight swap: rot the stored
                # bytes AFTER the CRC so the next take fails exactly
                # like post-completion corruption
                rec.corrupt_on_complete = False
                flat = rec.k.reshape(-1).view(np.uint8)
                flat[0] ^= 0xFF
            self.puts += 1
            return True

    def put(self, key: int, k_pages: np.ndarray, v_pages: np.ndarray,
            *, shards: int = 1) -> bool:
        """Store one swapped-out prefix's page bytes under ``key`` in
        one synchronous step (reserve + complete — the sync escape
        hatch and the swap-in deferral path). Returns False — and
        stores nothing — when the entry alone exceeds the arena;
        otherwise evicts least-recently-put entries until it fits,
        firing ``on_evict`` per victim. The arrays are defensively
        copied (once, in :meth:`complete` — views only; arrays the
        caller already owns are adopted, the pre-async contract) and
        checksummed per shard at rest. No outer lock: the caller is
        the scheduler thread and the worker only ever completes its
        OWN keys, so nothing can race the fresh pending record —
        which keeps complete's copy+CRC off the arena lock here
        too."""
        nbytes = int(np.asarray(k_pages).nbytes
                     + np.asarray(v_pages).nbytes)
        if not self.put_pending(key, nbytes, shards=shards):
            return False
        return self.complete(key, k_pages, v_pages)

    def take(self, key: int) -> Optional[HostTierRecord]:
        """POP the entry for ``key`` and re-verify its per-shard
        checksums: ``record.valid`` is False when any shard's stored
        bytes no longer match the swap-out CRC (corruption — the
        engine must degrade the hit to a verified miss). None when the
        key is absent (e.g. evicted by capacity pressure since the
        match walk) or still pending (the worker job died before
        completing — same degradation; the engine joins the worker
        before taking, so a healthy in-flight swap is never consumed
        half-done)."""
        with self._lock:
            rec = self._entries.pop(int(key), None)
            if rec is None:
                return None
            self._bytes_used -= rec.nbytes
            if rec.pending:
                _logger.warning("host tier entry %d taken while still "
                                "pending (its swap-out never completed)"
                                " — degrading to a verified miss", key)
                return None
            self.takes += 1
            rec.valid = _shard_checksums(rec.k, rec.v,
                                         rec.shards) == rec.crc
            if not rec.valid:
                self.corruptions_detected += 1
                _logger.warning("host tier entry %d failed its swap-in "
                                "checksum — degrading to a verified "
                                "miss", key)
            return rec

    def export_record(self, key: int) -> Optional[dict]:
        """POP ``key``'s resident record and return its wire form —
        the cross-process half of a disaggregated handoff (ownership
        transfers to the wire: the exporting arena releases the bytes
        NOW, the importing arena adopts them). None when the key is
        absent (evicted since the handoff was collected) or still
        pending (bytes in flight) — both degrade to the key-less
        handoff, i.e. a decode-side re-prefill, per the verified-miss
        contract. No checksum walk here: the swap-out CRCs travel and
        the importer's :meth:`take` re-verifies at swap-in."""
        with self._lock:
            rec = self._entries.get(int(key))
            if rec is None or rec.pending:
                return None
            wire = record_to_wire(int(key), rec)
            del self._entries[int(key)]
            self._bytes_used -= rec.nbytes
            return wire

    def import_record(self, wire: dict) -> Optional[int]:
        """Adopt a wire-form record into THIS arena under its
        original key (handoff keys are request uids — positive, so
        they can never collide with a local engine's negative
        synthetic prefix keys). Same admission rules as a local put:
        an over-capacity record is declined (returns None — the
        caller degrades to a key-less handoff), otherwise LRU
        eviction makes room and the key is returned. Counted as a
        ``put`` — the record enters the arena exactly as a completed
        swap-out would."""
        key, rec = record_from_wire(wire)
        with self._lock:
            if rec.nbytes > self.capacity_bytes:
                self.declined += 1
                _logger.debug(
                    "host tier declined imported %d-byte record %d "
                    "(capacity %d)", rec.nbytes, key,
                    self.capacity_bytes)
                return None
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes_used -= old.nbytes
            while self._bytes_used + rec.nbytes > self.capacity_bytes:
                self._evict_lru()
            rec.last_used = next(self._clock)
            self._entries[key] = rec
            self._bytes_used += rec.nbytes
            self.puts += 1
            return key

    def add_on_evict(self, fn: Callable[[int], None]) -> None:
        """Register an ADDITIONAL eviction listener (shared-arena
        mode: every co-owning engine hooks its prefix-index drop here
        — overwriting ``on_evict`` would silently orphan the other
        engines' swapped entries). Listeners fire on the caller's
        thread, after ``on_evict``, once per evicted key; double
        registration is collapsed."""
        if fn not in self._evict_listeners:
            self._evict_listeners.append(fn)

    def _evict_lru(self) -> None:
        key, rec = min(self._entries.items(),
                       key=lambda kv: kv[1].last_used)
        del self._entries[key]
        self._bytes_used -= rec.nbytes
        self.evictions += 1
        _logger.debug("host tier evicted entry %d (capacity pressure)",
                      key)
        if self.on_evict is not None:
            self.on_evict(key)
        for fn in self._evict_listeners:
            fn(key)

    # ------------------------------------------------------------ lifecycle
    def corrupt_entry(self, key: int, *, byte_index: int = 0) -> None:
        """CHAOS/DEBUG ONLY: flip one byte of the stored K block so the
        next :meth:`take` fails its checksum — the
        ``swap_corruption`` fault kind's injection primitive (proving
        the verified-miss degradation, exactly as
        ``corrupt_page_table`` proves the auditor's sensitivity). On a
        PENDING record (the injection racing an in-flight swap) the
        corruption is armed instead and lands the moment
        :meth:`complete` stores the bytes — the race resolves to the
        same verified miss either way. Raises KeyError when the key is
        absent."""
        with self._lock:
            rec = self._entries[int(key)]
            if rec.pending:
                rec.corrupt_on_complete = True
                return
            flat = rec.k.reshape(-1).view(np.uint8)
            flat[int(byte_index) % flat.size] ^= 0xFF

    def discard(self, key: int) -> bool:
        """Drop ``key``'s record WITHOUT verifying or returning it (no
        ``on_evict``, no counters): the shared-arena reset path — an
        engine tearing down its own swapped entries must release their
        reserved bytes without the checksum walk :meth:`take` pays,
        and without clearing sibling engines' records the way
        :meth:`clear` would. False when absent."""
        with self._lock:
            rec = self._entries.pop(int(key), None)
            if rec is None:
                return False
            self._bytes_used -= rec.nbytes
            return True

    def clear(self) -> None:
        """Drop every entry — pending ones included; a worker's late
        ``complete`` finds its record gone and discards (counters
        survive — run-scoped, like the prefix cache's). No ``on_evict``
        callbacks: clear is the engine-driven teardown half of
        ``reset(clear_prefixes=True)``, where the index entries are
        being dropped anyway."""
        with self._lock:
            self._entries.clear()
            self._bytes_used = 0

    def stats(self) -> dict:
        """Host-side snapshot (the bench's host-tier honesty row).
        ``swapping`` counts records whose bytes are still in flight on
        the :class:`SwapWorker`."""
        with self._lock:
            return {
                "entries": self.size,
                "swapping": sum(r.pending
                                for r in self._entries.values()),
                "bytes_used": self.bytes_used,
                "capacity_bytes": self.capacity_bytes,
                "puts": self.puts,
                "takes": self.takes,
                "evictions": self.evictions,
                "declined": self.declined,
                "corruptions_detected": self.corruptions_detected,
            }


class SwapWorker:
    """One background thread that completes swap-outs off the
    admission path (the :class:`~apex_tpu.serving.DraftWorker`
    pattern: daemon thread, bounded queue, jobs as closures over
    snapshots, exceptions surfaced at the join, idempotent
    :meth:`stop`).

    The contract that keeps this SAFE to thread is snapshot purity
    plus single-writer structure: every submitted job closes over the
    DISPATCHED device gather's output buffers (an immutable snapshot
    of the pool bytes at eviction time — the pages can be reused the
    moment the gather is enqueued, because program order sequences the
    gather before any later overwrite) and only ever calls
    :meth:`HostTier.complete`, which fills bytes into a record the
    scheduler thread already reserved and never mutates the prefix
    index. Timing can change WHEN host bytes land, never what they
    are — which is why async and sync swap streams are bitwise
    identical.

    API: :meth:`submit` enqueues ``fn`` under ``key`` (the bounded
    queue applies backpressure — a full queue blocks the submitter,
    bounding in-flight host copies); :meth:`join` blocks until
    ``key``'s job has retired, re-raising the job's exception if it
    died (the engine degrades that to a verified miss); :meth:`drain`
    waits the whole queue out (the leak-free kill contract: a replica
    killed with a non-empty swap queue completes its puts, so the
    arena and the prefix index still reconcile); :meth:`stop` drains
    then shuts the thread down (idempotent — the engine registers it
    with ``weakref.finalize``). After stop, :meth:`submit` runs jobs
    inline — the sync degradation, never a dropped swap.

    Job closures MAY emit request-trace spans (:mod:`apex_tpu
    .telemetry.tracing`): the engine captures the admitting request's
    trace id at dispatch and the job's ``swap_out_store`` span lands
    on this thread (``serving-swap-worker`` in the Chrome trace) —
    the tracer is lock-protected and appends are token-invisible, so
    the purity contract above is untouched."""

    _MAX_ERRORS = 64

    def __init__(self, max_queue: int = 64):
        self._jobs: "queue.Queue" = queue.Queue(maxsize=int(max_queue))
        self._cond = threading.Condition()
        self._inflight: set = set()
        self._errors: Dict[Any, BaseException] = {}
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-swap-worker")
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._jobs.get()
            if item is None:
                return
            key, fn = item
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at join
                with self._cond:
                    self._errors[key] = e
                    while len(self._errors) > self._MAX_ERRORS:
                        self._errors.pop(next(iter(self._errors)))
            finally:
                with self._cond:
                    self._inflight.discard(key)
                    self._cond.notify_all()

    def submit(self, key, fn: Callable[[], None]) -> None:
        """Enqueue ``fn`` to run on the worker thread under ``key``.
        ``fn`` MUST close over snapshots (dispatched device buffers,
        immutable host values) — never live mutable state. After
        :meth:`stop`, runs inline (the sync degradation). A stale
        un-joined error parked under the same key is dropped — a new
        job's outcome must never be judged by a dead predecessor's
        exception."""
        with self._cond:
            self._errors.pop(key, None)
            if self._stopped:
                stopped = True
            else:
                stopped = False
                self._inflight.add(key)
        if stopped:
            fn()
            return
        self._jobs.put((key, fn))

    def in_flight(self, key) -> bool:
        with self._cond:
            return key in self._inflight

    def pending_keys(self) -> List[Any]:
        with self._cond:
            return list(self._inflight)

    def join(self, key) -> None:
        """Block until ``key``'s job has retired (the in-flight-hit
        join: a hit racing its own swap-out waits for the arena write
        instead of reading partial bytes). Re-raises the job's
        exception when it died — the caller degrades to a verified
        miss."""
        with self._cond:
            while key in self._inflight:
                self._cond.wait(timeout=1.0)
            err = self._errors.pop(key, None)
        if err is not None:
            raise err

    def drain(self, timeout: Optional[float] = 10.0) -> bool:
        """Wait until every submitted job has retired (True) or
        ``timeout`` elapses (False) — the kill-time contract: queued
        swap-outs COMPLETE (their arena records fill in), so a drained
        engine's cross-tier audit reconciles."""
        deadline = None if timeout is None \
            else threading.TIMEOUT_MAX if timeout < 0 else timeout
        with self._cond:
            return self._cond.wait_for(lambda: not self._inflight,
                                       timeout=deadline)

    def stop(self) -> None:
        """Drain then shut the thread down (idempotent; registered as
        the owning engine's finalizer)."""
        with self._cond:
            if self._stopped:
                return
            self._stopped = True
        self.drain()
        self._jobs.put(None)
        self._thread.join(timeout=2.0)
