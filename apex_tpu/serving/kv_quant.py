"""Quantized KV-cache storage: int8 pools with per-``[layer, head]`` scales.

Serving capacity is HBM-bound and the KV pool is the dominant resident
allocation, so halving its bytes doubles resident prefixes, COW-shared
pages and concurrent slots on the same silicon. This module is the
storage-dtype tier the amp cast policies (:mod:`apex_tpu.amp.policy`
O0-O3) stop short of: where a policy picks the COMPUTE half dtype
(bf16), :class:`KVQuantConfig` picks the cache STORAGE dtype (int8)
independently — K/V leave the qkv GEMM in the compute half, are
quantized at the write site, and are dequantized INSIDE the attention
kernels (int8 block load → per-head scale multiply → the existing
online-softmax fp32 math), so quantized K/V never materialise in bf16
outside VMEM.

Scale layout — the design's load-bearing choice::

    k_scale, v_scale : fp32 [layers, heads]

- **per-head, not per-page/per-token**: a scale is a property of the
  (layer, head) DISTRIBUTION, frozen at engine construction from a
  calibration absmax. Storage stays a pure pytree of two int8 arrays
  plus two tiny fp32 arrays; no scale metadata rides the pages.
- **copy-on-write sharing stays free**: a prefix hit shares quantized
  pages by refcount bump exactly as in bf16 — because scales are not
  per-page, a shared page needs no scale copy and a donor and borrower
  read identical bytes through identical scales.
- **speculative rollback stays length arithmetic**: the rejected tail's
  quantized K/V sits past the committed length, unreachable and
  overwritten write-then-attend, with no scale state to unwind.
- **tensor parallelism shards scales with the pool**: ``[layers,
  heads]`` splits along the heads axis next to ``[layers, num_pages,
  heads/tp, page_len, head_dim]`` — each shard quantizes and
  dequantizes its own heads with its own scale slice, collective-free.

Numerics: symmetric linear quantization to ``[-127, 127]`` (qmax
:data:`QMAX`), ``scale = absmax * margin / 127``. The round-trip error
per element is bounded by ``scale / 2`` for inputs inside the
calibrated range (clipped beyond it — the ``margin`` headroom exists
because decode-time K/V can modestly exceed a prompt-sample absmax).
Greedy serving accuracy is therefore a TOLERANCE claim, not a bitwise
one: the quantized engine is measured as a token-match-rate against
the bf16 oracle (``bench_serving.py --quantized-kv``), while
``kv_quant=None`` remains the default and the bitwise baseline.

Calibration: per-``[layer, head]`` absmax either given explicitly
(``calibration_absmax`` — a scalar, a ``[layers, heads]`` array, or a
``(k, v)`` pair of either) or measured by one eager ``return_kv``
forward over a deterministic token sample (``calibration_tokens`` /
seeded random ints). An absmax of 0 or a non-finite absmax would
produce degenerate scales — dequantizing everything to 0 or NaN — so
:meth:`KVQuantConfig.resolve_scales` raises at ENGINE CONSTRUCTION,
never letting a degenerate scale surface later as NaN output.

The numeric core — grid, scale resolution, degenerate-absmax guard —
lives in :mod:`apex_tpu.serving.quant_common`, shared with the weight
tier (:mod:`apex_tpu.serving.weight_quant`); ``QMAX`` / ``quantize`` /
``dequantize`` / ``expand_scale`` are re-exported here unchanged, so
every pre-refactor import keeps working.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .quant_common import (QMAX, check_absmax, dequantize, expand_scale,
                           quantize, scale_from_absmax)

__all__ = ["KVQuantConfig", "QMAX", "quantize", "dequantize",
           "expand_scale"]


def _as_layer_head(value, layers: int, heads: int, what: str):
    arr = np.asarray(value, np.float32)
    if arr.ndim == 0:
        arr = np.full((layers, heads), float(arr), np.float32)
    if arr.shape != (layers, heads):
        raise ValueError(
            f"{what} calibration absmax must be a scalar or a "
            f"[layers={layers}, heads={heads}] array, got {arr.shape}")
    return arr


# eq=False: calibration_absmax may hold arrays and calibration_tokens a
# list, so a generated __eq__ would raise on array truthiness and the
# paired __hash__ would make the config unhashable — identity semantics
# keep the frozen config usable as a dict key / set member / static arg
@dataclasses.dataclass(frozen=True, eq=False)
class KVQuantConfig:
    """Storage-dtype tier for the serving KV cache (``Engine(kv_quant=
    KVQuantConfig())``): int8 K/V with per-``[layer, head]`` fp32
    scales.

    Parameters
    ----------
    dtype:
        Cache storage dtype. Only ``int8`` is implemented (the bf16
        default lives at ``kv_quant=None``, not here).
    scale_granularity:
        Only ``"head"`` (one scale per ``[layer, head]``) is
        implemented — the granularity at which copy-on-write page
        sharing needs no scale copy and tensor parallelism shards
        scales with the pool.
    calibration_absmax:
        Explicit per-``[layer, head]`` absolute-maximum calibration: a
        scalar, a ``[layers, heads]`` array, or a ``(k, v)`` pair of
        either. ``None`` (default) calibrates by running one eager
        ``return_kv`` forward over ``calibration_tokens`` (or a seeded
        random sample) and taking per-``[layer, head]`` absmax of the
        returned K/V. Zero or non-finite values are rejected LOUDLY at
        engine construction (degenerate scales), never deferred to NaN
        output.
    calibration_tokens:
        Token sample for auto-calibration (e.g. a representative
        system prompt); ``None`` draws ``calibration_len`` seeded
        random ids. Ignored when ``calibration_absmax`` is given.
    calibration_len / calibration_seed:
        Size and seed of the random fallback sample.
    margin:
        Headroom factor on the calibrated absmax (``scale = absmax *
        margin / 127``): decode-time K/V can modestly exceed a
        prompt-sample absmax, and a clipped outlier costs more accuracy
        than one coarser quantization step. The 1.25 default covers the
        decode drift measured on the shared-prefix bench stream (absmax
        up to ~1.12x the prompt-sample calibration); pushing it far
        higher trades the clipping it prevents for rounding error
        everywhere (the grid coarsens with the scale), which flips
        near-tie argmaxes just as surely as clipping does.
    """

    dtype: Any = jnp.int8
    scale_granularity: str = "head"
    calibration_absmax: Optional[Union[float, Any, Tuple]] = None
    calibration_tokens: Optional[Sequence[int]] = None
    calibration_len: int = 32
    calibration_seed: int = 0
    margin: float = 1.25

    def __post_init__(self):
        if jnp.dtype(self.dtype) != jnp.int8:
            raise ValueError(
                f"KVQuantConfig supports int8 storage only, got "
                f"{jnp.dtype(self.dtype).name} (bf16 storage is the "
                f"kv_quant=None default, not a quant config)")
        if self.scale_granularity != "head":
            raise ValueError(
                f"KVQuantConfig supports scale_granularity='head' "
                f"(one scale per [layer, head]), got "
                f"{self.scale_granularity!r}")
        if not (np.isfinite(self.margin) and self.margin > 0):
            raise ValueError(f"margin must be finite and > 0, got "
                             f"{self.margin}")
        if self.calibration_len < 1:
            raise ValueError("calibration_len must be >= 1")

    # ----------------------------------------------------------- scales
    def _calibrate(self, model, params, layers: int, heads: int):
        """Measure per-[layer, head] absmax from one eager return_kv
        forward over the calibration sample (the serving prefill path's
        own K/V, so the measured range is the stored range)."""
        vocab = int(model.vocab_size)
        max_len = int(getattr(model, "max_seq_len", self.calibration_len))
        if self.calibration_tokens is not None:
            toks = np.asarray(self.calibration_tokens, np.int32)
            if toks.ndim != 1 or toks.size < 1:
                raise ValueError("calibration_tokens must be a non-"
                                 "empty 1-D token sequence")
            toks = toks[:max_len]
        else:
            rng = np.random.default_rng(self.calibration_seed)
            n = min(self.calibration_len, max_len)
            toks = rng.integers(1, vocab, size=n).astype(np.int32)
        _, (k, v) = model.apply({"params": params}, toks[None, :],
                                train=False, return_kv=True)
        # [layers, 1, heads, S, d] -> absmax over (batch, pos, dim)
        k_absmax = np.asarray(jnp.max(jnp.abs(jnp.asarray(k, jnp.float32)),
                                      axis=(1, 3, 4)))
        v_absmax = np.asarray(jnp.max(jnp.abs(jnp.asarray(v, jnp.float32)),
                                      axis=(1, 3, 4)))
        if k_absmax.shape != (layers, heads):
            raise ValueError(
                f"calibration forward returned K/V for "
                f"{k_absmax.shape} (layers, heads); engine expected "
                f"({layers}, {heads})")
        return k_absmax, v_absmax

    def resolve_scales(self, model, params, *, layers: int, heads: int):
        """The per-``[layer, head]`` fp32 scale pair ``(k_scale,
        v_scale)`` the engine stores alongside its cache pytree.

        Raises :class:`ValueError` at (engine) construction when any
        calibration absmax is zero or non-finite — a zero absmax would
        make ``quantize`` divide by ~0 and ``dequantize`` return 0
        everywhere, a non-finite one would poison every attended token;
        both must fail HERE, loudly, not later as NaN output."""
        if self.calibration_absmax is not None:
            cal = self.calibration_absmax
            if isinstance(cal, tuple) and len(cal) == 2:
                k_absmax = _as_layer_head(cal[0], layers, heads, "K")
                v_absmax = _as_layer_head(cal[1], layers, heads, "V")
            else:
                k_absmax = _as_layer_head(cal, layers, heads, "K")
                v_absmax = k_absmax.copy()
        else:
            k_absmax, v_absmax = self._calibrate(model, params, layers,
                                                 heads)
        for name, absmax in (("K", k_absmax), ("V", v_absmax)):
            check_absmax(
                absmax,
                describe=lambda lh, n=name: (
                    f"{n} calibration absmax at [layer={lh[0]}, "
                    f"head={lh[1]}]"),
                hint="fix the calibration sample or pass an explicit "
                     "positive calibration_absmax")
        k_scale = scale_from_absmax(k_absmax, self.margin)
        v_scale = scale_from_absmax(v_absmax, self.margin)
        return jnp.asarray(k_scale), jnp.asarray(v_scale)
