"""apex_tpu — a TPU-native re-design of NVIDIA Apex (reference: tanghl1994/apex).

Apex is a mixed-precision + fused-kernel + data-parallel utility library layered
on PyTorch/CUDA (reference: apex/__init__.py). apex_tpu provides the same
capability surface layered on JAX/XLA/Pallas:

- ``apex_tpu.amp``            — opt-level cast policies (O0..O3) + dynamic loss
  scaling with master weights (reference: apex/amp/).
- ``apex_tpu.optimizers``     — fused whole-model optimizers (FusedAdam,
  FusedLAMB, FusedSGD, FusedNovoGrad, FusedAdagrad) built on a flat-superbuffer
  multi-tensor harness (reference: apex/optimizers/ + csrc/multi_tensor_*).
- ``apex_tpu.normalization``  — FusedLayerNorm / FusedRMSNorm backed by Pallas
  kernels with fp32 accumulation (reference: apex/normalization/).
- ``apex_tpu.parallel``       — DistributedDataParallel-shaped data parallelism
  over ICI collectives, SyncBatchNorm via Welford psum, LARC
  (reference: apex/parallel/).
- ``apex_tpu.transformer``    — Megatron-style tensor/pipeline/sequence
  parallelism on a jax.sharding.Mesh (reference: apex/transformer/).
- ``apex_tpu.contrib``        — fused cross-entropy, multihead attention, flash
  attention, distributed (ZeRO-style) optimizers, sparsity, etc.
  (reference: apex/contrib/).
- ``apex_tpu.telemetry``      — structured in-jit training telemetry: metrics
  registry, JSONL/stdout sinks, one-callback-per-step emission from the amp
  train step, comm-health counters, run-summary CLI (no reference
  counterpart — apex observes with NVTX + recipe prints only).
- ``apex_tpu.serving``        — compiled KV-cache inference: slot cache in the
  amp half dtype, one jitted prefill + one jitted decode-step program, and a
  continuous-batching scheduler with bounded-queue backpressure (no reference
  counterpart — apex is training-only).

Unlike the reference, everything here is functional and jit-first: policies are
dtype rules applied at trace time (not monkey-patches), the loss scaler is a
pytree carried in the train state, and comm is XLA collectives over a named-axis
mesh (not NCCL).
"""

from importlib import import_module as _import_module

# package-wide logging surface (promoted from transformer/log_util.py);
# stdlib-only, so the eager import costs nothing
from .log_util import get_logger, set_logging_level

__version__ = "0.1.0"

_SUBMODULES = (
    "RNN",
    "amp",
    "comm",
    "contrib",
    "fp16_utils",
    "fused_dense",
    "kernels",
    "log_util",
    "mlp",
    "models",
    "multi_tensor_apply",
    "normalization",
    "optimizers",
    "parallel",
    "pyprof",
    "reparameterization",
    "serving",
    "telemetry",
    "transformer",
    "utils",
)

__all__ = list(_SUBMODULES) + ["get_logger", "set_logging_level"]


def __getattr__(name):
    if name in _SUBMODULES:
        mod = _import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))
