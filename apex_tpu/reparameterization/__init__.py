"""apex_tpu.reparameterization — weight-norm reparameterization.

TPU equivalent of apex/reparameterization/ (reference:
reparameterization.py — class Reparameterization; weight_norm.py — class
WeightNorm). Apex's version exists because torch's weight_norm was not
fp16-safe: the norm must be computed in fp32 even when weights are fp16.

Functional design: instead of monkey-patching module attributes, a
reparameterized weight is stored as ``(v, g)`` and materialized by
:func:`compute_weight` inside the forward pass — the natural jax shape of
apex's pre-forward hook. :class:`WeightNormDense` is a flax layer using it;
:func:`apply_weight_norm` / :func:`remove_weight_norm` convert existing param
trees, mirroring apex's ``apply_weight_norm(module)`` API.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "Reparameterization",
    "WeightNorm",
    "WeightNormDense",
    "apply_weight_norm",
    "compute_weight",
    "remove_weight_norm",
]


def _norm_except(v: jnp.ndarray, dim: int) -> jnp.ndarray:
    """L2 norm over every axis but ``dim``, fp32 accumulation.

    weight_norm.py — WeightNorm.compute_weight computes
    ``norm(v.view(v.size(dim), -1), dim=1)`` in fp32 (the fp16-safety fix the
    apex fork exists for).
    """
    v32 = jnp.asarray(v, jnp.float32)
    axes = tuple(i for i in range(v.ndim) if i != dim % v.ndim)
    return jnp.sqrt(jnp.sum(v32 * v32, axis=axes, keepdims=True))


def compute_weight(v: jnp.ndarray, g: jnp.ndarray, dim: int = 0) -> jnp.ndarray:
    """w = g * v / ||v||, norms taken per-slice along ``dim`` in fp32.

    weight_norm.py — WeightNorm.compute_weight.
    """
    norm = _norm_except(v, dim)
    g32 = jnp.asarray(g, jnp.float32)
    shape = [1] * v.ndim
    shape[dim % v.ndim] = v.shape[dim % v.ndim]
    w = g32.reshape(shape) * jnp.asarray(v, jnp.float32) / norm
    return w.astype(jnp.asarray(v).dtype)


class Reparameterization:
    """Base reparameterization (reparameterization.py — Reparameterization).

    Subclasses define ``compute_weight(*params)`` and
    ``reparameterize(weight) -> params``. Stateless here — params live in the
    user's pytree.
    """

    dim: int = 0

    @staticmethod
    def compute_weight(*params):
        raise NotImplementedError

    @staticmethod
    def reparameterize(weight):
        raise NotImplementedError


class WeightNorm(Reparameterization):
    """weight_norm.py — class WeightNorm, functional form."""

    def __init__(self, dim: int = 0):
        self.dim = dim

    def compute_weight(self, v, g):  # type: ignore[override]
        return compute_weight(v, g, self.dim)

    def reparameterize(self, weight) -> Tuple[jnp.ndarray, jnp.ndarray]:  # type: ignore[override]
        norm = _norm_except(weight, self.dim)
        g = norm.reshape((weight.shape[self.dim % weight.ndim],))
        return jnp.asarray(weight), g.astype(jnp.asarray(weight).dtype)


def apply_weight_norm(params: Any, names: Optional[Sequence[str]] = None,
                      dim: int = 0) -> Any:
    """Split selected kernels into (v, g) pairs in a param pytree.

    apex: ``apply_weight_norm(module, name='weight')`` installs hooks. Here:
    every dict key named in ``names`` (default: 'kernel'/'weight') is replaced
    by ``{name}_v`` / ``{name}_g`` entries.
    """
    names = tuple(names or ("kernel", "weight"))
    wn = WeightNorm(dim)

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, sub in node.items():
                if k in names and isinstance(sub, jax.Array):
                    v, g = wn.reparameterize(sub)
                    out[f"{k}_v"], out[f"{k}_g"] = v, g
                else:
                    out[k] = walk(sub)
            return out
        return node

    return walk(jax.tree_util.tree_map(jnp.asarray, params))


def remove_weight_norm(params: Any, names: Optional[Sequence[str]] = None,
                       dim: int = 0) -> Any:
    """Materialize (v, g) pairs back into plain kernels (apex:
    remove_weight_norm)."""
    names = tuple(names or ("kernel", "weight"))

    def walk(node):
        if isinstance(node, dict):
            out = {}
            done = set()
            for k in node:
                if k.endswith("_v") and k[:-2] in names and f"{k[:-2]}_g" in node:
                    base = k[:-2]
                    out[base] = compute_weight(node[k], node[f"{base}_g"], dim)
                    done.add(f"{base}_g")
                elif k not in done and not (
                        k.endswith("_g") and k[:-2] in names
                        and f"{k[:-2]}_v" in node):
                    out[k] = walk(node[k])
            return out
        return node

    return walk(params)


class WeightNormDense(nn.Module):
    """Dense layer with weight-norm reparameterized kernel.

    The flax-native way to *use* the reparameterization (apex users wrap
    ``nn.Linear`` with ``apply_weight_norm``).
    """

    features: int
    use_bias: bool = True
    dim: int = 1  # norm per output feature (kernel is [in, out])
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        v = self.param("kernel_v", nn.initializers.lecun_normal(),
                       (in_features, self.features), self.param_dtype)
        g = self.param("kernel_g",
                       lambda key, shape, dtype: jnp.ones(shape, dtype),
                       (self.features,), self.param_dtype)
        kernel = compute_weight(v, g, dim=self.dim)
        if self.dtype is not None:
            kernel = kernel.astype(self.dtype)
            x = x.astype(self.dtype)
        y = x @ kernel
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + jnp.asarray(bias, y.dtype)
        return y
