"""Autocast interop helpers — parity with apex/_autocast_utils.py (P43).

The reference's ``_cast_if_autocast_enabled`` bridges apex's fused ops with
native ``torch.cuda.amp.autocast``: when autocast is active, inputs are cast
to the autocast dtype before entering a fused kernel that bypasses the
dispatcher. The functional analogue delegates to the policy engine's own
cast (`apex_tpu/amp/policy.py — _cast_floating`) so there is exactly one
cast implementation.
"""

from __future__ import annotations

import jax.numpy as jnp


def _cast_if_autocast_enabled(*args, policy=None, dtype=None):
    """Cast floating array args to the active compute dtype.

    ``policy`` (an :class:`apex_tpu.amp.Policy`) or an explicit ``dtype``
    names the target; with neither, args pass through unchanged (autocast
    "disabled"). Non-floating leaves are untouched, like the reference.
    """
    from apex_tpu.amp.policy import _cast_floating

    if dtype is None and policy is not None:
        dtype = policy.compute_dtype
    if dtype is None or dtype == jnp.float32:
        return args
    return tuple(_cast_floating(a, dtype) for a in args)
