"""apex_tpu.comm — the distributed communication backend.

The reference's comm backend is NCCL reached through ``torch.distributed``
(apex/parallel/distributed.py — flat_dist_call calls dist.all_reduce;
apex/transformer uses dist.all_gather / reduce_scatter / batch_isend_irecv;
contrib adds raw NCCL + CUDA IPC). On TPU none of that exists or is needed:
the fabric is ICI (intra-slice) + DCN (cross-slice), and the collectives are
XLA ops emitted from ``jax.lax`` primitives under ``shard_map``/``pjit`` on a
``jax.sharding.Mesh``.

This module is the single place upper layers get their mesh and collectives
from, so nothing else in the framework calls raw ``jax.lax`` comm ops or
constructs meshes ad-hoc (SURVEY §3.4's "thin comm module" design). Axis
conventions:

- ``data``  — data parallel; outermost, so multi-slice layouts put it on DCN.
- ``model`` — tensor/sequence parallel (Megatron TP group); innermost → ICI.
- ``pipe``  — pipeline stages, between the two.
- ``expert``— reserved extension point (the reference has no EP; SURVEY §3.3).

Process bootstrap: `jax.distributed.initialize` (multi-host), not
WORLD_SIZE/RANK env bootstrap (reference: apex/parallel/multiproc.py).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "AXIS_DATA", "AXIS_MODEL", "AXIS_PIPE", "AXIS_EXPERT", "AXIS_CONTEXT",
    "make_mesh", "make_hybrid_mesh", "default_mesh", "get_mesh", "set_mesh",
    "reset_mesh", "axis_size",
    "all_reduce", "all_reduce_max", "all_gather", "reduce_scatter",
    "ppermute", "broadcast_from", "axis_index", "initialize_distributed",
]

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"
AXIS_CONTEXT = "context"  # sequence/context parallel (ring attention)

_MESH: Optional[Mesh] = None


def initialize_distributed(**kwargs):
    """Multi-host bootstrap. TPU equivalent of the reference's
    ``torch.distributed.init_process_group("nccl", init_method="env://")``
    (examples/imagenet/main_amp.py — args.distributed block): on TPU pods the
    coordinator/process ids come from the runtime, so this is one call."""
    jax.distributed.initialize(**kwargs)


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from ``{axis_name: size}`` in the given axis order.

    Axis order is physical: earlier axes change slowest across the device
    list, so callers should order axes outermost-first (``data`` before
    ``model``) to keep TP collectives on ICI neighbours — the TPU analogue of
    apex putting NCCL rings inside a node.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(axes)
    sizes = tuple(int(axes[n]) for n in names)
    need = int(np.prod(sizes)) if sizes else 1
    if need > len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need], dtype=object).reshape(sizes)
    return Mesh(arr, names)


def make_hybrid_mesh(ici_axes: Dict[str, int],
                     dcn_axes: Dict[str, int]) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` partition ACROSS slices (riding DCN,
    the slow fabric), ``ici_axes`` within a slice (ICI). This is how the
    SURVEY §3.4 mapping scales past one slice: put data parallelism (the
    once-per-step grad allreduce) on DCN and TP/SP/PP (the per-layer
    collectives) on ICI — the TPU analogue of apex keeping NCCL rings
    inside a node and gradient averaging across nodes.

    Example on 4 slices of a v5e-64::

        mesh = comm.make_hybrid_mesh(ici_axes={"pipe": 4, "model": 16},
                                     dcn_axes={"data": 4})

    Axis names may appear in only one of the two dicts (size 1 elsewhere).
    On a single slice (or hosts whose devices carry no slice topology,
    e.g. the CPU test backend) this degrades to :func:`make_mesh` with the
    DCN axes outermost — same names, same shape, so code written against
    the hybrid mesh runs unchanged in CI.
    """
    overlap = set(ici_axes) & set(dcn_axes)
    if overlap:
        raise ValueError(
            f"axes {sorted(overlap)} appear in both ici_axes and dcn_axes; "
            f"an axis lives on exactly one fabric")
    names = tuple(dcn_axes) + tuple(ici_axes)
    devices = jax.devices()
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
        from jax.experimental import mesh_utils

        ici_shape = [ici_axes.get(n, 1) for n in names]
        dcn_shape = [dcn_axes.get(n, 1) for n in names]
        arr = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
        return Mesh(arr, names)
    # single slice / no slice topology: plain mesh, DCN axes outermost
    # ({**dcn, **ici} insertion order is exactly `names`)
    return make_mesh({**dcn_axes, **ici_axes})


def ensure_devices(n: int) -> list:
    """Return ≥ ``n`` devices, falling back to virtual CPU devices when the
    attached platform has fewer (hermetic runs of multi-device recipes).

    **Call this at program start, before creating any jax arrays or
    compiled computations.** When the attached platform is short it
    switches backends (``clear_backends``), which invalidates every live
    array and jitted executable; to prevent silent corruption it refuses
    to switch while arrays are live.

    The config updates are needed even when ``JAX_PLATFORMS=cpu`` is
    exported — the axon sitecustomize imports jax at interpreter start and
    pins ``jax_platforms``, overriding the env var; and
    ``jax_num_cpu_devices`` refuses to change on initialized backends,
    hence the clear_backends first.
    """
    devices = jax.devices()
    if len(devices) < n:
        from jax.extend.backend import clear_backends

        import gc

        live = jax.live_arrays()
        if live:
            # dead-but-uncollected arrays (reference cycles, pytest-pinned
            # tracebacks) must not trigger a spurious refusal
            gc.collect()
            live = jax.live_arrays()
        if live:
            raise RuntimeError(
                f"ensure_devices({n}) would switch jax backends, "
                f"invalidating {len(live)} live array(s). Call it before "
                "creating any arrays or compiled computations (recipe "
                "start), as the examples do.")
        clear_backends()
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n)
        devices = jax.devices()
    if len(devices) < n:
        raise RuntimeError(f"need {n} devices, have {len(devices)}")
    return devices


def default_mesh() -> Mesh:
    """All local devices on a single ``data`` axis — what plain apex DDP
    (pure data parallelism) corresponds to."""
    return make_mesh({AXIS_DATA: len(jax.devices())})


def set_mesh(mesh: Mesh) -> Mesh:
    """Install the process-global mesh (parallel_state-style registry;
    reference: apex/transformer/parallel_state.py keeps module globals)."""
    global _MESH
    _MESH = mesh
    return mesh


def get_mesh() -> Mesh:
    global _MESH
    if _MESH is None:
        _MESH = default_mesh()
    return _MESH


def reset_mesh() -> None:
    """Drop the installed mesh (parallel_state.destroy_model_parallel path);
    the next get_mesh() lazily rebuilds the data-only default."""
    global _MESH
    _MESH = None


def axis_size(axis_name: str, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh if mesh is not None else get_mesh()
    return int(mesh.shape.get(axis_name, 1))


# ----------------------------------------------------------------- collectives
# Thin wrappers so upper layers never touch jax.lax comm primitives directly.
# All of these are only meaningful inside shard_map/pmap with the named axis
# bound; under plain jit they raise NameError from XLA, matching the reference
# where dist.all_reduce without init_process_group raises.

def _account(op: str, tree) -> None:
    """Comm-health accounting (apex_tpu.telemetry): bytes/calls/leaves
    counters per collective. Runs at TRACE time — once per compiled
    program, so the counters read what ONE execution moves (see
    telemetry.account_collective). Lazy import keeps this module
    importable standalone and the disabled path one dict lookup."""
    from apex_tpu import telemetry

    telemetry.account_collective(op, tree)


def all_reduce(x, axis_name: str, op: str = "sum"):
    """dist.all_reduce equivalent. op: sum|mean|max|min."""
    _account("all_reduce", x)
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_reduce_max(x, axis_name: str):
    _account("all_reduce", x)
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """dist.all_gather equivalent (concatenate along ``axis``)."""
    _account("all_gather", x)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    """dist.reduce_scatter equivalent (sum + scatter along ``axis``)."""
    _account("reduce_scatter", x)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def ppermute(x, axis_name: str, perm):
    """Point-to-point collective permute — the TPU stand-in for every
    send/recv pattern in the reference (pipeline p2p_communication._communicate
    and the halo exchanges of contrib peer_memory/nccl_p2p)."""
    _account("ppermute", x)
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    """This shard's coordinate along the axis (dist.get_rank equivalent)."""
    return jax.lax.axis_index(axis_name)


def broadcast_from(x, axis_name: str, src: int = 0):
    """dist.broadcast equivalent: every member gets src's value. Apex DDP
    broadcasts params from rank 0 at init (distributed.py — __init__'s
    flat_dist_call(dist.broadcast)); under SPMD initialization is already
    replicated, so this exists for API parity and odd cases.

    One-to-many can't be a single ppermute (sources must be unique); the
    SPMD form is mask + psum, which XLA lowers to a broadcast from src.
    """
    _account("broadcast", x)
    x = jnp.asarray(x)
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    # psum promotes bool/narrow ints; the broadcast contract preserves dtype
    return jax.lax.psum(masked, axis_name).astype(x.dtype)


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh if mesh is not None else get_mesh()
    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh: Optional[Mesh] = None,
                  axis: str = AXIS_DATA) -> NamedSharding:
    """Batch-dim sharding over the data axis."""
    mesh = mesh if mesh is not None else get_mesh()
    return NamedSharding(mesh, PartitionSpec(axis))
