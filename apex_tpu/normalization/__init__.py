"""apex_tpu.normalization — FusedLayerNorm / FusedRMSNorm modules.

Mirrors the reference's ``apex/normalization/fused_layer_norm.py``
(FusedLayerNorm, FusedRMSNorm, MixedFusedLayerNorm, MixedFusedRMSNorm) as flax
modules over the Pallas kernels in apex_tpu.kernels.layer_norm. The reference
falls back to ``F.layer_norm`` when its CUDA ext is missing; here the kernel
itself falls back to the jnp reference path off the TPU-aligned hot path, so
the module API is unconditional.

"Mixed" in apex means fp32 params with fp16 I/O (MixedFusedLayerNorm casts
inputs to param dtype); here that is the natural flax split of ``dtype``
(compute) vs ``param_dtype`` (storage), with stats always fp32 in-kernel.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.kernels.layer_norm import layer_norm, rms_norm

__all__ = ["FusedLayerNorm", "FusedRMSNorm", "MixedFusedLayerNorm",
           "MixedFusedRMSNorm", "fused_layer_norm", "fused_rms_norm"]


def _norm_shape(normalized_shape) -> Sequence[int]:
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(normalized_shape)


def fused_layer_norm(x, weight=None, bias=None, eps: float = 1e-5):
    """Functional fused LayerNorm (reference: fused_layer_norm_cuda.forward)."""
    return layer_norm(x, weight, bias, eps=eps)


def fused_rms_norm(x, weight=None, eps: float = 1e-5):
    """Functional fused RMSNorm (reference: rms_forward_affine)."""
    return rms_norm(x, weight, eps=eps)


class FusedLayerNorm(nn.Module):
    """LayerNorm over the trailing ``normalized_shape`` dims.

    Reference: apex/normalization/fused_layer_norm.py — class FusedLayerNorm
    (elementwise_affine selects the affine/no-affine kernel pair).
    """

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    # apex fused_layer_norm.py — memory_efficient: backward keeps the
    # output (not the input); needs nonzero gamma
    memory_efficient: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        hidden = 1
        for s in shape:
            hidden *= s
        # O1 engine: 'layer_norm' is an FP32_FUNCS entry — with no explicit
        # dtype, an active autocast policy lifts the op to fp32 (input AND
        # output, like apex's patched F.layer_norm; the next FP16 op casts
        # back down). Kernel stats are fp32 in every case.
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "layer_norm")
        if dtype is not None:
            x = jnp.asarray(x, dtype)
        orig_shape = x.shape
        x2 = x.reshape(x.shape[:x.ndim - len(shape)] + (hidden,))
        if self.elementwise_affine:
            # params keep apex's weight shape (= normalized_shape, matching
            # apex FusedLayerNorm state_dicts); the kernel sees them flat.
            weight = self.param("scale", nn.initializers.ones, shape,
                                self.param_dtype).reshape(hidden)
            bias = self.param("bias", nn.initializers.zeros, shape,
                              self.param_dtype).reshape(hidden)
        else:
            weight = bias = None
        y = layer_norm(x2, weight, bias, eps=self.eps,
                       memory_efficient=self.memory_efficient)
        return y.reshape(orig_shape)


class FusedRMSNorm(nn.Module):
    """RMSNorm (reference: fused_layer_norm.py — class FusedRMSNorm)."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_shape(self.normalized_shape)
        hidden = 1
        for s in shape:
            hidden *= s
        # same O1 lift as FusedLayerNorm ('layer_norm' FP32 classification)
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "layer_norm")
        if dtype is not None:
            x = jnp.asarray(x, dtype)
        orig_shape = x.shape
        x2 = x.reshape(x.shape[:x.ndim - len(shape)] + (hidden,))
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, shape,
                                self.param_dtype).reshape(hidden)
        else:
            weight = None
        y = rms_norm(x2, weight, eps=self.eps,
                     memory_efficient=self.memory_efficient)
        return y.reshape(orig_shape)


# apex's "Mixed" variants exist because its FusedLayerNorm requires weight
# dtype == input dtype while MixedFusedLayerNorm allows fp32 gamma/beta with
# half inputs (apex/normalization/fused_layer_norm.py — MixedFusedLayerNorm).
# Here the base modules ALREADY implement that contract (param_dtype defaults
# to fp32, stats accumulate fp32 in-kernel, I/O dtype follows the input), so
# the Mixed names are pure API-parity aliases.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
