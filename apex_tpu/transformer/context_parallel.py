"""Context parallelism: ring attention + Ulysses (all-to-all) attention.

The reference has NO context parallelism (SURVEY §3.3: "CP / ring attention /
Ulysses — absent from apex"); its only long-sequence mechanisms are Megatron
sequence parallelism and fused attention kernels. On TPU, long-context
distribution is first-class, so this module supplies both standard schemes
on top of the blockwise flash kernel (apex_tpu/kernels/flash_attention.py),
which was written chunkwise-over-KV precisely so these slot in:

- :func:`ring_attention` — sequence sharded over a ``context`` mesh axis;
  KV chunks rotate around the ring via ``jax.lax.ppermute`` while each
  device's Q stays put, combining per-chunk (o, logsumexp) partial softmaxes
  into the exact global softmax. Memory per chip is O(seq/n); the rotation
  rides ICI neighbour links. Backward rotates (k, v, dk, dv) together so
  gradients arrive home after exactly n hops.
- :func:`ulysses_attention` — all-to-all head scatter: seq-sharded activations
  are transposed to head-sharded via ``lax.all_to_all``, full-sequence flash
  attention runs locally on heads/n heads, and a second all-to-all restores
  sequence sharding. Cheaper collectives for moderate sequence lengths;
  requires num_heads % axis_size == 0.

Both are exact (not approximations) and differentiable; both must be called
inside ``shard_map`` with the sequence dimension sharded over ``axis_name``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm import AXIS_CONTEXT
from apex_tpu.kernels.flash_attention import (_flatten as _flat, _match_vma,
                                              _mix_seed, attn_chunk_bwd,
                                              attn_chunk_fwd, flash_attention)

__all__ = ["ring_attention", "ulysses_attention", "AXIS_CONTEXT",
           "zigzag_order", "zigzag_inverse"]

_NEG_INF = -1e30


def _axis_size(axis_name):
    # Static under shard_map: psum of a literal 1 over the axis.
    return lax.psum(1, axis_name)


def _vary_like(x, *likes):
    """Give a freshly-created constant the union of the varying-manual-axes
    of ``likes`` so it types consistently with per-shard data in cond/switch/
    loop carries — q/k may vary over MORE than the ring axis (e.g. a 'data'
    axis in a DP+CP shard_map)."""
    for like in likes:
        x = _match_vma(x, like)
    return x


def _combine(o_run, lse_run, o_t, lse_t):
    """Merge two normalized partial-softmax results (o, lse) exactly."""
    lse_new = jnp.logaddexp(lse_run, lse_t)
    w1 = jnp.exp(lse_run - lse_new)[..., None]
    w2 = jnp.exp(lse_t - lse_new)[..., None]
    return o_run * w1 + o_t * w2, lse_new


def _rotate(tree, axis_name, n):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm), tree)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring(q, k, v, dropout_seed, axis_name, causal, scale, dropout_rate):
    out, _ = _ring_fwd(q, k, v, dropout_seed, axis_name, causal, scale,
                       dropout_rate)
    return out


def _pair_seed(dropout_seed, kv_idx, my_idx):
    """Per-(q-chunk, kv-chunk) dropout seed: HASHED so no two ring pairs
    (or steps, under the seed=step idiom) share a mask field; the same
    derivation in forward and backward replays the mask."""
    if dropout_seed is None:
        return None
    return _mix_seed(jnp.asarray(dropout_seed, jnp.int32), my_idx, kv_idx, 1)


def _chunk_cases(q3, k3, v3, causal, scale, kv_idx, my_idx,
                 dropout_rate=0.0, dropout_seed=None):
    """(o, lse) for one ring step, dispatching on the chunk relation.

    With contiguous sequence chunks, chunk j is entirely *before* chunk i in
    global positions when j < i → unmasked; j == i → local causal mask;
    j > i → fully masked out (skip). Non-causal always takes the full path.
    """
    seed = _pair_seed(dropout_seed, kv_idx, my_idx)
    if not causal:
        return attn_chunk_fwd(q3, k3, v3, scale=scale, causal=False,
                              dropout_rate=dropout_rate, dropout_seed=seed)
    bh, s, d = q3.shape

    def full(_):
        return attn_chunk_fwd(q3, k3, v3, scale=scale, causal=False,
                              dropout_rate=dropout_rate, dropout_seed=seed)

    def diag(_):
        return attn_chunk_fwd(q3, k3, v3, scale=scale, causal=True,
                              dropout_rate=dropout_rate, dropout_seed=seed)

    def skip(_):
        return (_vary_like(jnp.zeros((bh, s, d), jnp.float32), q3, k3),
                _vary_like(jnp.full((bh, s), _NEG_INF, jnp.float32), q3, k3))

    branch = jnp.where(kv_idx < my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2))
    return lax.switch(branch, [full, diag, skip], None)


def _chunk_bwd_cases(q3, k3, v3, do3, lse, delta, causal, scale, kv_idx,
                     my_idx, dropout_rate=0.0, dropout_seed=None):
    """(dq, dk, dv) for one chunk pair, dispatching on the chunk relation —
    the backward mirror of :func:`_chunk_cases`; shared by the contiguous
    and zigzag rings."""
    seed = _pair_seed(dropout_seed, kv_idx, my_idx)
    if not causal:
        return attn_chunk_bwd(q3, k3, v3, do3, lse, delta,
                              scale=scale, causal=False,
                              dropout_rate=dropout_rate, dropout_seed=seed)

    def full(_):
        return attn_chunk_bwd(q3, k3, v3, do3, lse, delta,
                              scale=scale, causal=False,
                              dropout_rate=dropout_rate, dropout_seed=seed)

    def diag(_):
        return attn_chunk_bwd(q3, k3, v3, do3, lse, delta,
                              scale=scale, causal=True,
                              dropout_rate=dropout_rate, dropout_seed=seed)

    def skip(_):
        return (_vary_like(jnp.zeros(q3.shape, jnp.float32), q3, k3),
                _vary_like(jnp.zeros(k3.shape, jnp.float32), q3, k3),
                _vary_like(jnp.zeros(v3.shape, jnp.float32), q3, k3))

    branch = jnp.where(kv_idx < my_idx, 0,
                       jnp.where(kv_idx == my_idx, 1, 2))
    return lax.switch(branch, [full, diag, skip], None)


def _ring_fwd(q, k, v, dropout_seed, axis_name, causal, scale,
              dropout_rate):
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s, d = q.shape
    q3, k3, v3 = _flat(q), _flat(k), _flat(v)

    def compute(t, o_run, lse_run, k_cur, v_cur):
        kv_idx = (idx - t) % n
        o_t, lse_t = _chunk_cases(q3, k_cur, v_cur, causal, scale, kv_idx,
                                  idx, dropout_rate, dropout_seed)
        return _combine(o_run, lse_run, o_t, lse_t)

    def step(t, carry):
        o_run, lse_run, k_cur, v_cur = carry
        o_run, lse_run = compute(t, o_run, lse_run, k_cur, v_cur)
        k_cur, v_cur = _rotate((k_cur, v_cur), axis_name, n)
        return o_run, lse_run, k_cur, v_cur

    # Constant-initialized carries are "replicated" over the mesh while the
    # loop body makes them device-varying; align the types. The final chunk
    # is computed OUTSIDE the loop so its KV rotation (whose result nobody
    # reads) never hits the ICI ring.
    o0 = _vary_like(jnp.zeros((b * h, s, d), jnp.float32), q3, k3)
    lse0 = _vary_like(jnp.full((b * h, s), _NEG_INF, jnp.float32), q3, k3)
    o_run, lse_run, k_last, v_last = lax.fori_loop(
        0, n - 1, step, (o0, lse0, k3, v3))
    o3, lse = compute(n - 1, o_run, lse_run, k_last, v_last)
    out = o3.astype(q.dtype).reshape(b, h, s, d)
    return out, (q3, k3, v3, o3, lse, dropout_seed)


def _ring_bwd(axis_name, causal, scale, dropout_rate, res, g):
    q3, k3, v3, o3, lse, dropout_seed = res
    b, h = g.shape[0], g.shape[1]
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    do3 = _flat(g)
    delta = jnp.sum(jnp.asarray(do3, jnp.float32) * o3, axis=-1)  # [bh, s]

    def accumulate(t, dq, k_cur, v_cur, dk_acc, dv_acc):
        kv_idx = (idx - t) % n
        dq_t, dk_t, dv_t = _chunk_bwd_cases(q3, k_cur, v_cur, do3, lse,
                                            delta, causal, scale, kv_idx,
                                            idx, dropout_rate, dropout_seed)
        return dq + dq_t, dk_acc + dk_t, dv_acc + dv_t

    def step(t, carry):
        dq, k_cur, v_cur, dk_acc, dv_acc = carry
        dq, dk_acc, dv_acc = accumulate(t, dq, k_cur, v_cur, dk_acc, dv_acc)
        # dk/dv rotate WITH their kv chunk: after n hops they are home.
        k_cur, v_cur, dk_acc, dv_acc = _rotate(
            (k_cur, v_cur, dk_acc, dv_acc), axis_name, n)
        return dq, k_cur, v_cur, dk_acc, dv_acc

    dq0 = _vary_like(jnp.zeros(q3.shape, jnp.float32), q3, k3)
    dk0 = _vary_like(jnp.zeros(k3.shape, jnp.float32), q3, k3)
    dv0 = _vary_like(jnp.zeros(v3.shape, jnp.float32), q3, k3)
    # Last chunk outside the loop: only the accumulators need the final hop
    # home — k/v would be sent around once more just to be dropped.
    dq, k_last, v_last, dk_acc, dv_acc = lax.fori_loop(
        0, n - 1, step, (dq0, k3, v3, dk0, dv0))
    dq, dk_acc, dv_acc = accumulate(n - 1, dq, k_last, v_last, dk_acc, dv_acc)
    dk, dv = _rotate((dk_acc, dv_acc), axis_name, n)

    s, d = q3.shape[1], q3.shape[2]
    return (dq.astype(q3.dtype).reshape(b, h, s, d),
            dk.astype(k3.dtype).reshape(b, h, k3.shape[1], d),
            dv.astype(v3.dtype).reshape(b, h, v3.shape[1], d), None)


_ring.defvjp(_ring_fwd, _ring_bwd)


# ------------------------------------------------- zig-zag (balanced causal)
def zigzag_order(seq_len: int, n: int):
    """Global→zigzag permutation indices for a sequence of ``seq_len`` over
    ``n`` ring ranks: the sequence splits into 2n chunks and rank i holds
    chunks (i, 2n-1-i), so causal work is the same on every rank ((i+1) +
    (2n-i) chunk-pairs = 2n+1). Apply as ``x[..., zigzag_order(S, n), :]``
    on the GLOBAL sequence dim before contiguous sharding; positions/masks
    must be permuted identically."""
    if seq_len % (2 * n):
        raise ValueError(f"seq_len {seq_len} must divide into 2*{n} chunks")
    c = seq_len // (2 * n)
    head = jnp.arange(n, dtype=jnp.int32)              # chunk i
    tail = 2 * n - 1 - head                            # chunk 2n-1-i
    chunks = jnp.stack([head, tail], axis=1).reshape(-1)  # [2n] chunk ids
    offs = jnp.arange(c, dtype=jnp.int32)
    return (chunks[:, None] * c + offs[None, :]).reshape(-1)


def zigzag_inverse(seq_len: int, n: int):
    """Inverse permutation: zigzag-ordered → natural global order."""
    return jnp.argsort(zigzag_order(seq_len, n)).astype(jnp.int32)


def _zz_halves(x3, half):
    return x3[:, :half], x3[:, half:]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_zz(q, k, v, dropout_seed, axis_name, causal, scale, dropout_rate):
    out, _ = _ring_zz_fwd(q, k, v, dropout_seed, axis_name, causal, scale,
                          dropout_rate)
    return out


def _ring_zz_fwd(q, k, v, dropout_seed, axis_name, causal, scale,
                 dropout_rate):
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s, d = q.shape
    half = s // 2
    q3, k3, v3 = _flat(q), _flat(k), _flat(v)
    qa, qb = _zz_halves(q3, half)
    qa_idx, qb_idx = idx, 2 * n - 1 - idx

    def compute(t, oa, la, ob, lb, k_cur, v_cur):
        r = (idx - t) % n
        ka, kb = _zz_halves(k_cur, half)
        va, vb = _zz_halves(v_cur, half)
        ka_idx, kb_idx = r, 2 * n - 1 - r
        o_t, l_t = _chunk_cases(qa, ka, va, causal, scale, ka_idx, qa_idx,
                                dropout_rate, dropout_seed)
        oa, la = _combine(oa, la, o_t, l_t)
        o_t, l_t = _chunk_cases(qa, kb, vb, causal, scale, kb_idx, qa_idx,
                                dropout_rate, dropout_seed)
        oa, la = _combine(oa, la, o_t, l_t)
        o_t, l_t = _chunk_cases(qb, ka, va, causal, scale, ka_idx, qb_idx,
                                dropout_rate, dropout_seed)
        ob, lb = _combine(ob, lb, o_t, l_t)
        o_t, l_t = _chunk_cases(qb, kb, vb, causal, scale, kb_idx, qb_idx,
                                dropout_rate, dropout_seed)
        ob, lb = _combine(ob, lb, o_t, l_t)
        return oa, la, ob, lb

    def step(t, carry):
        oa, la, ob, lb, k_cur, v_cur = carry
        oa, la, ob, lb = compute(t, oa, la, ob, lb, k_cur, v_cur)
        k_cur, v_cur = _rotate((k_cur, v_cur), axis_name, n)
        return oa, la, ob, lb, k_cur, v_cur

    oa0 = _vary_like(jnp.zeros((b * h, half, d), jnp.float32), q3, k3)
    la0 = _vary_like(jnp.full((b * h, half), _NEG_INF, jnp.float32), q3, k3)
    carry = (oa0, la0, jnp.copy(oa0), jnp.copy(la0), k3, v3)
    oa, la, ob, lb, k_last, v_last = lax.fori_loop(0, n - 1, step, carry)
    oa, la, ob, lb = compute(n - 1, oa, la, ob, lb, k_last, v_last)
    o3 = jnp.concatenate([oa, ob], axis=1)
    lse = jnp.concatenate([la, lb], axis=1)
    out = o3.astype(q.dtype).reshape(b, h, s, d)
    return out, (q3, k3, v3, o3, lse, dropout_seed)


def _ring_zz_bwd(axis_name, causal, scale, dropout_rate, res, g):
    q3, k3, v3, o3, lse, dropout_seed = res
    b, h = g.shape[0], g.shape[1]
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    s, d = q3.shape[1], q3.shape[2]
    half = s // 2
    do3 = _flat(g)
    delta = jnp.sum(jnp.asarray(do3, jnp.float32) * o3, axis=-1)  # [bh, s]

    qa, qb = _zz_halves(q3, half)
    doa, dob = _zz_halves(do3, half)
    lsa, lsb = lse[:, :half], lse[:, half:]
    dea, deb = delta[:, :half], delta[:, half:]
    qa_idx, qb_idx = idx, 2 * n - 1 - idx

    def accumulate(t, dqa, dqb, k_cur, v_cur, dk_acc, dv_acc):
        r = (idx - t) % n
        ka, kb = _zz_halves(k_cur, half)
        va, vb = _zz_halves(v_cur, half)
        ka_idx, kb_idx = r, 2 * n - 1 - r
        dq_t, dka1, dva1 = _chunk_bwd_cases(qa, ka, va, doa, lsa, dea,
                                            causal, scale, ka_idx, qa_idx,
                                            dropout_rate, dropout_seed)
        dqa = dqa + dq_t
        dq_t, dkb1, dvb1 = _chunk_bwd_cases(qa, kb, vb, doa, lsa, dea,
                                            causal, scale, kb_idx, qa_idx,
                                            dropout_rate, dropout_seed)
        dqa = dqa + dq_t
        dq_t, dka2, dva2 = _chunk_bwd_cases(qb, ka, va, dob, lsb, deb,
                                            causal, scale, ka_idx, qb_idx,
                                            dropout_rate, dropout_seed)
        dqb = dqb + dq_t
        dq_t, dkb2, dvb2 = _chunk_bwd_cases(qb, kb, vb, dob, lsb, deb,
                                            causal, scale, kb_idx, qb_idx,
                                            dropout_rate, dropout_seed)
        dqb = dqb + dq_t
        dk_t = jnp.concatenate([dka1 + dka2, dkb1 + dkb2], axis=1)
        dv_t = jnp.concatenate([dva1 + dva2, dvb1 + dvb2], axis=1)
        return dqa, dqb, dk_acc + dk_t, dv_acc + dv_t

    def step(t, carry):
        dqa, dqb, k_cur, v_cur, dk_acc, dv_acc = carry
        dqa, dqb, dk_acc, dv_acc = accumulate(t, dqa, dqb, k_cur, v_cur,
                                              dk_acc, dv_acc)
        k_cur, v_cur, dk_acc, dv_acc = _rotate(
            (k_cur, v_cur, dk_acc, dv_acc), axis_name, n)
        return dqa, dqb, k_cur, v_cur, dk_acc, dv_acc

    dqa0 = _vary_like(jnp.zeros((b * h, half, d), jnp.float32), q3, k3)
    dk0 = _vary_like(jnp.zeros(k3.shape, jnp.float32), q3, k3)
    carry = (dqa0, jnp.copy(dqa0), k3, v3, dk0, jnp.copy(dk0))
    dqa, dqb, k_last, v_last, dk_acc, dv_acc = lax.fori_loop(
        0, n - 1, step, carry)
    dqa, dqb, dk_acc, dv_acc = accumulate(n - 1, dqa, dqb, k_last, v_last,
                                          dk_acc, dv_acc)
    dk, dv = _rotate((dk_acc, dv_acc), axis_name, n)
    dq = jnp.concatenate([dqa, dqb], axis=1)

    return (dq.astype(q3.dtype).reshape(b, h, s, d),
            dk.astype(k3.dtype).reshape(b, h, s, d),
            dv.astype(v3.dtype).reshape(b, h, s, d), None)


_ring_zz.defvjp(_ring_zz_fwd, _ring_zz_bwd)


def ring_attention(q, k, v, *, axis_name: str = AXIS_CONTEXT,
                   causal: bool = False, scale: Optional[float] = None,
                   layout: str = "contiguous",
                   dropout_rate: float = 0.0, dropout_seed=None):
    """Exact ring attention over a context-parallel mesh axis.

    q, k, v: [batch, heads, local_seq, head_dim], sequence sharded over
    ``axis_name``. Must be called inside shard_map.

    ``layout="contiguous"``: shard i holds global positions
    [i*local_seq, (i+1)*local_seq). Simple, but under ``causal`` the work is
    imbalanced — rank i computes i+1 chunk-pairs, so the step time is rank
    n-1's full load.

    ``layout="zigzag"``: shard i holds global chunks (i, 2n-1-i) of size
    local_seq/2 (permute the global sequence with :func:`zigzag_order`
    before sharding, and outputs/positions back with
    :func:`zigzag_inverse`). Every rank computes exactly 2n+1 sub-chunk
    pairs under ``causal`` — balanced, ~2× faster at large n.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    # no per-shard seed fold needed (unlike ulysses): every (q-chunk,
    # kv-chunk) pair seed hashes in the GLOBAL chunk ids via _pair_seed,
    # and each rank owns distinct q chunks — mask fields are already
    # rank-distinct and replay on the rank that computed them
    if layout == "contiguous" or (layout == "zigzag" and not causal):
        # non-causal attention is layout-invariant: the contiguous ring
        # computes the identical result in one full-chunk pass per step
        # instead of four half-chunk passes
        return _ring(q, k, v, dropout_seed, axis_name, causal, float(scale),
                     dropout_rate)
    if layout == "zigzag":
        if q.shape[2] % 2:
            raise ValueError(
                f"zigzag layout needs an even local_seq, got {q.shape[2]}")
        return _ring_zz(q, k, v, dropout_seed, axis_name, causal,
                        float(scale), dropout_rate)
    raise ValueError(f"unknown ring layout {layout!r} "
                     "(expected 'contiguous' or 'zigzag')")


def ulysses_attention(q, k, v, *, axis_name: str = AXIS_CONTEXT,
                      causal: bool = False, scale: Optional[float] = None,
                      segment_ids: Optional[jnp.ndarray] = None,
                      bias: Optional[jnp.ndarray] = None,
                      dropout_rate: float = 0.0, dropout_seed=None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    Seq-sharded [b, h, s/n, d] → head-sharded [b, h/n, s, d] via
    ``lax.all_to_all``, full-sequence flash attention locally, then the
    inverse all-to-all. Differentiable end-to-end (all_to_all transposes to
    itself); requires heads % axis_size == 0.

    ``bias`` [b|1, h|1, S, S] covers the FULL sequence; the head dim must
    be 1 (head-broadcast) — per-head bias would need an all-to-all of the
    bias to follow its heads to their owning shard. ``dropout_rate``/
    ``dropout_seed``: fused softmax dropout; the per-shard head slice makes
    each shard's mask distinct automatically (the flash kernel seeds per
    local batch·head, and the shard index is folded in here).
    """
    n = _axis_size(axis_name)
    h = q.shape[1]
    if h % n != 0:
        raise ValueError(f"heads ({h}) not divisible by axis size ({n})")
    if bias is not None and bias.shape[1] != 1:
        raise ValueError(
            "ulysses_attention: per-head bias is not supported (heads "
            "scatter across shards); use a [b|1, 1, S, S] bias")
    qh, kh, vh = (lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                                 tiled=True) for t in (q, k, v))
    if segment_ids is not None and segment_ids.shape[1] != qh.shape[2]:
        # seq-sharded [b, s/n] like q — gather to the full sequence the
        # post-all_to_all attention runs over.
        segment_ids = lax.all_gather(segment_ids, axis_name, axis=1,
                                     tiled=True)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        # distinct mask field per shard (each shard holds different heads).
        # HASH the shard index in — linear addition would make shard k at
        # step t collide with shard k+1 at step t-1 under the seed=step
        # idiom, exactly the collision class _mix_seed exists to prevent.
        from apex_tpu.kernels.flash_attention import _mix_seed
        dropout_seed = _mix_seed(jnp.asarray(dropout_seed, jnp.int32),
                                 lax.axis_index(axis_name), 0, 0)
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                          segment_ids=segment_ids, bias=bias,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
