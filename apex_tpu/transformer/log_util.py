"""Transformer logging utilities — thin aliases over the package-wide
surface (apex_tpu.log_util — get_logger / set_logging_level).

Reference: apex/transformer/log_util.py — get_transformer_logger,
set_logging_level. The reference scopes these to the transformer subtree;
the real implementation now lives at the package root and this module
keeps the transformer-scoped names (and the ``apex_tpu.transformer``
logger namespace) for API parity.
"""

from __future__ import annotations

import logging

from ..log_util import get_logger

__all__ = ["get_transformer_logger", "set_logging_level"]

_ROOT = "apex_tpu.transformer"


def get_transformer_logger(name: str = "") -> logging.Logger:
    """Namespaced logger (reference: get_transformer_logger(__name__))."""
    return get_logger(f"transformer.{name}" if name else "transformer")


def set_logging_level(verbosity) -> None:
    """Set the shared transformer logger level (reference:
    set_logging_level; accepts ints or level names)."""
    get_logger("transformer").setLevel(verbosity)
