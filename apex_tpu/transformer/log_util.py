"""Transformer logging utilities.

Reference: apex/transformer/log_util.py — get_transformer_logger,
set_logging_level. Same tiny surface on stdlib logging.
"""

from __future__ import annotations

import logging

__all__ = ["get_transformer_logger", "set_logging_level"]

_ROOT = "apex_tpu.transformer"


def get_transformer_logger(name: str = "") -> logging.Logger:
    """Namespaced logger (reference: get_transformer_logger(__name__))."""
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def set_logging_level(verbosity) -> None:
    """Set the shared transformer logger level (reference:
    set_logging_level; accepts ints or level names)."""
    logging.getLogger(_ROOT).setLevel(verbosity)
