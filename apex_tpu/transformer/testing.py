"""Testing helpers — parity with apex/transformer/testing/ (P26), plus the
full-parallelism dryrun model.

The reference ships a standalone toy GPT/BERT and global_vars for its
run_transformer tests. The TPU equivalent centers on
:func:`build_full_parallel_step`: a miniature transformer training step that
exercises EVERY parallelism axis at once —

- **dp**   gradient psum over ``data`` (apex DDP semantics via
  ``amp.make_train_step(grad_average_axis="data")``)
- **tp**   Column/RowParallelLinear over ``model``
- **sp**   sequence-parallel activations (gather/reduce-scatter pair)
- **pp**   collective-permute 1F1B pipelining over ``pipe``
- **ep**   expert-parallel MoE all_to_all over the ``data`` axis, with the
  per-leaf grad reduction mask (expert grads are never psummed)

Grad-correctness notes encoded here (the parts a naive composition gets
wrong):

- params replicated over ``model`` whose activations are model-sharded
  (LN params, row bias, every MoE param under SP) are passed through
  ``copy_to_tensor_model_parallel_region`` — identity forward, psum
  backward — the Megatron rule for LN grads under sequence parallelism;
- MoE expert weights are sharded over ``data``: their complete grads arrive
  via the all_to_all transpose, so the DDP mask marks them False (scale by
  1/world, no psum).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.kernels.layer_norm import layer_norm
from apex_tpu.transformer.moe import MoEMLP
from apex_tpu.transformer.pipeline_parallel.schedules import (
    make_pipeline_loss_fn)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear, RowParallelLinear)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region)

__all__ = ["build_full_parallel_step", "make_full_parallel_inputs",
           "factor_mesh_axes"]


def factor_mesh_axes(n: int) -> Dict[str, int]:
    """Factor ``n`` devices into (data, pipe, model) sizes, largest first on
    data, preferring 2s on pipe/model so every axis is exercised when room
    allows (8 → 2/2/2, 4 → 1/2/2, 2 → 1/1/2, 1 → 1/1/1)."""
    model = 2 if n % 2 == 0 else 1
    rest = n // model
    pipe = 2 if rest % 2 == 0 else 1
    data = rest // pipe
    return {"data": data, "pipe": pipe, "model": model}


def _stage_params(rng, *, hidden, inner, tp, dp, n_experts, e_inner):
    """Host-side numpy params for ONE stage, with explicit shard dims for
    model-/data-sharded leaves (leading tp / dp dims).

    Weights are drawn as GLOBAL matrices and then split, so two different
    (tp, dp) layouts built from the same seed describe the identical model —
    the property the cross-layout parity test asserts."""
    rs = np.random.RandomState(rng)
    e_local = n_experts // dp

    def w(*shape, scale=0.05):
        return (rs.randn(*shape) * scale).astype(np.float32)

    col_global = w(hidden, inner)            # [H, I] → column blocks
    row_global = w(inner, hidden)            # [I, H] → row blocks
    moe_w1 = w(n_experts, hidden, e_inner, scale=0.02)
    moe_w2 = w(n_experts, e_inner, hidden, scale=0.02)

    return {
        "ln1_scale": np.ones((hidden,), np.float32),
        "ln1_bias": np.zeros((hidden,), np.float32),
        # A = [A_1 .. A_p] column split → [tp, H, I/tp]
        "col_kernel": np.ascontiguousarray(
            col_global.reshape(hidden, tp, inner // tp).transpose(1, 0, 2)),
        "col_bias": np.zeros((tp, inner // tp), np.float32),
        # row split is contiguous over I → [tp, I/tp, H]
        "row_kernel": row_global.reshape(tp, inner // tp, hidden).copy(),
        "row_bias": np.zeros((hidden,), np.float32),
        "ln2_scale": np.ones((hidden,), np.float32),
        "ln2_bias": np.zeros((hidden,), np.float32),
        "moe": {
            "router": {"kernel": w(hidden, n_experts, scale=0.02),
                       "bias": np.zeros((n_experts,), np.float32)},
            "w1": moe_w1.reshape(dp, e_local, hidden, e_inner).copy(),
            "b1": np.zeros((dp, e_local, e_inner), np.float32),
            "w2": moe_w2.reshape(dp, e_local, e_inner, hidden).copy(),
            "b2": np.zeros((dp, e_local, hidden), np.float32),
        },
    }


# per-leaf: which mesh axes (beyond 'pipe') the GLOBAL array carries as
# leading shard dims, in order. Used to build in_specs and to strip the
# local singleton dims inside shard_map.
_SHARD_AXES = {
    ("col_kernel",): ("model",),
    ("col_bias",): ("model",),
    ("row_kernel",): ("model",),
    ("moe", "w1"): ("data",),
    ("moe", "b1"): ("data",),
    ("moe", "w2"): ("data",),
    ("moe", "b2"): ("data",),
}

# leaves replicated over 'data' get the normal DDP psum-mean; data-sharded
# expert leaves must not (their grads arrive complete via a2a transpose)
_DATA_SHARDED = {("moe", "w1"), ("moe", "b1"), ("moe", "w2"), ("moe", "b2")}


def _leaf_key(path) -> Tuple[str, ...]:
    return tuple(getattr(p, "key", str(p)) for p in path)


def make_full_parallel_inputs(*, n_stages, tp, dp, hidden=32, inner=64,
                              n_experts=4, e_inner=32, micro=4, batch=2,
                              seq=8, seed=0, capacity_factor=1.25,
                              num_chunks=1):
    """Global (host) params + microbatch stream + in_specs for shard_map.

    Returns (params, specs, mask, microbatches, targets, dims). Activation
    layout is [S_local, B, H] (sequence first — the SP shard dim), so the
    global microbatch array is [M, DP, TP, S_local, B, H]."""
    from jax.sharding import PartitionSpec as P

    # num_chunks > 1: interleaved virtual pipeline — logical stage
    # (c*pp + r) lives on pipe rank r as its chunk c, so the stacked row
    # order is r*v + c ↦ stage c*pp + r (schedules.py's round-robin split)
    L = n_stages * num_chunks
    stages = [_stage_params(seed + s, hidden=hidden, inner=inner, tp=tp,
                            dp=dp, n_experts=n_experts, e_inner=e_inner)
              for s in range(L)]
    if num_chunks > 1:
        order = [c * n_stages + r for r in range(n_stages)
                 for c in range(num_chunks)]
        stages = [stages[i] for i in order]
    params = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *stages)

    def spec_of(path, leaf):
        axes = _SHARD_AXES.get(_leaf_key(path), ())
        return P("pipe", *axes)

    specs = jax.tree_util.tree_map_with_path(spec_of, params)
    mask = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _leaf_key(path) not in _DATA_SHARDED, params)

    rs = np.random.RandomState(seed + 999)
    s_local = seq // tp
    mb = rs.randn(micro, dp, tp, s_local, batch, hidden).astype(np.float32)
    tg = rs.randn(micro, dp, tp, s_local, batch, hidden).astype(np.float32)
    dims = dict(hidden=hidden, inner=inner, n_experts=n_experts,
                e_inner=e_inner, tp=tp, dp=dp, n_stages=n_stages,
                capacity_factor=capacity_factor, num_chunks=num_chunks)
    return params, specs, mask, mb, tg, dims


def _strip_local(params, num_chunks=1):
    """Inside shard_map every sharded leading dim is a singleton: index it
    away (any model/data shard dim; the pipe dim too unless it carries
    ``num_chunks`` virtual-stage rows, which stage_fn consumes via
    schedules._chunk)."""

    def strip(path, leaf):
        n_shard = len(_SHARD_AXES.get(_leaf_key(path), ()))
        if num_chunks == 1:
            leaf = leaf[0]          # pipe singleton
            for _ in range(n_shard):
                leaf = leaf[0]
            return leaf
        # keep the [v, ...] chunk stack; drop shard singletons at axis 1
        for _ in range(n_shard):
            leaf = leaf[:, 0]
        return leaf

    return jax.tree_util.tree_map_with_path(strip, params)


def build_full_parallel_step(dims, mask, *, opt_level="O2",
                             n_steps: int = 2):
    """Returns ``run(global_params, microbatches, targets) -> losses[n]`` to
    be wrapped in ``shard_map`` over a ("data", "pipe", "model") mesh.

    Inside: strips shard dims, builds the amp-O2 train step over the
    pipelined stage function, runs ``n_steps`` steps on the same batch.
    """
    hidden, inner = dims["hidden"], dims["inner"]
    tp, dp = dims["tp"], dims["dp"]
    n_experts, e_inner = dims["n_experts"], dims["e_inner"]
    n_stages = dims["n_stages"]

    col = ColumnParallelLinear(input_size=hidden, output_size=inner,
                               use_bias=False, sequence_parallel_enabled=True,
                               world_size=tp)
    row = RowParallelLinear(input_size=inner, output_size=hidden,
                            use_bias=False, input_is_parallel=True,
                            sequence_parallel_enabled=True, world_size=tp)
    moe = MoEMLP(hidden=hidden, intermediate=e_inner, num_experts=n_experts,
                 axis_name="data",
                 capacity_factor=dims.get("capacity_factor", 1.25))

    def rep(p):
        # replicated-over-model param whose activations are model-sharded:
        # identity fwd, psum bwd over 'model' (Megatron SP LN-grad rule)
        return copy_to_tensor_model_parallel_region(p, "model") if tp > 1 \
            else p

    def stage_fn(p, x):
        s_l, b, h = x.shape
        a = x
        h1 = layer_norm(a.reshape(-1, hidden), rep(p["ln1_scale"]),
                        rep(p["ln1_bias"])).reshape(a.shape)
        h1 = col.apply({"params": {"kernel": p["col_kernel"]}}, h1)
        h1 = h1 + p["col_bias"]  # model-sharded: grads local-complete
        h1 = jax.nn.gelu(h1, approximate=False)
        h1 = row.apply({"params": {"kernel": p["row_kernel"]}}, h1)
        h1 = h1 + rep(p["row_bias"])
        a = a + h1
        h2 = layer_norm(a.reshape(-1, hidden), rep(p["ln2_scale"]),
                        rep(p["ln2_bias"]))
        moe_params = jax.tree_util.tree_map(rep, p["moe"])
        y, _aux = moe.apply({"params": moe_params}, h2)
        # pipe-boundary activations keep the input dtype: the scan carry
        # (and ppermute buffers) must be type-stable across stages
        return jnp.asarray(a + y.reshape(a.shape), x.dtype)

    def mb_loss(y, t):
        # fp32 loss math (amp FP32_FUNCS)
        l = jnp.mean((jnp.asarray(y, jnp.float32)
                      - jnp.asarray(t, jnp.float32)) ** 2)
        if tp > 1:
            # under SP each model rank sees a seq chunk; collective
            # transposes make the optimized objective Σ over ranks of the
            # returned scalars, so return local/tp (→ objective = global
            # mean) and add a value-only psum so the REPORTED loss is the
            # global mean too (same trick as schedules.make_pipeline_loss_fn
            # uses over the pipe axis).
            l = l / tp
            l = l + jax.lax.stop_gradient(jax.lax.psum(l, "model") - l)
        return l

    num_chunks = dims.get("num_chunks", 1)
    pipe_loss = make_pipeline_loss_fn(stage_fn, mb_loss,
                                      num_stages=n_stages,
                                      num_chunks=num_chunks)

    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    import optax
    # the mask tree mirrors params but holds python bools; no shard dims.
    # every axis with shard-local params (pipe stages, tp kernels, data-
    # sharded experts) must sync found_inf — see make_train_step docs.
    sync = tuple(ax for ax, size in
                 (("data", dp), ("pipe", n_stages), ("model", tp))
                 if size > 1)
    init_fn, step_fn = amp.make_train_step(
        pipe_loss, optax.sgd(0.05), policy,
        grad_average_axis="data" if dp > 1 else None,
        grad_average_mask=mask if dp > 1 else None,
        overflow_sync_axes=sync or None)

    def run(global_params, mb, tg):
        p = _strip_local(global_params, num_chunks)
        batch = (mb[:, 0, 0], tg[:, 0, 0])  # local mb: [M,1,1,S,B,H]
        state = init_fn(p)
        losses = []
        for _ in range(n_steps):
            state, metrics = step_fn(state, batch)
            losses.append(metrics["loss"])
        return jnp.stack(losses)

    return run
