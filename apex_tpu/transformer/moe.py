"""Expert parallelism: mixture-of-experts with an ``expert`` mesh axis.

The reference has NO expert parallelism (SURVEY §3.3 — "EP: absent from
apex; leave extension point in mesh design"). This module fills that
extension point the TPU-native way — the GShard/Switch formulation whose
dispatch/combine are einsums (MXU work, XLA-fusable) and whose only
communication is one ``all_to_all`` pair over the ``expert`` axis (ICI).

Design (top-1 switch routing, Fedus et al. 2021; GShard dispatch algebra,
Lepikhin et al. 2020):

- every shard routes its local tokens over ALL ``num_experts`` experts;
- dispatch tensor [tokens, E, C] scatters tokens into per-expert capacity
  slots; tokens over capacity are dropped (their combine weight is 0 and the
  residual path carries them — standard switch behavior);
- ``all_to_all`` sends each expert's slots to the shard that owns it, local
  expert MLPs run on [E_local, shards*C, H], and the inverse ``all_to_all``
  brings results home for the weighted combine.

Single-shard (no mesh axis) degenerates to the same math without the
all_to_alls, so the layer is testable on one device and parity-testable
against its sharded self.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm import AXIS_EXPERT

__all__ = ["MoEMLP", "top1_routing", "top2_routing", "router_z_loss"]


def _scatter_to_slots(mask, pos, gate, capacity):
    """(dispatch, combine) [T,E,C] for one routing choice: ``mask`` [T,E]
    marks each token's expert, ``pos`` [T,E] its queue position there (only
    the masked entry meaningful), ``gate`` [T] its combine weight. Tokens at
    pos >= capacity are dropped (dispatch row zero)."""
    keep = (pos < capacity).astype(jnp.float32) * mask         # [T, E]
    p = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)         # [T]
    dispatch = keep[:, :, None] * jax.nn.one_hot(p, capacity)[:, None, :]
    return dispatch, dispatch * gate[:, None, None]


def top1_routing(router_logits, num_experts: int, capacity: int):
    """Switch top-1 router → (dispatch [T,E,C], combine [T,E,C], aux_loss).

    aux_loss is the switch load-balancing loss (mean_prob · mean_assignment
    · E), reference formulation from the Switch paper.
    """
    T = router_logits.shape[0]
    probs = jax.nn.softmax(jnp.asarray(router_logits, jnp.float32), axis=-1)
    expert_index = jnp.argmax(probs, axis=-1)                  # [T]
    expert_mask = jax.nn.one_hot(expert_index, num_experts)    # [T, E]

    # position of each token within its expert's queue (prefix count)
    position_in_expert = (jnp.cumsum(expert_mask, axis=0) - 1.0) * expert_mask
    gate = jnp.sum(probs * expert_mask, axis=-1)               # [T]
    dispatch, combine = _scatter_to_slots(expert_mask, position_in_expert,
                                          gate, capacity)

    # load-balancing aux loss
    density = jnp.mean(expert_mask, axis=0)                    # [E]
    density_proxy = jnp.mean(probs, axis=0)                    # [E]
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


def router_z_loss(router_logits):
    """ST-MoE router z-loss (Zoph et al. 2022): mean(logsumexp(logits)²).
    Keeps router logits small so the fp32 softmax stays well-conditioned.
    When adding it to the objective yourself, ~1e-3 is the paper's weight;
    through ``MoEMLP(router_z_weight=...)`` it is folded into the returned
    aux and therefore ALSO scaled by the caller's aux weight — see the
    ``router_z_weight`` field doc."""
    lse = jax.nn.logsumexp(jnp.asarray(router_logits, jnp.float32), axis=-1)
    return jnp.mean(lse ** 2)


def top2_routing(router_logits, num_experts: int, capacity: int):
    """GShard top-2 router → (dispatch [T,E,C], combine [T,E,C], aux_loss).

    Each token goes to its two highest-probability experts with combine
    weights renormalized over the pair (GShard, Lepikhin et al. 2020).
    Capacity is filled by all first choices before any second choice (the
    GShard ordering: second choices are the first dropped under pressure).
    aux_loss uses the FIRST-choice assignment density, the standard
    formulation shared with switch.
    """
    T = router_logits.shape[0]
    probs = jax.nn.softmax(jnp.asarray(router_logits, jnp.float32), axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)                          # [T]
    mask1 = jax.nn.one_hot(idx1, num_experts)                  # [T, E]
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)                      # [T]
    mask2 = jax.nn.one_hot(idx2, num_experts)

    p1 = jnp.sum(probs * mask1, axis=-1)                       # [T]
    # from the top-1-masked probs: a saturated softmax (p1 == 1 exactly)
    # leaves probs_wo1 all-zero and argmax would alias expert 0 — p2 == 0
    # then zeroes mask2 so no phantom second choice is dispatched and w1
    # renormalizes to 1
    p2 = jnp.sum(probs_wo1 * mask2, axis=-1)
    mask2 = mask2 * (p2 > 0.0).astype(jnp.float32)[:, None]
    denom = jnp.maximum(p1 + p2, 1e-9)
    w1, w2 = p1 / denom, p2 / denom

    # queue positions: every first choice precedes every second choice
    pos1 = (jnp.cumsum(mask1, axis=0) - 1.0) * mask1           # [T, E]
    count1 = jnp.sum(mask1, axis=0)                            # [E]
    pos2 = ((jnp.cumsum(mask2, axis=0) - 1.0) + count1[None, :]) * mask2

    d1, c1 = _scatter_to_slots(mask1, pos1, w1, capacity)
    d2, c2 = _scatter_to_slots(mask2, pos2, w2, capacity)
    # a slot is owned by exactly one (token, choice): positions are disjoint
    dispatch = d1 + d2
    combine = c1 + c2

    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert-parallel MLP block.

    ``num_experts`` total experts; inside ``shard_map`` over ``axis_name``
    each shard holds ``num_experts // axis_size`` of them. Outside a mesh
    (``axis_name=None`` or unbound) all experts are local — identical math.

    ``__call__(x[T, H]) -> (y[T, H], aux_loss)``; callers add
    ``aux_weight * aux_loss`` to their objective.
    """

    hidden: int
    intermediate: int
    num_experts: int
    capacity_factor: float = 1.25
    router_top_k: int = 1          # 1 = switch, 2 = GShard top-2
    # ST-MoE z-loss weight RELATIVE to the load-balancing term: the layer
    # returns aux = lb_aux + router_z_weight * z_loss and the caller scales
    # the whole thing by its aux weight. For an objective weighting of
    # aux_weight=1e-2 on lb and the paper's 1e-3 on z, set
    # router_z_weight=0.1.
    router_z_weight: float = 0.0
    axis_name: Optional[str] = AXIS_EXPERT
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _axis_size(self) -> int:
        if self.axis_name is None:
            return 1
        try:
            return int(lax.psum(1, self.axis_name))
        except NameError:  # axis not bound: single-shard math
            return 1

    @nn.compact
    def __call__(self, x):
        T, H = x.shape
        E = self.num_experts
        ep = self._axis_size()
        if E % ep:
            raise ValueError(f"num_experts={E} not divisible by expert-"
                             f"parallel size {ep}")
        e_local = E // ep
        if self.router_top_k not in (1, 2):
            raise ValueError(
                f"router_top_k must be 1 (switch) or 2 (GShard top-2), "
                f"got {self.router_top_k}")
        # capacity per expert per shard (scaled by top_k: each token takes
        # router_top_k slots on average), padded to a multiple of 4 sublanes
        C = max(4, int(self.capacity_factor * self.router_top_k * T / E
                       + 0.5))
        C = (C + 3) // 4 * 4

        router = nn.Dense(E, dtype=jnp.float32,
                          param_dtype=self.param_dtype, name="router")
        logits = router(jnp.asarray(x, jnp.float32))
        routing = top1_routing if self.router_top_k == 1 else top2_routing
        dispatch, combine, aux = routing(logits, E, C)
        if self.router_z_weight:
            aux = aux + self.router_z_weight * router_z_loss(logits)
        dispatch = jnp.asarray(dispatch, x.dtype)

        # scatter tokens into expert slots: [E, C, H]
        slots = jnp.einsum("tec,th->ech", dispatch, x,
                           preferred_element_type=jnp.float32)
        slots = jnp.asarray(slots, x.dtype)

        if ep > 1:
            # [E, C, H] → [ep, e_local, C, H] —a2a→ local experts' slots
            # from every shard: [ep, e_local, C, H] → [e_local, ep*C, H]
            slots = slots.reshape(ep, e_local, C, H)
            slots = lax.all_to_all(slots, self.axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
            slots = jnp.moveaxis(slots, 0, 1).reshape(e_local, ep * C, H)
        else:
            slots = slots.reshape(e_local, C, H)

        # local expert MLPs, batched over the expert dim (one big MXU GEMM)
        w1 = self.param("w1", nn.initializers.normal(stddev=0.02),
                        (e_local, H, self.intermediate), self.param_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (e_local, self.intermediate), self.param_dtype)
        w2 = self.param("w2", nn.initializers.normal(stddev=0.02),
                        (e_local, self.intermediate, H), self.param_dtype)
        b2 = self.param("b2", nn.initializers.zeros,
                        (e_local, H), self.param_dtype)
        h = jnp.einsum("esh,ehi->esi", slots, jnp.asarray(w1, slots.dtype),
                       preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h + b1[:, None, :], approximate=False)
        h = jnp.asarray(h, slots.dtype)
        out = jnp.einsum("esi,eih->esh", h, jnp.asarray(w2, slots.dtype),
                         preferred_element_type=jnp.float32)
        out = jnp.asarray(out + b2[:, None, :], x.dtype)

        if ep > 1:
            out = out.reshape(e_local, ep, C, H)
            out = jnp.moveaxis(out, 1, 0)              # [ep, e_local, C, H]
            out = lax.all_to_all(out, self.axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
            out = out.reshape(E, C, H)
        else:
            out = out.reshape(E, C, H)

        y = jnp.einsum("tec,ech->th", jnp.asarray(combine, jnp.float32),
                       jnp.asarray(out, jnp.float32),
                       preferred_element_type=jnp.float32)
        return jnp.asarray(y, x.dtype), aux
