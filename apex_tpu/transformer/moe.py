"""Expert parallelism: mixture-of-experts with an ``expert`` mesh axis.

The reference has NO expert parallelism (SURVEY §3.3 — "EP: absent from
apex; leave extension point in mesh design"). This module fills that
extension point the TPU-native way — the GShard/Switch formulation whose
dispatch/combine are einsums (MXU work, XLA-fusable) and whose only
communication is one ``all_to_all`` pair over the ``expert`` axis (ICI).

Design (top-1 switch routing, Fedus et al. 2021; GShard dispatch algebra,
Lepikhin et al. 2020):

- every shard routes its local tokens over ALL ``num_experts`` experts;
- dispatch tensor [tokens, E, C] scatters tokens into per-expert capacity
  slots; tokens over capacity are dropped (their combine weight is 0 and the
  residual path carries them — standard switch behavior);
- ``all_to_all`` sends each expert's slots to the shard that owns it, local
  expert MLPs run on [E_local, shards*C, H], and the inverse ``all_to_all``
  brings results home for the weighted combine.

Single-shard (no mesh axis) degenerates to the same math without the
all_to_alls, so the layer is testable on one device and parity-testable
against its sharded self.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm import AXIS_EXPERT

__all__ = ["MoEMLP", "top1_routing"]


def top1_routing(router_logits, num_experts: int, capacity: int):
    """Switch top-1 router → (dispatch [T,E,C], combine [T,E,C], aux_loss).

    aux_loss is the switch load-balancing loss (mean_prob · mean_assignment
    · E), reference formulation from the Switch paper.
    """
    T = router_logits.shape[0]
    probs = jax.nn.softmax(jnp.asarray(router_logits, jnp.float32), axis=-1)
    expert_index = jnp.argmax(probs, axis=-1)                  # [T]
    expert_mask = jax.nn.one_hot(expert_index, num_experts)    # [T, E]

    # position of each token within its expert's queue (prefix count)
    position_in_expert = (jnp.cumsum(expert_mask, axis=0) - 1.0) * expert_mask
    in_capacity = (position_in_expert < capacity).astype(jnp.float32) \
        * expert_mask
    gate = jnp.sum(probs * expert_mask, axis=-1)               # [T]

    pos = jnp.sum(position_in_expert, axis=-1).astype(jnp.int32)  # [T]
    pos_one_hot = jax.nn.one_hot(pos, capacity)                # [T, C]
    dispatch = in_capacity[:, :, None] * pos_one_hot[:, None, :]  # [T,E,C]
    combine = dispatch * gate[:, None, None]

    # load-balancing aux loss
    density = jnp.mean(expert_mask, axis=0)                    # [E]
    density_proxy = jnp.mean(probs, axis=0)                    # [E]
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert-parallel MLP block.

    ``num_experts`` total experts; inside ``shard_map`` over ``axis_name``
    each shard holds ``num_experts // axis_size`` of them. Outside a mesh
    (``axis_name=None`` or unbound) all experts are local — identical math.

    ``__call__(x[T, H]) -> (y[T, H], aux_loss)``; callers add
    ``aux_weight * aux_loss`` to their objective.
    """

    hidden: int
    intermediate: int
    num_experts: int
    capacity_factor: float = 1.25
    axis_name: Optional[str] = AXIS_EXPERT
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _axis_size(self) -> int:
        if self.axis_name is None:
            return 1
        try:
            return int(lax.psum(1, self.axis_name))
        except NameError:  # axis not bound: single-shard math
            return 1

    @nn.compact
    def __call__(self, x):
        T, H = x.shape
        E = self.num_experts
        ep = self._axis_size()
        if E % ep:
            raise ValueError(f"num_experts={E} not divisible by expert-"
                             f"parallel size {ep}")
        e_local = E // ep
        # capacity per expert per shard, padded to a multiple of 4 sublanes
        C = max(4, int(self.capacity_factor * T / E + 0.5))
        C = (C + 3) // 4 * 4

        router = nn.Dense(E, dtype=jnp.float32,
                          param_dtype=self.param_dtype, name="router")
        dispatch, combine, aux = top1_routing(
            router(jnp.asarray(x, jnp.float32)), E, C)
        dispatch = jnp.asarray(dispatch, x.dtype)

        # scatter tokens into expert slots: [E, C, H]
        slots = jnp.einsum("tec,th->ech", dispatch, x,
                           preferred_element_type=jnp.float32)
        slots = jnp.asarray(slots, x.dtype)

        if ep > 1:
            # [E, C, H] → [ep, e_local, C, H] —a2a→ local experts' slots
            # from every shard: [ep, e_local, C, H] → [e_local, ep*C, H]
            slots = slots.reshape(ep, e_local, C, H)
            slots = lax.all_to_all(slots, self.axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
            slots = jnp.moveaxis(slots, 0, 1).reshape(e_local, ep * C, H)
        else:
            slots = slots.reshape(e_local, C, H)

        # local expert MLPs, batched over the expert dim (one big MXU GEMM)
        w1 = self.param("w1", nn.initializers.normal(stddev=0.02),
                        (e_local, H, self.intermediate), self.param_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (e_local, self.intermediate), self.param_dtype)
        w2 = self.param("w2", nn.initializers.normal(stddev=0.02),
                        (e_local, self.intermediate, H), self.param_dtype)
        b2 = self.param("b2", nn.initializers.zeros,
                        (e_local, H), self.param_dtype)
        h = jnp.einsum("esh,ehi->esi", slots, jnp.asarray(w1, slots.dtype),
                       preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h + b1[:, None, :], approximate=False)
        h = jnp.asarray(h, slots.dtype)
        out = jnp.einsum("esi,eih->esh", h, jnp.asarray(w2, slots.dtype),
                         preferred_element_type=jnp.float32)
        out = jnp.asarray(out + b2[:, None, :], x.dtype)

        if ep > 1:
            out = out.reshape(e_local, ep, C, H)
            out = jnp.moveaxis(out, 1, 0)              # [ep, e_local, C, H]
            out = lax.all_to_all(out, self.axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
            out = out.reshape(E, C, H)
        else:
            out = out.reshape(E, C, H)

        y = jnp.einsum("tec,ech->th", jnp.asarray(combine, jnp.float32),
                       jnp.asarray(out, jnp.float32),
                       preferred_element_type=jnp.float32)
        return jnp.asarray(y, x.dtype), aux
