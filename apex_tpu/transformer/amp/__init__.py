"""Transformer AMP bridge — parity with apex/transformer/amp/grad_scaler.py.

The reference subclasses ``torch.cuda.amp.GradScaler`` to add a ``min_scale``
floor (Megatron trains long enough that repeated overflows could otherwise
drive the scale to zero). Here the same variant is a thin construction over
:class:`apex_tpu.amp.scaler.LossScaler` / :func:`init_scaler`, exposing
torch-GradScaler argument names.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, init_scaler

__all__ = ["GradScaler", "grad_scaler_state"]


class GradScaler(LossScaler):
    """Min-scale-flooring GradScaler (reference:
    transformer/amp/grad_scaler.py — class GradScaler(torch GradScaler)).

    torch argument names: ``init_scale``, ``growth_factor``,
    ``backoff_factor``, ``growth_interval``, plus the Megatron ``min_scale``.
    ``growth_factor`` and ``1/backoff_factor`` must agree (the underlying
    schedule uses one symmetric factor, apex's 2x/0.5x).
    """

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000,
                 min_scale=1.0, enabled=True):
        if abs(growth_factor * backoff_factor - 1.0) > 1e-6:
            raise ValueError(
                "GradScaler requires backoff_factor == 1/growth_factor "
                f"(got {growth_factor} and {backoff_factor}); the scale "
                "schedule is symmetric like apex's 2x/0.5x")
        super().__init__(
            loss_scale="dynamic" if enabled else 1.0,
            init_scale=init_scale, scale_factor=growth_factor,
            scale_window=growth_interval, min_loss_scale=min_scale)

    # torch-GradScaler names
    def get_scale(self):
        return self.loss_scale()

    def scale(self, loss):
        return self.scale_loss(jnp.asarray(loss))

    def update(self):
        return self.update_scale()


def grad_scaler_state(init_scale=2.0 ** 16, growth_factor=2.0,
                      growth_interval=2000, min_scale=1.0, hysteresis=2):
    """Functional form: a ScalerState with the Megatron min-scale floor, for
    use inside make_train_step-style jitted steps. ``hysteresis=2`` is the
    Megatron DynamicGradScaler default: the first overflow since the last
    growth is tolerated, each further one backs the scale off (reference:
    csrc/update_scale_hysteresis.cu)."""
    return init_scaler("dynamic", init_scale=init_scale,
                       scale_factor=growth_factor,
                       scale_window=growth_interval,
                       min_loss_scale=min_scale, hysteresis=hysteresis)
