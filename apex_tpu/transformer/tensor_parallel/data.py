"""Data broadcast utilities.

Reference: apex/transformer/tensor_parallel/data.py — broadcast_data:
rank 0 of each tensor-parallel group broadcasts the tokenized batch so the
other TP ranks (which share the same data shard) don't each run the data
pipeline.

TPU design: a pytree map over the comm module's single broadcast primitive
(``comm.broadcast_from``); keys/dtype bookkeeping from the reference
collapses away.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_MODEL, broadcast_from

__all__ = ["broadcast_data"]


def broadcast_data(data, axis_name: str = AXIS_MODEL):
    """Every rank returns TP-rank-0's ``data`` pytree (reference:
    data.py — broadcast_data, minus the torch dtype/size plumbing)."""
    return jax.tree_util.tree_map(
        lambda x: broadcast_from(jnp.asarray(x), axis_name), data)
