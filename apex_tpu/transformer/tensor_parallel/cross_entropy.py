"""Vocab-parallel cross entropy.

Reference: apex/transformer/tensor_parallel/cross_entropy.py —
_VocabParallelCrossEntropy.forward/backward: with logits sharded over the
vocab dim across the TP group, compute per-token CE with three collectives
(max, predicted-logit, sum-exp) and a manual softmax-minus-onehot backward.

TPU version: same collectives over the ``model`` axis, inside shard_map.
The backward is a hand-written custom_vjp exactly like the reference — not
because autodiff can't differentiate the collectives, but because under
SPMD each rank holds a *replicated copy* of the loss, and the psum transpose
would sum the per-copy cotangents (a world-size overcount). The reference
has the same structure for the same reason: its backward uses only local
(softmax - onehot), no collective.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_MODEL

__all__ = ["vocab_parallel_cross_entropy"]


def _axis_info(axis_name):
    try:
        rank = jax.lax.axis_index(axis_name)
        world = jax.lax.psum(1, axis_name)
        return rank, world, True
    except NameError:
        return 0, 1, False


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis_name: str = AXIS_MODEL):
    """``vocab_parallel_logits``: [..., vocab/tp] shard-local; ``target``:
    [...] int global vocab ids. Returns per-token loss [...] in fp32."""
    loss, _ = _xent_fwd_impl(vocab_parallel_logits, target, label_smoothing,
                             axis_name)
    return loss


def _xent_fwd_impl(vocab_parallel_logits, target, label_smoothing, axis_name):
    logits = jnp.asarray(vocab_parallel_logits, jnp.float32)
    vocab_local = logits.shape[-1]
    rank, world, distributed = _axis_info(axis_name)

    # 1) global max for stability (reference: all_reduce MAX); pure
    # stabilizer, excluded from the grad path by construction of the vjp.
    local_max = jnp.max(logits, axis=-1)
    global_max = jax.lax.pmax(local_max, axis_name) if distributed \
        else local_max
    logits = logits - global_max[..., None]

    # 2) predicted logit: mask ids outside the local slice, psum
    first = rank * vocab_local
    local_t = target - first
    in_range = (local_t >= 0) & (local_t < vocab_local)
    safe = jnp.where(in_range, local_t, 0)
    pred = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    pred = jnp.where(in_range, pred, 0.0)
    if distributed:
        pred = jax.lax.psum(pred, axis_name)

    # 3) sum of exp across the vocab shards
    exp_logits = jnp.exp(logits)
    sum_exp = jnp.sum(exp_logits, axis=-1)
    if distributed:
        sum_exp = jax.lax.psum(sum_exp, axis_name)
    log_z = jnp.log(sum_exp)

    loss = log_z - pred
    vocab_size = vocab_local * world
    if label_smoothing > 0.0:
        # Reference (later vintages): smoothed loss mixes in the mean of all
        # log-probs: (1-eps)*nll + eps/K * sum_k (log_z - logit_k).
        sum_logits = jnp.sum(logits, axis=-1)
        if distributed:
            sum_logits = jax.lax.psum(sum_logits, axis_name)
        mean_log_probs = log_z - sum_logits / vocab_size
        loss = (1.0 - label_smoothing) * loss \
            + label_smoothing * mean_log_probs

    softmax_local = exp_logits / sum_exp[..., None]
    residuals = (softmax_local, in_range, safe, vocab_size,
                 jnp.zeros((0,), jnp.asarray(vocab_parallel_logits).dtype))
    return loss, residuals


def _xent_fwd(vocab_parallel_logits, target, label_smoothing, axis_name):
    return _xent_fwd_impl(vocab_parallel_logits, target, label_smoothing,
                          axis_name)


def _xent_bwd(label_smoothing, axis_name, residuals, g):
    softmax_local, in_range, safe, vocab_size, dtype_token = residuals
    in_dtype = dtype_token.dtype
    # reference backward: grad = (softmax - onehot_local) * g, all-local.
    onehot = jax.nn.one_hot(safe, softmax_local.shape[-1],
                            dtype=softmax_local.dtype)
    onehot = onehot * in_range[..., None]
    if label_smoothing > 0.0:
        target_dist = (1.0 - label_smoothing) * onehot \
            + label_smoothing / vocab_size
    else:
        target_dist = onehot
    grad = (softmax_local - target_dist) * g[..., None]
    tgt_cot = jnp.zeros(safe.shape, jax.dtypes.float0)
    return jnp.asarray(grad, in_dtype), tgt_cot


vocab_parallel_cross_entropy.defvjp(_xent_fwd, _xent_bwd)
