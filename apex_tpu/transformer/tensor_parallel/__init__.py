"""Tensor parallelism (reference: apex/transformer/tensor_parallel/)."""

from .cross_entropy import vocab_parallel_cross_entropy
from .data import broadcast_data
from .layers import (ColumnParallelLinear, RowParallelLinear,
                     VocabParallelEmbedding,
                     linear_with_grad_accumulation_and_async_allreduce)
from .mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from .random import (RNGStatesTracker, checkpoint, get_cuda_rng_tracker,
                     get_rng_tracker, model_parallel_cuda_manual_seed,
                     model_parallel_manual_seed)
from .utils import (VocabUtility, divide, ensure_divisibility,
                    split_tensor_along_last_dim)

__all__ = [
    "broadcast_data",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "linear_with_grad_accumulation_and_async_allreduce",
    "vocab_parallel_cross_entropy",
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "VocabUtility", "divide", "ensure_divisibility",
    "split_tensor_along_last_dim",
    "RNGStatesTracker", "get_rng_tracker", "model_parallel_manual_seed",
    "checkpoint", "get_cuda_rng_tracker", "model_parallel_cuda_manual_seed",
]
