"""TP-aware RNG + activation checkpointing.

Reference: apex/transformer/tensor_parallel/random.py — class
CudaRNGStatesTracker (named CUDA RNG streams; the 'model-parallel-rng' stream
is seeded differently per TP rank so dropout masks differ across TP shards),
``model_parallel_cuda_manual_seed``, and ``checkpoint`` (activation
checkpointing that snapshots/restores both RNG streams so recompute replays
identical dropout).

TPU design: JAX PRNG is functional — keys are values, not device state — so
the whole "fork and restore RNG state" problem the reference solves
disappears: ``jax.checkpoint`` replays dropout bit-identically because the
key is an argument. What remains worth keeping is the *naming* structure:
a tracker mapping stream names to keys, with the model-parallel stream
offset by TP rank (reference offsets seed by
``get_tensor_model_parallel_rank() * 2718``).
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_MODEL

__all__ = ["RNGStatesTracker", "get_rng_tracker",
           "model_parallel_manual_seed", "checkpoint",
           "get_cuda_rng_tracker", "model_parallel_cuda_manual_seed"]

_MODEL_PARALLEL_RNG = "model-parallel-rng"
_DEFAULT_RNG = "default-rng"


class RNGStatesTracker:
    """Named PRNG streams (reference: CudaRNGStatesTracker). ``add`` seeds a
    stream; ``fork`` yields its key and advances the stream so successive
    forks draw fresh randomness, mirroring how the reference's fork leaves
    the stream advanced after the region."""

    def __init__(self):
        self._keys: Dict[str, jax.Array] = {}

    def reset(self):
        self._keys.clear()

    def get_states(self):
        return dict(self._keys)

    def set_states(self, states):
        self._keys = dict(states)

    def add(self, name: str, seed: int):
        if name in self._keys:
            raise RuntimeError(f"rng stream {name} already initialized")
        self._keys[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        if name not in self._keys:
            raise RuntimeError(f"rng stream {name} is not initialized")
        key, nxt = jax.random.split(self._keys[name])
        self._keys[name] = nxt
        yield key


_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _TRACKER


def model_parallel_manual_seed(seed: int, tp_rank=None):
    """Seed both streams (reference: model_parallel_cuda_manual_seed):
    default stream = seed; model-parallel stream = seed + 2718 + tp_rank.
    ``tp_rank`` may be a traced axis_index inside shard_map; fold_in keeps
    that functional."""
    if tp_rank is None:
        try:
            tp_rank = jax.lax.axis_index(AXIS_MODEL)
        except NameError:
            tp_rank = 0
    _TRACKER.reset()
    _TRACKER.add(_DEFAULT_RNG, seed)
    key = jax.random.fold_in(jax.random.PRNGKey(seed + 2718),
                             jnp.asarray(tp_rank, jnp.uint32))
    _TRACKER._keys[_MODEL_PARALLEL_RNG] = key


def checkpoint(fn, *args, **kwargs):
    """Activation checkpointing (reference: tensor_parallel/random.py —
    checkpoint / class CheckpointFunction). ``jax.checkpoint`` recomputes the
    forward during backward; dropout replay is automatic since keys are
    arguments — no RNG snapshotting needed."""
    return jax.checkpoint(fn)(*args, **kwargs)


# Reference-named aliases so Megatron-style code ports unchanged.
get_cuda_rng_tracker = get_rng_tracker
model_parallel_cuda_manual_seed = model_parallel_manual_seed
