"""Megatron-style tensor-parallel layers.

Reference: apex/transformer/tensor_parallel/layers.py — ColumnParallelLinear,
RowParallelLinear, VocabParallelEmbedding,
linear_with_grad_accumulation_and_async_allreduce.

TPU design: flax modules that hold the SHARD-LOCAL parameter (out//tp or
in//tp) and are meant to run inside shard_map over the ``model`` axis; the
differentiable collectives come from mappings.py. Under plain pjit/GSPMD the
same math needs only PartitionSpec annotations — each module exposes its
sharding via ``kernel_partition_spec()`` for that path. The reference's async
allreduce-overlapped-with-wgrad trick (linear_with_grad_accumulation_and_
async_allreduce) is XLA's latency-hiding scheduler's job here; the function
exists for API parity and simply does the math.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu import comm
from apex_tpu.comm import AXIS_MODEL
from .mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
)
from .utils import divide

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding",
           "linear_with_grad_accumulation_and_async_allreduce"]


def _maybe_axis_index(axis_name: str):
    """Rank along ``axis_name`` when bound (inside shard_map), else 0."""
    try:
        return jax.lax.axis_index(axis_name)
    except NameError:
        return 0


def _sharded_init(init: Callable, axis_name: str):
    """Fold the TP rank into the rng so shards draw independent weights —
    the reference initializes the full weight and scatters
    (layers.py — _initialize_affine_weight_gpu uses the TP rng tracker)."""

    def wrapped(key, shape, dtype):
        idx = _maybe_axis_index(axis_name)
        key = jax.random.fold_in(key, idx) if not isinstance(idx, int) else key
        return init(key, shape, dtype)

    return wrapped


class ColumnParallelLinear(nn.Module):
    """Y = XA + b with A split column-wise: A = [A_1 .. A_p].

    Reference: tensor_parallel/layers.py — class ColumnParallelLinear.
    Input is replicated over the TP group (or sequence-sharded when
    ``sequence_parallel_enabled``); output is the local column block unless
    ``gather_output``.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel_enabled: bool = False
    axis_name: str = AXIS_MODEL
    world_size: Optional[int] = None
    # None → consult the O1 engine ('linear' is FP16_FUNCS); fp32 otherwise
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros

    def _world(self) -> int:
        return (self.world_size if self.world_size is not None
                else comm.axis_size(self.axis_name))

    @nn.compact
    def __call__(self, x):
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        world = self._world()
        out_local = divide(self.output_size, world)
        kernel = self.param("kernel",
                            _sharded_init(self.kernel_init, self.axis_name),
                            (self.input_size, out_local), self.param_dtype)
        if self.sequence_parallel_enabled and world > 1:
            # SP: activations arrive sequence-sharded; the all-gather here is
            # the fwd half of the split TP all-reduce (mappings — SP pair).
            x = gather_from_sequence_parallel_region(x, self.axis_name, 0)
        elif world > 1:
            x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        y = jnp.dot(jnp.asarray(x, dtype), jnp.asarray(kernel, dtype))
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (out_local,),
                              self.param_dtype)
            y = y + jnp.asarray(bias, dtype)
        if self.gather_output and world > 1:
            y = gather_from_tensor_model_parallel_region(y, self.axis_name, -1)
        return y

    def kernel_partition_spec(self) -> PartitionSpec:
        """The GSPMD half of SURVEY §3.3's TP mapping: under plain
        jit, annotate the FULL kernel with this spec (columns sharded
        over the TP axis) and XLA inserts the collectives mappings.py
        spells out. Consumed by examples/lm --partitioning gspmd."""
        return PartitionSpec(None, self.axis_name)


class RowParallelLinear(nn.Module):
    """Y = XA + b with A split row-wise; local matmuls partial-summed by an
    all-reduce (or reduce-scatter under SP).

    Reference: tensor_parallel/layers.py — class RowParallelLinear. Bias is
    added AFTER the reduction (on the full sum), as the reference does.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel_enabled: bool = False
    axis_name: str = AXIS_MODEL
    world_size: Optional[int] = None
    # None → consult the O1 engine ('linear' is FP16_FUNCS); fp32 otherwise
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros

    def _world(self) -> int:
        return (self.world_size if self.world_size is not None
                else comm.axis_size(self.axis_name))

    @nn.compact
    def __call__(self, x):
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        world = self._world()
        in_local = divide(self.input_size, world)
        kernel = self.param("kernel",
                            _sharded_init(self.kernel_init, self.axis_name),
                            (in_local, self.output_size), self.param_dtype)
        if not self.input_is_parallel and world > 1:
            from .mappings import scatter_to_tensor_model_parallel_region
            x = scatter_to_tensor_model_parallel_region(x, self.axis_name, -1)
        y = jnp.dot(jnp.asarray(x, dtype), jnp.asarray(kernel, dtype))
        if world > 1:
            if self.sequence_parallel_enabled:
                y = reduce_scatter_to_sequence_parallel_region(
                    y, self.axis_name, 0)
            else:
                y = reduce_from_tensor_model_parallel_region(y, self.axis_name)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.output_size,),
                              self.param_dtype)
            y = y + jnp.asarray(bias, dtype)
        return y

    def kernel_partition_spec(self) -> PartitionSpec:
        """GSPMD spec: rows (the contraction dim) sharded over the TP
        axis — XLA turns the partial products into the all-reduce the
        explicit path does via reduce_from_tensor_model_parallel_region.
        """
        return PartitionSpec(self.axis_name, None)


class VocabParallelEmbedding(nn.Module):
    """Embedding with the vocab dim sharded over the TP group.

    Reference: tensor_parallel/layers.py — class VocabParallelEmbedding:
    mask ids outside the local [first, last) range, look up with the offset
    subtracted, zero the masked rows, all-reduce the partial embeddings.
    """

    num_embeddings: int
    embedding_dim: int
    axis_name: str = AXIS_MODEL
    world_size: Optional[int] = None
    # None → activations in the embedding table's own dtype (embedding
    # lookups are not classified by the O1 tables)
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    embedding_init: Callable = nn.initializers.normal(stddev=0.02)

    def _world(self) -> int:
        return (self.world_size if self.world_size is not None
                else comm.axis_size(self.axis_name))

    @nn.compact
    def __call__(self, ids):
        world = self._world()
        vocab_local = divide(self.num_embeddings, world)
        table = self.param("embedding",
                           _sharded_init(self.embedding_init, self.axis_name),
                           (vocab_local, self.embedding_dim),
                           self.param_dtype)
        table = jnp.asarray(table, self.dtype)
        if world == 1:
            return jnp.take(table, ids, axis=0)
        rank = _maybe_axis_index(self.axis_name)
        first = rank * vocab_local
        local = ids - first
        in_range = (local >= 0) & (local < vocab_local)
        safe = jnp.where(in_range, local, 0)
        out = jnp.take(table, safe, axis=0)
        out = jnp.where(in_range[..., None], out, jnp.zeros_like(out))
        return reduce_from_tensor_model_parallel_region(out, self.axis_name)

    def kernel_partition_spec(self) -> PartitionSpec:
        """GSPMD spec: vocab rows sharded over the TP axis; XLA handles
        the out-of-shard lookups the explicit path masks by hand."""
        return PartitionSpec(self.axis_name, None)


def linear_with_grad_accumulation_and_async_allreduce(
        x, weight, bias=None, gradient_accumulation_fusion: bool = False,
        async_grad_allreduce: bool = False,
        sequence_parallel_enabled: bool = False,
        axis_name: str = AXIS_MODEL):
    """API-parity shim (reference: layers.py —
    linear_with_grad_accumulation_and_async_allreduce / class
    LinearWithGradAccumulationAndAsyncCommunication). On TPU the
    wgrad/allreduce overlap and the fp32 grad accumulation are XLA's
    latency-hiding scheduler's and donation's job; the semantics reduce to:
    gather under SP, matmul, and — when async_grad_allreduce — the identity-
    fwd/psum-bwd mapping on the input."""
    if sequence_parallel_enabled:
        x = gather_from_sequence_parallel_region(x, axis_name, 0)
    elif async_grad_allreduce:
        x = copy_to_tensor_model_parallel_region(x, axis_name)
    # same O1-engine consultation as the module classes above ('linear' is
    # FP16_FUNCS): the Megatron shim must not silently diverge from them
    from apex_tpu.amp.autocast import cast_op_inputs

    x, weight = cast_op_inputs("linear", x, weight)
    y = jnp.dot(x, weight)
    if bias is not None:
        y = y + jnp.asarray(bias, y.dtype)
    return y
