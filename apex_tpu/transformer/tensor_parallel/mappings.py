"""Differentiable TP collective "mappings".

Reference: apex/transformer/tensor_parallel/mappings.py — the four Megatron
autograd pairs (_CopyToModelParallelRegion, _ReduceFromModelParallelRegion,
_ScatterToModelParallelRegion, _GatherFromModelParallelRegion) plus the
sequence-parallel pair (reduce_scatter_to_sequence_parallel_region /
gather_from_sequence_parallel_region, vintage >=2022).

TPU design: each pair is a jax.custom_vjp whose forward/backward are the dual
collectives over the named ``model`` axis; they are meaningful inside
shard_map (where the axis is bound) — under plain pjit/GSPMD these mappings
collapse into sharding constraints and are not needed, which is the idiomatic
default path (SURVEY §3.3). All functions take the values shard-local.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_MODEL

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
]


# --------------------------------------------------------- identity fwd / psum bwd
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name: str = AXIS_MODEL):
    """f: identity; df: all-reduce. Placed where a replicated activation
    enters a column-parallel matmul (reference — _CopyToModelParallelRegion).
    """
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# --------------------------------------------------------- psum fwd / identity bwd
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name: str = AXIS_MODEL):
    """f: all-reduce; df: identity. Output of a row-parallel matmul
    (reference — _ReduceFromModelParallelRegion)."""
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# --------------------------------------------------------- split fwd / gather bwd
def _local_slice(x, axis_name, axis):
    rank = jax.lax.axis_index(axis_name)
    world = jax.lax.psum(1, axis_name)
    chunk = x.shape[axis] // world
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_tensor_model_parallel_region(x, axis_name: str = AXIS_MODEL,
                                            axis: int = -1):
    """f: keep own last-dim slice; df: all-gather
    (reference — _ScatterToModelParallelRegion)."""
    return _local_slice(x, axis_name, axis if axis >= 0 else x.ndim + axis)


def _scatter_fwd(x, axis_name, axis):
    a = axis if axis >= 0 else x.ndim + axis
    return _local_slice(x, axis_name, a), None


def _scatter_bwd(axis_name, axis, _, g):
    a = axis if axis >= 0 else g.ndim + axis
    return (jax.lax.all_gather(g, axis_name, axis=a, tiled=True),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# --------------------------------------------------------- gather fwd / split bwd
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_tensor_model_parallel_region(x, axis_name: str = AXIS_MODEL,
                                             axis: int = -1):
    """f: all-gather along ``axis``; df: keep own slice
    (reference — _GatherFromModelParallelRegion)."""
    a = axis if axis >= 0 else x.ndim + axis
    return jax.lax.all_gather(x, axis_name, axis=a, tiled=True)


def _gather_fwd(x, axis_name, axis):
    a = axis if axis >= 0 else x.ndim + axis
    return jax.lax.all_gather(x, axis_name, axis=a, tiled=True), None


def _gather_bwd(axis_name, axis, _, g):
    a = axis if axis >= 0 else g.ndim + axis
    return (_local_slice(g, axis_name, a),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ------------------------------------------------------------ sequence parallel
def scatter_to_sequence_parallel_region(x, axis_name: str = AXIS_MODEL,
                                        axis: int = 0):
    """Split along the sequence dim over the TP group (embedding output →
    SP region). bwd: all-gather. Same pair as scatter_to_…(axis=seq)."""
    return scatter_to_tensor_model_parallel_region(x, axis_name, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis_name: str = AXIS_MODEL,
                                               axis: int = 0):
    """f: reduce-scatter along sequence dim; df: all-gather. This is the SP
    split of the TP all-reduce (reference mappings.py —
    _ReduceScatterToSequenceParallelRegion); fwd+bwd together cost the same
    bytes as one all-reduce, the Megatron-SP trick."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def _rs_fwd(x, axis_name, axis):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True), None


def _rs_bwd(axis_name, axis, _, g):
    return (jax.lax.all_gather(g, axis_name, axis=axis, tiled=True),)


reduce_scatter_to_sequence_parallel_region.defvjp(_rs_fwd, _rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis_name: str = AXIS_MODEL,
                                         axis: int = 0):
    """f: all-gather along sequence dim; df: reduce-scatter (reference —
    _GatherFromSequenceParallelRegion with tensor_parallel_output_grad=True).
    """
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def _gs_fwd(x, axis_name, axis):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True), None


def _gs_bwd(axis_name, axis, _, g):
    return (jax.lax.psum_scatter(g, axis_name, scatter_dimension=axis,
                                 tiled=True),)


gather_from_sequence_parallel_region.defvjp(_gs_fwd, _gs_bwd)
