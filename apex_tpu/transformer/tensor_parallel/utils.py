"""TP helpers. Reference: apex/transformer/utils.py (divide,
ensure_divisibility) and apex/transformer/tensor_parallel/utils.py
(split_tensor_along_last_dim, class VocabUtility)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ensure_divisibility", "divide", "split_tensor_along_last_dim",
           "VocabUtility"]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Reference: tensor_parallel/utils.py — split_tensor_along_last_dim.
    (jnp.split copies under jit either way; the reference's
    contiguous_split_chunks flag has no XLA meaning.)"""
    last = tensor.shape[-1]
    divide(last, num_partitions)
    return jnp.split(tensor, num_partitions, axis=-1)


class VocabUtility:
    """Reference: tensor_parallel/utils.py — class VocabUtility: the
    [first, last) vocab slice owned by a TP rank."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_vocab_size,
                                                  rank, world_size=None):
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size, rank,
                                           world_size):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)
