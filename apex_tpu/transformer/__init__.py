"""apex_tpu.transformer — Megatron-style model parallelism on a TPU mesh.

Reference: apex/transformer/ (parallel_state, tensor_parallel,
pipeline_parallel, functional). The process-group bookkeeping becomes a
jax.sharding.Mesh with named axes; TP mappings become differentiable
collectives (shard_map) or sharding constraints (pjit); PP becomes
collective-permute pipelining over the ``pipe`` axis.
"""

from . import amp  # noqa: F401
from . import context_parallel  # noqa: F401
from . import enums  # noqa: F401
from . import functional  # noqa: F401
from . import log_util  # noqa: F401
from . import moe  # noqa: F401
from . import parallel_state  # noqa: F401
from . import pipeline_parallel  # noqa: F401
from . import tensor_parallel  # noqa: F401
from . import testing  # noqa: F401
from .context_parallel import (ring_attention, ulysses_attention,  # noqa: F401
                               zigzag_inverse, zigzag_order)
from .enums import AttnMaskType, AttnType, LayerType, ModelType  # noqa: F401
from .log_util import get_transformer_logger, set_logging_level  # noqa: F401
from .moe import MoEMLP  # noqa: F401

__all__ = ["amp", "log_util", "testing",
           "get_transformer_logger", "set_logging_level",
           "parallel_state", "tensor_parallel", "pipeline_parallel",
           "functional", "enums", "context_parallel", "moe", "AttnMaskType",
           "AttnType", "LayerType", "ModelType", "ring_attention", "zigzag_order", "zigzag_inverse",
           "ulysses_attention", "MoEMLP"]
