"""Stage-to-stage activation transfer.

Reference: apex/transformer/pipeline_parallel/p2p_communication.py —
_communicate builds torch.distributed batched isend/irecv between pipeline
ranks, with shape pre-exchange; send_forward / recv_forward / send_backward /
recv_backward / combined variants wrap it.

TPU design: there is no user-level P2P — the primitive is
``jax.lax.ppermute`` over the ``pipe`` mesh axis (a collective-permute rides
ICI directly). Because XLA programs are SPMD, "send to next stage" and
"receive from previous stage" are ONE op executed by all ranks, so the
send/recv split of the reference collapses: ``send_forward`` IS
``recv_forward`` on the other end. Shapes are static under jit, so the
reference's tensor-shape pre-exchange has no equivalent. These wrappers exist
so schedule code and ported Megatron code keep their vocabulary; the real
schedule (schedules.py) calls them inside shard_map.
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu.comm import AXIS_PIPE, axis_size

__all__ = ["send_forward", "send_backward", "send_forward_recv_backward",
           "send_backward_recv_forward", "shift_right", "shift_left"]


def _ring_perm(n: int, step: int):
    return [(i, (i + step) % n) for i in range(n)]


def shift_right(x, axis_name: str = AXIS_PIPE, n: Optional[int] = None):
    """Move each stage's value to the NEXT stage (forward activations).
    Stage 0 receives stage n-1's value (callers mask it or feed fresh
    microbatches there)."""
    n = n if n is not None else axis_size(axis_name)
    return jax.lax.ppermute(x, axis_name, _ring_perm(n, +1))


def shift_left(x, axis_name: str = AXIS_PIPE, n: Optional[int] = None):
    """Move each stage's value to the PREVIOUS stage (backward grads)."""
    n = n if n is not None else axis_size(axis_name)
    return jax.lax.ppermute(x, axis_name, _ring_perm(n, -1))


# Reference-vocabulary aliases. In SPMD one collective is both sides.
def send_forward(output_tensor, axis_name: str = AXIS_PIPE):
    """= recv_forward on the next stage."""
    return shift_right(output_tensor, axis_name)


def send_backward(input_tensor_grad, axis_name: str = AXIS_PIPE):
    """= recv_backward on the previous stage."""
    return shift_left(input_tensor_grad, axis_name)


def send_forward_recv_backward(output_tensor, axis_name: str = AXIS_PIPE):
    """In SPMD both directions are independent collectives; autodiff of
    shift_right already produces the shift_left of grads, so the fused
    send/recv pairs of the reference are only needed as vocabulary."""
    return shift_right(output_tensor, axis_name)


def send_backward_recv_forward(input_tensor_grad, axis_name: str = AXIS_PIPE):
    return shift_left(input_tensor_grad, axis_name)
