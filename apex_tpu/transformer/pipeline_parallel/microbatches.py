"""Microbatch calculators.

Reference: apex/transformer/pipeline_parallel/microbatches.py —
build_num_microbatches_calculator, ConstantNumMicroBatches,
RampupBatchsizeNumMicroBatches. Pure bookkeeping; ported semantics, no torch.
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["build_num_microbatches_calculator", "ConstantNumMicroBatches",
           "RampupBatchsizeNumMicroBatches"]


class ConstantNumMicroBatches:
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        micro_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_times_dp != 0:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"micro_batch*dp ({micro_times_dp})")
        self.num_micro_batches = global_batch_size // micro_times_dp
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check=True):
        pass


class RampupBatchsizeNumMicroBatches:
    """Linear global-batch ramp: start → global over ramp_samples
    (reference: RampupBatchsizeNumMicroBatches.update)."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        diff = global_batch_size - start_batch_size
        if diff % batch_size_increment != 0:
            raise ValueError("ramp range not divisible by increment")
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments else 0)
        self.update(0, False)

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check=True):
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment) \
                if self.rampup_samples_per_increment else 0
            self.current_global_batch_size = min(
                self.global_batch_size,
                self.start_batch_size + steps * self.batch_size_increment)
        if consistency_check and (self.current_global_batch_size %
                                  self.micro_batch_times_data_parallel_size):
            raise ValueError("current global batch not divisible by micro*dp")
        self.num_micro_batches = (self.current_global_batch_size //
                                  self.micro_batch_times_data_parallel_size)


def build_num_microbatches_calculator(
        rank: int = 0,
        rampup_batch_size: Optional[Sequence[int]] = None,
        global_batch_size: int = 1,
        micro_batch_size: int = 1,
        data_parallel_size: int = 1):
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(global_batch_size, micro_batch_size,
                                       data_parallel_size)
    start, incr, samples = (int(rampup_batch_size[0]),
                            int(rampup_batch_size[1]),
                            int(rampup_batch_size[2]))
    return RampupBatchsizeNumMicroBatches(
        start, incr, samples, global_batch_size, micro_batch_size,
        data_parallel_size)
