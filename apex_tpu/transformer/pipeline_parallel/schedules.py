"""Pipeline-parallel schedules.

Reference: apex/transformer/pipeline_parallel/schedules/ —
forward_backward_no_pipelining, forward_backward_pipelining_without_
interleaving (1F1B: warmup/steady/cooldown over torch.distributed P2P),
forward_backward_pipelining_with_interleaving (virtual stages), selected by
get_forward_backward_func.

TPU design — collective-permute pipelining. The reference hand-schedules
1F1B because torch autograd is eager and NCCL P2P must be interleaved by
hand. Under XLA the whole pipeline is ONE program: microbatches flow through
stages via ``ppermute`` over the ``pipe`` axis inside ``lax.scan``, and the
BACKWARD schedule is derived by autodiff (the transpose of a ppermute scan is
the reversed-perm scan — exactly the cooldown/steady/warmup mirror), with
XLA's latency-hiding scheduler overlapping the permutes with compute. Memory
behavior matches GPipe fill-drain; wrap ``stage_fn`` in ``jax.checkpoint``
(tensor_parallel.random.checkpoint) to get the activation-memory profile the
reference gets from its schedule.

Interleaving (virtual pipeline): each device holds ``v`` model chunks;
logical stage ``s = chunk * pp + rank`` (the reference's round-robin model
split). The carry holds ``v`` in-flight buffers; each tick applies every
local chunk and rotates, promoting a buffer to the next chunk when it wraps
past the last device.

The stage functions here are FUNCTIONAL: ``stage_fn(chunk_params, x) -> y``
with identical activation shapes at every boundary (the reference has the
same constraint — tensor_shape is fixed in its _communicate).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_PIPE

__all__ = ["pipeline_apply", "make_pipeline_loss_fn",
           "forward_backward_no_pipelining",
           "forward_backward_pipelining_without_interleaving",
           "forward_backward_pipelining_with_interleaving",
           "get_forward_backward_func", "build_model"]


def _chunk(tree, c):
    return jax.tree_util.tree_map(lambda l: l[c], tree)


def _pipe_scan(stage_fn, local_chunks, microbatches, *, axis_name: str,
               num_stages: int, num_chunks: int):
    """Run the rotation; returns per-tick last-chunk outputs [T, ...] (the
    finished-microbatch stream on the last stage) and T."""
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    L = num_stages * num_chunks
    T = M + L - 1

    x0 = jnp.zeros_like(microbatches[0])
    bufs0 = jnp.stack([x0] * num_chunks)  # [v, ...] in-flight buffers

    def tick(bufs, t):
        # stage 0 (device 0, chunk 0) consumes the microbatch stream at
        # compute time; drain ticks re-feed the last microbatch harmlessly
        # (those copies never reach the last logical stage within T ticks).
        fresh = microbatches[jnp.clip(t, 0, M - 1)]
        x0 = jnp.where(rank == 0, fresh, bufs[0])
        xs = bufs.at[0].set(x0)
        ys = jnp.stack([
            stage_fn(_chunk(local_chunks, c) if num_chunks > 1
                     else local_chunks, xs[c])
            for c in range(num_chunks)])
        shifted = jax.lax.ppermute(
            ys, axis_name, [(i, (i + 1) % num_stages)
                            for i in range(num_stages)])
        # device 0: buffer c+1 is promoted from chunk c leaving the last
        # device (roll); its buffer 0 slot is dead — overwritten by the
        # stream next tick. other devices: same chunk, previous device.
        bufs_next = jnp.where(rank == 0, jnp.roll(shifted, 1, axis=0),
                              shifted)
        return bufs_next, ys[num_chunks - 1]

    _, outs = jax.lax.scan(tick, bufs0, jnp.arange(T))
    return outs, T


def pipeline_apply(stage_fn: Callable, local_chunks, microbatches, *,
                   axis_name: str = AXIS_PIPE, num_stages: int,
                   num_chunks: int = 1, broadcast: bool = True):
    """Forward the microbatch stream [M, ...] through the pipeline; returns
    outputs [M, ...]. Valid natively on the last stage; with ``broadcast``
    the outputs are psum-replicated to every stage (zeros elsewhere + psum).
    """
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    L = num_stages * num_chunks
    outs, _ = _pipe_scan(stage_fn, local_chunks, microbatches,
                         axis_name=axis_name, num_stages=num_stages,
                         num_chunks=num_chunks)
    outs = outs[L - 1:]  # microbatch m finishes at tick m + L - 1
    if broadcast:
        is_last = (rank == num_stages - 1)
        masked = jnp.where(is_last, outs, jnp.zeros_like(outs))
        # value-only broadcast: psum under stop_gradient so the transpose
        # doesn't multiply the (replicated) cotangent by num_stages; the
        # grad path stays the local masked term.
        outs = masked + jax.lax.stop_gradient(
            jax.lax.psum(masked, axis_name) - masked)
    return outs


def make_pipeline_loss_fn(stage_fn: Callable, loss_fn: Callable, *,
                          axis_name: str = AXIS_PIPE, num_stages: int,
                          num_chunks: int = 1):
    """Build ``fn(local_chunks, (microbatches, targets)) -> scalar loss``.

    This is the composition point with apex_tpu.amp.make_train_step: the
    pipelined model becomes an ordinary loss function whose params are the
    stage-local chunk stack (shard params [L, ...] over the pipe axis with
    in_spec P('pipe') and they arrive here as [v, ...]).

    ``loss_fn(output, target) -> scalar`` (per-microbatch mean).
    """

    def fn(local_chunks, batch):
        microbatches, targets = batch
        rank = jax.lax.axis_index(axis_name)
        M = microbatches.shape[0]
        L = num_stages * num_chunks
        outs, T = _pipe_scan(stage_fn, local_chunks, microbatches,
                             axis_name=axis_name, num_stages=num_stages,
                             num_chunks=num_chunks)

        def per_tick(t):
            m = jnp.clip(t - (L - 1), 0, M - 1)
            l = loss_fn(outs[t], targets[m])
            valid = (t >= L - 1) & (rank == num_stages - 1)
            return jnp.where(valid, l, 0.0)

        total = jnp.sum(jax.vmap(per_tick)(jnp.arange(T)))
        # replicate the scalar across stages so every rank's train step sees
        # the same loss (grads for other stages' params flow via ppermute's
        # transpose regardless). The psum is value-only (stop_gradient):
        # under check_rep=False its transpose would psum the replicated
        # cotangent and scale every grad by num_stages.
        total = total + jax.lax.stop_gradient(
            jax.lax.psum(total, axis_name) - total)
        return total / M

    return fn


# ------------------------------------------------------- reference-shaped API
def forward_backward_no_pipelining(loss_fn, params, microbatches, targets,
                                   grad: bool = True):
    """Grad accumulation over microbatches, no pipe axis (reference:
    schedules/fwd_bwd_no_pipelining.py). ``loss_fn(params, mb, tgt)``."""

    def body(carry, mt):
        mb, tgt = mt
        if grad:
            l, g = jax.value_and_grad(loss_fn)(params, mb, tgt)
        else:
            l, g = loss_fn(params, mb, tgt), None
        loss_acc, grad_acc = carry
        if grad:
            grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, g)
        return (loss_acc + l, grad_acc), None

    M = microbatches.shape[0]
    zero_g = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.result_type(p)), params)
    (loss, grads), _ = jax.lax.scan(body, (0.0, zero_g),
                                    (microbatches, targets))
    if grad:
        grads = jax.tree_util.tree_map(lambda g: g / M, grads)
        return loss / M, grads
    return loss / M


def forward_backward_pipelining_without_interleaving(
        stage_fn, loss_fn, local_params, microbatches, targets, *,
        axis_name: str = AXIS_PIPE, num_stages: int, grad: bool = True):
    """1F1B-equivalent (reference: schedules/fwd_bwd_pipelining_without_
    interleaving.py). Must run inside shard_map with the pipe axis bound."""
    pl = make_pipeline_loss_fn(stage_fn, loss_fn, axis_name=axis_name,
                               num_stages=num_stages, num_chunks=1)
    if grad:
        return jax.value_and_grad(pl)(local_params, (microbatches, targets))
    return pl(local_params, (microbatches, targets))


def forward_backward_pipelining_with_interleaving(
        stage_fn, loss_fn, local_chunks, microbatches, targets, *,
        axis_name: str = AXIS_PIPE, num_stages: int, num_chunks: int,
        grad: bool = True):
    """Interleaved virtual-pipeline schedule (reference:
    schedules/fwd_bwd_pipelining_with_interleaving.py)."""
    pl = make_pipeline_loss_fn(stage_fn, loss_fn, axis_name=axis_name,
                               num_stages=num_stages, num_chunks=num_chunks)
    if grad:
        return jax.value_and_grad(pl)(local_chunks, (microbatches, targets))
    return pl(local_chunks, (microbatches, targets))


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: int = 1):
    """Reference: schedules/__init__.py — get_forward_backward_func picks the
    schedule from (vpp, pp)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None \
                and virtual_pipeline_model_parallel_size > 1:
            return functools.partial(
                forward_backward_pipelining_with_interleaving,
                num_stages=pipeline_model_parallel_size,
                num_chunks=virtual_pipeline_model_parallel_size)
        return functools.partial(
            forward_backward_pipelining_without_interleaving,
            num_stages=pipeline_model_parallel_size)
    return forward_backward_no_pipelining


def build_model(model_provider_func: Callable, *,
                num_stages: int, num_chunks: int = 1,
                wrap_with_ddp: bool = False, **provider_kwargs) -> list:
    """Reference: schedules/common.py — build_model(model_provider_func,
    wrap_with_ddp, virtual_pipeline_model_parallel_size): calls the provider
    once per virtual-stage chunk on this rank with pre_process/post_process
    flags marking the true pipeline ends, and returns the chunk list.

    Functional analogue: the provider is called once per LOGICAL stage
    ``s = chunk * num_stages + rank`` (the reference's round-robin split)
    and returns that chunk's params (or an inited module/any pytree). The
    result is RANK-MAJOR — entry ``rank * num_chunks + chunk`` — so that
    stacking leaf-wise and sharding over the pipe axis with in_spec
    P('pipe') lands each rank exactly its own [num_chunks, ...] block, in
    the local-chunk order pipeline_apply/make_pipeline_loss_fn expect.
    ``wrap_with_ddp`` is accepted for signature parity and ignored:
    gradient averaging is composed in amp.make_train_step
    (grad_average_axis), not by wrapping modules.
    """
    L = num_stages * num_chunks
    models = []
    for rank in range(num_stages):
        for chunk in range(num_chunks):
            s = chunk * num_stages + rank
            models.append(model_provider_func(
                pre_process=(s == 0), post_process=(s == L - 1),
                **provider_kwargs))
    return models
