"""Pipeline-parallel schedules.

Reference: apex/transformer/pipeline_parallel/schedules/ —
forward_backward_no_pipelining, forward_backward_pipelining_without_
interleaving (1F1B: warmup/steady/cooldown over torch.distributed P2P),
forward_backward_pipelining_with_interleaving (virtual stages), selected by
get_forward_backward_func.

TPU design — collective-permute pipelining, two complementary paths:

1. **Autodiff path** (:func:`make_pipeline_loss_fn` / :func:`pipeline_apply`):
   microbatches flow through stages via ``ppermute`` inside ``lax.scan``; the
   backward schedule is derived by autodiff (the transpose of a ppermute scan
   is the reversed-perm scan — the cooldown/steady/warmup mirror). Composes
   as an ordinary differentiable loss with amp.make_train_step, but memory
   behaves like GPipe fill-drain: scan residuals grow with microbatch count.
2. **Hand-scheduled 1F1B** (:func:`forward_backward_1f1b`): one forward-only
   scan interleaving a fwd stage step and a bwd stage step per tick, with a
   static-depth saved-input FIFO and in-backward recompute — activation
   memory O(pp), flat in M, the reference schedule's actual memory profile.
   Returns (loss, grads) like the reference's fwd-bwd functions.

Interleaving (virtual pipeline): each device holds ``v`` model chunks;
logical stage ``s = chunk * pp + rank`` (the reference's round-robin model
split). The carry holds ``v`` in-flight buffers; each tick applies every
local chunk and rotates, promoting a buffer to the next chunk when it wraps
past the last device.

The stage functions here are FUNCTIONAL: ``stage_fn(chunk_params, x) -> y``
with identical activation shapes at every boundary (the reference has the
same constraint — tensor_shape is fixed in its _communicate).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_PIPE

__all__ = ["pipeline_apply", "make_pipeline_loss_fn",
           "forward_backward_1f1b",
           "forward_backward_no_pipelining",
           "forward_backward_pipelining_without_interleaving",
           "forward_backward_pipelining_with_interleaving",
           "get_forward_backward_func", "build_model"]


def _chunk(tree, c):
    return jax.tree_util.tree_map(lambda l: l[c], tree)


def _pipe_scan(stage_fn, local_chunks, microbatches, *, axis_name: str,
               num_stages: int, num_chunks: int):
    """Run the rotation; returns per-tick last-chunk outputs [T, ...] (the
    finished-microbatch stream on the last stage) and T."""
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    L = num_stages * num_chunks
    T = M + L - 1

    x0 = jnp.zeros_like(microbatches[0])
    bufs0 = jnp.stack([x0] * num_chunks)  # [v, ...] in-flight buffers

    def tick(bufs, t):
        # stage 0 (device 0, chunk 0) consumes the microbatch stream at
        # compute time; drain ticks re-feed the last microbatch harmlessly
        # (those copies never reach the last logical stage within T ticks).
        fresh = microbatches[jnp.clip(t, 0, M - 1)]
        x0 = jnp.where(rank == 0, fresh, bufs[0])
        xs = bufs.at[0].set(x0)
        ys = jnp.stack([
            stage_fn(_chunk(local_chunks, c) if num_chunks > 1
                     else local_chunks, xs[c])
            for c in range(num_chunks)])
        shifted = jax.lax.ppermute(
            ys, axis_name, [(i, (i + 1) % num_stages)
                            for i in range(num_stages)])
        # device 0: buffer c+1 is promoted from chunk c leaving the last
        # device (roll); its buffer 0 slot is dead — overwritten by the
        # stream next tick. other devices: same chunk, previous device.
        bufs_next = jnp.where(rank == 0, jnp.roll(shifted, 1, axis=0),
                              shifted)
        return bufs_next, ys[num_chunks - 1]

    _, outs = jax.lax.scan(tick, bufs0, jnp.arange(T))
    return outs, T


def pipeline_apply(stage_fn: Callable, local_chunks, microbatches, *,
                   axis_name: str = AXIS_PIPE, num_stages: int,
                   num_chunks: int = 1, broadcast: bool = True):
    """Forward the microbatch stream [M, ...] through the pipeline; returns
    outputs [M, ...]. Valid natively on the last stage; with ``broadcast``
    the outputs are psum-replicated to every stage (zeros elsewhere + psum).
    """
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    L = num_stages * num_chunks
    outs, _ = _pipe_scan(stage_fn, local_chunks, microbatches,
                         axis_name=axis_name, num_stages=num_stages,
                         num_chunks=num_chunks)
    outs = outs[L - 1:]  # microbatch m finishes at tick m + L - 1
    if broadcast:
        is_last = (rank == num_stages - 1)
        masked = jnp.where(is_last, outs, jnp.zeros_like(outs))
        # value-only broadcast: psum under stop_gradient so the transpose
        # doesn't multiply the (replicated) cotangent by num_stages; the
        # grad path stays the local masked term.
        outs = masked + jax.lax.stop_gradient(
            jax.lax.psum(masked, axis_name) - masked)
    return outs


def make_pipeline_loss_fn(stage_fn: Callable, loss_fn: Callable, *,
                          axis_name: str = AXIS_PIPE, num_stages: int,
                          num_chunks: int = 1, remat: bool = False):
    """Build ``fn(local_chunks, (microbatches, targets)) -> scalar loss``.

    This is the composition point with apex_tpu.amp.make_train_step: the
    pipelined model becomes an ordinary loss function whose params are the
    stage-local chunk stack (shard params [L, ...] over the pipe axis with
    in_spec P('pipe') and they arrive here as [v, ...]).

    ``loss_fn(output, target) -> scalar`` (per-microbatch mean).

    ``remat=True`` wraps the stage function in ``jax.checkpoint``
    (reference: tensor_parallel/random.py — checkpoint), shrinking this
    autodiff path's saved residuals to the stage BOUNDARY activations:
    memory still grows with the microbatch count (the scan carry is saved
    per tick — use :func:`forward_backward_1f1b` for the O(pp) profile)
    but the per-tick constant drops from all stage internals to one
    boundary tensor.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def fn(local_chunks, batch):
        microbatches, targets = batch
        rank = jax.lax.axis_index(axis_name)
        M = microbatches.shape[0]
        L = num_stages * num_chunks
        outs, T = _pipe_scan(stage_fn, local_chunks, microbatches,
                             axis_name=axis_name, num_stages=num_stages,
                             num_chunks=num_chunks)

        # loss only on the M finished-microbatch ticks (static slice), not
        # all T — warmup/drain garbage never reaches loss_fn
        del T
        losses = jax.vmap(loss_fn)(outs[L - 1:], targets)        # [M]
        total = jnp.where(rank == num_stages - 1, jnp.sum(losses), 0.0)
        # replicate the scalar across stages so every rank's train step sees
        # the same loss (grads for other stages' params flow via ppermute's
        # transpose regardless). The psum is value-only (stop_gradient):
        # under check_vma=False its transpose would psum the replicated
        # cotangent and scale every grad by num_stages.
        total = total + jax.lax.stop_gradient(
            jax.lax.psum(total, axis_name) - total)
        return total / M

    return fn


def forward_backward_1f1b(stage_fn: Callable, loss_fn: Callable,
                          local_params, microbatches, targets, *,
                          axis_name: str = AXIS_PIPE, num_stages: int,
                          num_chunks: int = 1, loss_scale=None,
                          cotangent_dtype=jnp.float32,
                          loss_params=None,
                          return_input_cotangents: bool = False):
    """Hand-scheduled 1F1B with activation memory flat in the microbatch
    count — the TRUE memory profile of the reference schedules
    (apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py AND, via ``num_chunks>1``,
    fwd_bwd_pipelining_with_interleaving.py; SURVEY P24, §4.5).

    The autodiff path (:func:`make_pipeline_loss_fn` under ``jax.grad``)
    saves residuals for every scan tick, so its activation memory grows with
    the microbatch count M — exactly what 1F1B exists to prevent. This
    function instead writes the backward schedule BY HAND inside one
    forward-only ``lax.scan``. With ``v = num_chunks`` model chunks per
    device (logical stage ``s = chunk·pp + rank``, L = v·pp stages total):

    - each tick runs one forward stage step PER LOCAL CHUNK (microbatch
      stream + ppermute rotation, as _pipe_scan) AND one backward stage
      step per local chunk (cotangent counter-rotated with a reverse
      ppermute) — the steady-state interleaved-1F1B cadence. Ticks
      outside a chunk's validity window (warmup of later stages, drain)
      skip the stage forward / vjp recompute via ``lax.cond``, so the
      pipeline-bubble slots cost a branch rather than a full stage step;
    - the only per-microbatch state is one saved-input FIFO PER CHUNK of
      static depth 2·L−1 — independent of M. Stage internals are
      recomputed in the backward via ``jax.vjp`` (the reference trains big
      models with the same full-recompute policy:
      tensor_parallel/random.py — checkpoint);
    - microbatch m's forward runs on logical stage s at tick m+s; its
      backward on stage s at tick m + 2(L−1) − s; total ticks
      T = M + 2(L−1). The loss cotangent is seeded at the last logical
      stage (chunk v−1, device pp−1) in the same tick its forward
      completes (1F1B's defining "backward as early as possible");
    - chunk promotion: a chunk-c output leaving the last device becomes
      the chunk-c+1 input on device 0 (forward roll); a chunk-c cotangent
      leaving device 0 becomes the chunk-c−1 cotangent on the last device
      (backward counter-roll).

    Returns ``(mean_loss, grads)`` like the reference's fwd-bwd functions —
    grads for THIS device's chunk params (stacked ``[v, ...]`` when v>1),
    loss replicated across stages. Must run inside shard_map with the pipe
    axis bound. ``loss_scale`` (optional, traced ok) scales the seeded
    cotangent — the amp composition point (scale here, unscale via
    amp.unscale on the returned grads).

    ``cotangent_dtype`` (default fp32) is the dtype the boundary cotangent
    is rotated and promoted in: the loss-grad seed enters the ring at full
    precision and the where/zero masking arithmetic is exact. Stage
    outputs are coerced to the MICROBATCH dtype (the boundary type-
    stability contract), so each stage's vjp consumes the cotangent in
    that dtype and half-precision stages still round once per stage —
    what fp32 rotation removes is the second rounding at every device
    boundary and any range clipping of the scaled seed under fp16. Pass
    ``None`` to rotate in the activation dtype (round-2 behavior,
    cheapest on ICI bandwidth).

    In-flight bound: each device holds v FIFOs of depth 2L−1 ≈ 2·v²·pp
    saved microbatch inputs (v=1: 2·pp−1) — a ~2v× constant over the
    reference's interleaved in-flight bound (its warmup runs forwards at
    double rate; a uniform-tick collective-permute schedule spends that in
    exchange for one traced program) but flat in M, which is the property
    that matters at scale.

    Two hooks support the reference's pre_process/post_process pattern
    (an embedding feeding the pipe, a head+loss after it — schedules/
    common.py builds stage models with exactly these ends):

    - ``loss_params``: when given, ``loss_fn(y, target, loss_params)`` and
      the return becomes ``(loss, grads, aux)`` with
      ``aux["loss_param_grads"]`` — the head/criterion parameter grads,
      accumulated on the last stage and psum-replicated across the pipe
      axis (the analogue of Megatron's embedding-grad all-reduce between
      the end stages), scaled by ``loss_scale`` like the stage grads.
    - ``return_input_cotangents``: adds ``aux["input_cotangents"]`` —
      d(mean loss · scale)/d(microbatches), ``[M, ...]`` in
      ``cotangent_dtype``, psum-replicated across the pipe axis. Feed it
      to the vjp of whatever produced the stream (the embedding) to
      complete the backward outside the scan. Costs one O(M) buffer —
      the embedding-input stream the first stage holds anyway.
    """
    S = num_stages
    v = num_chunks
    if S <= 1:
        raise ValueError("forward_backward_1f1b needs num_stages > 1; use "
                         "forward_backward_no_pipelining")
    if v < 1:
        raise ValueError(f"num_chunks must be >= 1, got {v}")
    L = S * v
    rank = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    Q = 2 * L - 1
    T = M + 2 * (L - 1)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    x0 = jnp.zeros_like(microbatches[0])
    cdt = x0.dtype if cotangent_dtype is None else cotangent_dtype
    fwd_buf0 = jnp.stack([x0] * v)                    # [v, ...] in-flight
    cot_buf0 = jnp.zeros((v,) + x0.shape, cdt)        # [v, ...] cotangents
    queue0 = jnp.zeros((v, Q) + x0.shape, x0.dtype)   # per-chunk FIFOs
    grads0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), local_params)
    lgrads0 = (None if loss_params is None else jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), loss_params))
    dxs0 = (jnp.zeros((M,) + x0.shape, cdt)
            if return_input_cotangents else None)
    scale = 1.0 if loss_scale is None else loss_scale

    def cparams(c):
        return _chunk(local_params, c) if v > 1 else local_params

    def tick(carry, t):
        fwd_buf, cot_buf, queue, grads, lgrads, dxs, loss_acc = carry

        # ---- forward units: every local chunk steps once. Chunk 0 on
        # device 0 consumes the microbatch stream at compute time. Ticks
        # outside a chunk's validity window (warmup of later stages, drain)
        # SKIP the stage compute via lax.cond — round-2 weak #4c charged
        # the uniform-tick schedule a fully-masked recompute per idle slot;
        # now idle slots cost a branch, not a stage forward.
        fresh = microbatches[jnp.clip(t, 0, M - 1)]
        ys = []
        for c in range(v):
            x_in = fwd_buf[c]
            if c == 0:
                x_in = jnp.where(rank == 0, fresh, x_in)
            m_f = t - (c * S + rank)
            valid_f = (m_f >= 0) & (m_f < M)
            y_c = jax.lax.cond(
                valid_f,
                lambda a: jnp.asarray(stage_fn(a[0], a[1]), x0.dtype),
                lambda a: jnp.zeros(x0.shape, x0.dtype),
                (cparams(c), x_in))
            ys.append(y_c)
            queue = queue.at[c, t % Q].set(x_in)

        # ---- loss + seed cotangent, ONE loss eval (value_and_grad), run
        # only on the last stage's completion ticks: chunk v-1 on device
        # S-1 finishes microbatch t-(L-1) this tick and seeds its backward
        # the same tick.
        tgt = targets[jnp.clip(t - (L - 1), 0, M - 1)]
        need_loss = (rank == S - 1) & (t >= L - 1) & (t - (L - 1) < M)
        if loss_params is None:
            def _loss_seed(a):
                l, dly = jax.value_and_grad(loss_fn)(a[0], a[1])
                return jnp.asarray(l, jnp.float32), dly

            l, dly = jax.lax.cond(
                need_loss, _loss_seed,
                lambda a: (jnp.float32(0.0), jnp.zeros_like(a[0])),
                (ys[v - 1], tgt))
        else:
            def _loss_seed(a):
                l, (dly, dlp) = jax.value_and_grad(
                    loss_fn, argnums=(0, 2))(a[0], a[1], loss_params)
                return (jnp.asarray(l, jnp.float32), dly,
                        jax.tree_util.tree_map(
                            lambda d: jnp.asarray(d, jnp.float32), dlp))

            l, dly, dlp = jax.lax.cond(
                need_loss, _loss_seed,
                lambda a: (jnp.float32(0.0), jnp.zeros_like(a[0]),
                           jax.tree_util.tree_map(
                               lambda p: jnp.zeros(p.shape, jnp.float32),
                               loss_params)),
                (ys[v - 1], tgt))
            lgrads = jax.tree_util.tree_map(
                lambda g, d: g + d.astype(g.dtype), lgrads, dlp)
        loss_acc = loss_acc + l

        # ---- backward units: chunk c runs microbatch m_b's backward;
        # idle ticks skip the vjp recompute entirely (lax.cond)
        new_cots = []
        for c in range(v):
            m_b = t - 2 * (L - 1) + c * S + rank
            valid_b = (m_b >= 0) & (m_b < M)
            cot_in = cot_buf[c]
            if c == v - 1:
                cot_in = jnp.where(
                    rank == S - 1,
                    jnp.asarray(dly, cdt) * jnp.asarray(scale, cdt),
                    cot_in)
            # saved input for m_b: written at tick m_b + s = t - 2(L-1-s)
            slot = (t - 2 * (L - 1) + 2 * (c * S + rank)) % Q
            x_saved = jax.lax.dynamic_index_in_dim(
                queue[c], slot, axis=0, keepdims=False)

            def _do_bwd(a):
                p_c, x_s, ci = a
                # recompute-in-backward: vjp re-runs the stage forward
                # (reference: full recompute via tensor_parallel checkpoint)
                _, vjp_fn = jax.vjp(stage_fn, p_c, x_s)
                # stage outputs are coerced to x0.dtype in the forward
                # cond, so the vjp cotangent dtype is statically known
                dparams, dx = vjp_fn(jnp.asarray(ci, x0.dtype))
                return dparams, jnp.asarray(dx, cdt)

            def _skip_bwd(a):
                p_c, x_s, _ = a
                return (jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.asarray(p).dtype),
                    p_c), jnp.zeros(x0.shape, cdt))

            dparams, dx = jax.lax.cond(valid_b, _do_bwd, _skip_bwd,
                                       (cparams(c), x_saved, cot_in))
            if v > 1:
                grads = jax.tree_util.tree_map(
                    lambda g, d: g.at[c].add(d.astype(g.dtype)),
                    grads, dparams)
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, d: g + d.astype(g.dtype), grads, dparams)
            new_cots.append(dx)
            if c == 0 and return_input_cotangents:
                # stage 0's dx IS d(loss·scale)/d(microbatch m_b) — the
                # cotangent the stream producer (embedding) needs
                take = valid_b & (rank == 0)
                idx = jnp.clip(m_b, 0, M - 1)
                dxs = dxs.at[idx].set(
                    jnp.where(take, dx, dxs[idx]))

        # ---- rotations (+ chunk promotion rolls at the ring seams)
        shifted = jax.lax.ppermute(jnp.stack(ys), axis_name, fwd_perm)
        fwd_buf = jnp.where(rank == 0, jnp.roll(shifted, 1, axis=0),
                            shifted)
        cshift = jax.lax.ppermute(jnp.stack(new_cots), axis_name, bwd_perm)
        cot_buf = jnp.where(rank == S - 1, jnp.roll(cshift, -1, axis=0),
                            cshift)
        return (fwd_buf, cot_buf, queue, grads, lgrads, dxs, loss_acc), None

    carry0 = (fwd_buf0, cot_buf0, queue0, grads0, lgrads0, dxs0,
              jnp.float32(0.0))
    (_, _, _, grads, lgrads, dxs, loss), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))

    grads = jax.tree_util.tree_map(lambda g: g / M, grads)
    loss = loss / M
    # replicate the scalar loss across stages (value-only)
    loss = loss + jax.lax.stop_gradient(
        jax.lax.psum(loss, axis_name) - loss)
    if loss_params is None and not return_input_cotangents:
        return loss, grads
    aux = {}
    if loss_params is not None:
        # head/criterion grads live on the last stage only — replicate via
        # psum (Megatron's end-stage embedding-grad all-reduce analogue);
        # scale like the seeded stage grads so amp.unscale treats them
        # uniformly.
        aux["loss_param_grads"] = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis_name)
            * jnp.asarray(scale, g.dtype) / M,
            lgrads)
    if return_input_cotangents:
        aux["input_cotangents"] = jax.lax.psum(dxs, axis_name) / M
    return loss, grads, aux


# ------------------------------------------------------- reference-shaped API
def forward_backward_no_pipelining(loss_fn, params, microbatches, targets,
                                   grad: bool = True, accum_dtype=None):
    """Grad accumulation over microbatches, no pipe axis (reference:
    schedules/fwd_bwd_no_pipelining.py). ``loss_fn(params, mb, tgt)``.

    ``accum_dtype`` (default: each param's own dtype) is the accumulator
    dtype across microbatches — pass ``jnp.float32`` under half-precision
    params so the accumulation matches the 1F1B path's fp32 buffers (the
    reference's main_grads are fp32 for the same reason; half-dtype
    accumulation over many microbatches measurably degrades training)."""

    def body(carry, mt):
        mb, tgt = mt
        if grad:
            l, g = jax.value_and_grad(loss_fn)(params, mb, tgt)
        else:
            l, g = loss_fn(params, mb, tgt), None
        loss_acc, grad_acc = carry
        if grad:
            grad_acc = jax.tree_util.tree_map(
                lambda a, gg: a + gg.astype(a.dtype), grad_acc, g)
        return (loss_acc + l, grad_acc), None

    M = microbatches.shape[0]
    zero_g = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape,
                            accum_dtype or jnp.result_type(p)), params)
    (loss, grads), _ = jax.lax.scan(body, (0.0, zero_g),
                                    (microbatches, targets))
    if grad:
        grads = jax.tree_util.tree_map(lambda g: g / M, grads)
        return loss / M, grads
    return loss / M


def forward_backward_pipelining_without_interleaving(
        stage_fn, loss_fn, local_params, microbatches, targets, *,
        axis_name: str = AXIS_PIPE, num_stages: int, grad: bool = True,
        loss_scale=None, cotangent_dtype=jnp.float32):
    """1F1B (reference: schedules/fwd_bwd_pipelining_without_
    interleaving.py). Must run inside shard_map with the pipe axis bound.

    ``grad=True`` runs the hand-scheduled :func:`forward_backward_1f1b`
    (O(pp) activation memory, matching the reference's memory profile);
    ``grad=False`` is a plain pipelined forward. For a differentiable loss
    to hand to ``jax.grad``/amp.make_train_step, use
    :func:`make_pipeline_loss_fn` — its fill-drain autodiff memory grows
    with the microbatch count, the documented trade for whole-step jit
    composability.
    """
    if grad:
        return forward_backward_1f1b(stage_fn, loss_fn, local_params,
                                     microbatches, targets,
                                     axis_name=axis_name,
                                     num_stages=num_stages,
                                     loss_scale=loss_scale,
                                     cotangent_dtype=cotangent_dtype)
    pl = make_pipeline_loss_fn(stage_fn, loss_fn, axis_name=axis_name,
                               num_stages=num_stages, num_chunks=1)
    return pl(local_params, (microbatches, targets))


def forward_backward_pipelining_with_interleaving(
        stage_fn, loss_fn, local_chunks, microbatches, targets, *,
        axis_name: str = AXIS_PIPE, num_stages: int, num_chunks: int,
        grad: bool = True, loss_scale=None, cotangent_dtype=jnp.float32):
    """Interleaved virtual-pipeline schedule (reference:
    schedules/fwd_bwd_pipelining_with_interleaving.py — which is itself a
    1F1B schedule over virtual stages).

    ``grad=True`` runs the hand-scheduled :func:`forward_backward_1f1b`
    with ``num_chunks>1`` — activation memory flat in the microbatch
    count, the reference's interleaved memory profile (VERDICT round-2
    missing #1 closed). ``grad=False`` is a plain pipelined forward via
    the autodiff path.
    """
    if grad:
        return forward_backward_1f1b(stage_fn, loss_fn, local_chunks,
                                     microbatches, targets,
                                     axis_name=axis_name,
                                     num_stages=num_stages,
                                     num_chunks=num_chunks,
                                     loss_scale=loss_scale,
                                     cotangent_dtype=cotangent_dtype)
    pl = make_pipeline_loss_fn(stage_fn, loss_fn, axis_name=axis_name,
                               num_stages=num_stages, num_chunks=num_chunks)
    return pl(local_chunks, (microbatches, targets))


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: int = 1):
    """Reference: schedules/__init__.py — get_forward_backward_func picks the
    schedule from (vpp, pp)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None \
                and virtual_pipeline_model_parallel_size > 1:
            return functools.partial(
                forward_backward_pipelining_with_interleaving,
                num_stages=pipeline_model_parallel_size,
                num_chunks=virtual_pipeline_model_parallel_size)
        return functools.partial(
            forward_backward_pipelining_without_interleaving,
            num_stages=pipeline_model_parallel_size)
    return forward_backward_no_pipelining


def build_model(model_provider_func: Callable, *,
                num_stages: int, num_chunks: int = 1,
                wrap_with_ddp: bool = False, **provider_kwargs) -> list:
    """Reference: schedules/common.py — build_model(model_provider_func,
    wrap_with_ddp, virtual_pipeline_model_parallel_size): calls the provider
    once per virtual-stage chunk on this rank with pre_process/post_process
    flags marking the true pipeline ends, and returns the chunk list.

    Functional analogue: the provider is called once per LOGICAL stage
    ``s = chunk * num_stages + rank`` (the reference's round-robin split)
    and returns that chunk's params (or an inited module/any pytree). The
    result is RANK-MAJOR — entry ``rank * num_chunks + chunk`` — so that
    stacking leaf-wise and sharding over the pipe axis with in_spec
    P('pipe') lands each rank exactly its own [num_chunks, ...] block, in
    the local-chunk order pipeline_apply/make_pipeline_loss_fn expect.
    ``wrap_with_ddp`` is accepted for signature parity and ignored:
    gradient averaging is composed in amp.make_train_step
    (grad_average_axis), not by wrapping modules.
    """
    L = num_stages * num_chunks
    models = []
    for rank in range(num_stages):
        for chunk in range(num_chunks):
            s = chunk * num_stages + rank
            models.append(model_provider_func(
                pre_process=(s == 0), post_process=(s == L - 1),
                **provider_kwargs))
    return models
