"""Pipeline-parallel utilities.

Reference: apex/transformer/pipeline_parallel/utils.py —
``get_ltor_masks_and_position_ids`` (the Megatron GPT input-prep helper) and
the microbatch bookkeeping accessors. TPU notes: the mask is built with
broadcasted iota (static shapes, jit-friendly) rather than materialized
tril; loss-mask zeroing of EOD/pad tokens and the attention-mask reset at
EOD boundaries keep the reference's semantics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["get_ltor_masks_and_position_ids", "listify_model"]


def get_ltor_masks_and_position_ids(
        data: jnp.ndarray,
        eod_token: int,
        reset_position_ids: bool = False,
        reset_attention_mask: bool = False,
        eod_mask_loss: bool = False):
    """Left-to-right (causal) masks + position ids for token batch ``data``
    of shape [batch, seq].

    Returns (attention_mask, loss_mask, position_ids) with the reference's
    conventions: attention_mask is boolean [batch, 1, seq, seq] where True
    means MASKED OUT (the reference computes ``< 0.5`` on a tril of ones and
    passes the result to masked softmax); loss_mask is float [batch, seq]
    with 0.0 at EOD positions when ``eod_mask_loss``; position_ids reset to
    zero after each EOD when ``reset_position_ids``.
    """
    batch, seq = data.shape

    q_pos = jnp.arange(seq)[:, None]
    k_pos = jnp.arange(seq)[None, :]
    causal = k_pos <= q_pos                                # [seq, seq] visible

    # Document-boundary handling: token j is visible to token i only if no
    # EOD lies strictly between them (reference loops over eod indices and
    # zeroes the block-lower-triangle; cumulative-EOD-count equality is the
    # vectorized identical condition).
    if reset_attention_mask or reset_position_ids:
        is_eod = (data == eod_token)
        # doc id of each position = number of EODs strictly before it
        doc = jnp.cumsum(is_eod, axis=-1) - jnp.where(is_eod, 1, 0)
    if reset_attention_mask:
        same_doc = doc[:, :, None] == doc[:, None, :]      # [b, seq, seq]
        visible = causal[None] & same_doc
    else:
        visible = jnp.broadcast_to(causal[None], (batch, seq, seq))

    attention_mask = ~visible[:, None, :, :]               # True = masked

    loss_mask = jnp.ones((batch, seq), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(seq)[None, :], (batch, seq))
    if reset_position_ids:
        # Reference semantics: for each EOD at index i, positions from i+1
        # onward subtract (i+1) — the EOD itself keeps its position in the
        # prior document. doc_start[p] = 1 + (last EOD strictly before p),
        # or 0 in the first document.
        pos = jnp.arange(seq)[None, :]
        prev_is_eod = jnp.pad(is_eod[:, :-1], ((0, 0), (1, 0)))
        # lax.cummax == jnp.maximum.accumulate, but exists on every jax
        # this library targets (the ufunc .accumulate methods do not);
        # axis must be non-negative for the primitive
        doc_start = jax.lax.cummax(
            jnp.where(prev_is_eod, pos, 0), axis=1)
        position_ids = position_ids - doc_start

    return attention_mask, loss_mask, position_ids


def listify_model(model) -> list:
    """Reference: utils.listify_model — schedules accept a module or a list
    of virtual-stage chunks; normalize to a list."""
    return model if isinstance(model, list) else [model]
