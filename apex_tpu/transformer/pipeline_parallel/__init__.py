"""Pipeline parallelism (reference: apex/transformer/pipeline_parallel/)."""

from .microbatches import build_num_microbatches_calculator
from .p2p_communication import (send_backward, send_backward_recv_forward,
                                send_forward, send_forward_recv_backward,
                                shift_left, shift_right)
from .schedules import (build_model, forward_backward_1f1b,
                        forward_backward_no_pipelining,
                        forward_backward_pipelining_with_interleaving,
                        forward_backward_pipelining_without_interleaving,
                        get_forward_backward_func, make_pipeline_loss_fn,
                        pipeline_apply)
from .utils import get_ltor_masks_and_position_ids, listify_model

__all__ = [
    "build_num_microbatches_calculator",
    "send_forward", "send_backward", "send_forward_recv_backward",
    "send_backward_recv_forward", "shift_right", "shift_left",
    "pipeline_apply", "make_pipeline_loss_fn",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "get_forward_backward_func", "build_model",
    "get_ltor_masks_and_position_ids", "listify_model",
]
