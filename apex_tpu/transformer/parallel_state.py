"""Model-parallel state: mesh construction + rank/size accessors.

Reference: apex/transformer/parallel_state.py — initialize_model_parallel
builds torch.distributed process groups for data/tensor/pipeline/embedding
parallelism from (tp, pp, vpp) sizes and keeps them in module globals with
get_*_group/_rank/_world_size accessors.

TPU design: there are no communicator objects to build — a
``jax.sharding.Mesh`` with named axes IS the group structure, and XLA derives
every "group" (the set of devices varying along one axis) from the axis name.
So initialize_model_parallel constructs one mesh with axes
``('data', 'pipe', 'model')`` (outermost-first: DP rides DCN across slices,
TP stays on ICI neighbours — the analogue of apex nesting NCCL TP groups
inside a node) and installs it via apex_tpu.comm.set_mesh. The accessors keep
the reference's names so Megatron-style callers port unchanged; "rank in
group" accessors are trace-time values (``jax.lax.axis_index``) when called
inside shard_map, and host-side lookups otherwise.

Virtual pipeline (interleaved 1F1B) carries no group state — it is a loop
structure over model chunks (see pipeline_parallel.schedules) — so vpp here
is just a recorded size, exactly like the reference's
``_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE`` global.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from apex_tpu import comm
from apex_tpu.comm import AXIS_DATA, AXIS_MODEL, AXIS_PIPE

__all__ = [
    "initialize_model_parallel", "model_parallel_is_initialized",
    "destroy_model_parallel", "get_mesh",
    "get_tensor_model_parallel_axis", "get_pipeline_model_parallel_axis",
    "get_data_parallel_axis",
    "get_tensor_model_parallel_world_size", "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_world_size",
    "get_pipeline_model_parallel_rank",
    "get_data_parallel_world_size", "get_data_parallel_rank",
    "get_virtual_pipeline_model_parallel_world_size",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "is_pipeline_first_stage", "is_pipeline_last_stage",
]

_INITIALIZED = False
_VPP_WORLD: Optional[int] = None
_VPP_RANK: Optional[int] = None


def initialize_model_parallel(
        tensor_model_parallel_size_: int = 1,
        pipeline_model_parallel_size_: int = 1,
        virtual_pipeline_model_parallel_size_: Optional[int] = None,
        *,
        devices: Optional[Sequence] = None,
        **_ignored):
    """Build and install the global mesh.

    Mirrors the reference signature (parallel_state.py —
    initialize_model_parallel(tensor_model_parallel_size_,
    pipeline_model_parallel_size_, virtual_pipeline_model_parallel_size_)).
    Data-parallel size is derived: world // (tp * pp), reference behavior.
    """
    global _INITIALIZED, _VPP_WORLD, _VPP_RANK
    devices = list(devices if devices is not None else jax.devices())
    tp = int(tensor_model_parallel_size_)
    pp = int(pipeline_model_parallel_size_)
    world = len(devices)
    if world % (tp * pp) != 0:
        raise RuntimeError(
            f"world size {world} not divisible by tp({tp}) * pp({pp})")
    dp = world // (tp * pp)
    mesh = comm.make_mesh({AXIS_DATA: dp, AXIS_PIPE: pp, AXIS_MODEL: tp},
                          devices=devices)
    comm.set_mesh(mesh)
    _INITIALIZED = True
    _VPP_WORLD = (int(virtual_pipeline_model_parallel_size_)
                  if virtual_pipeline_model_parallel_size_ else None)
    _VPP_RANK = 0 if _VPP_WORLD else None
    return mesh


def model_parallel_is_initialized() -> bool:
    return _INITIALIZED


def destroy_model_parallel():
    """Reference: parallel_state.destroy_model_parallel resets globals."""
    global _INITIALIZED, _VPP_WORLD, _VPP_RANK
    comm.reset_mesh()
    _INITIALIZED = False
    _VPP_WORLD = None
    _VPP_RANK = None


def get_mesh():
    return comm.get_mesh()


# ------------------------------------------------------------------ axis names
def get_tensor_model_parallel_axis() -> str:
    return AXIS_MODEL


def get_pipeline_model_parallel_axis() -> str:
    return AXIS_PIPE


def get_data_parallel_axis() -> str:
    return AXIS_DATA


# ------------------------------------------------------------------ sizes/ranks
def _axis_size(name: str) -> int:
    return comm.axis_size(name)


def _axis_rank(name: str):
    """Inside shard_map/pmap: the trace-time index along ``name``. Outside a
    trace there is no meaningful per-device rank in a single-controller
    runtime; return 0 (reference ranks are per-process because torch is
    multi-controller)."""
    try:
        return jax.lax.axis_index(name)
    except NameError:
        return 0


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(AXIS_MODEL)


def get_tensor_model_parallel_rank():
    return _axis_rank(AXIS_MODEL)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(AXIS_PIPE)


def get_pipeline_model_parallel_rank():
    return _axis_rank(AXIS_PIPE)


def get_data_parallel_world_size() -> int:
    return _axis_size(AXIS_DATA)


def get_data_parallel_rank():
    return _axis_rank(AXIS_DATA)


def get_virtual_pipeline_model_parallel_world_size():
    return _VPP_WORLD


def get_virtual_pipeline_model_parallel_rank():
    return _VPP_RANK


def set_virtual_pipeline_model_parallel_rank(rank):
    global _VPP_RANK
    _VPP_RANK = rank


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Trace-time predicate inside shard_map (jnp bool), host bool outside."""
    if not ignore_virtual and _VPP_WORLD and (_VPP_RANK or 0) != 0:
        return False
    r = get_pipeline_model_parallel_rank()
    return r == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if (not ignore_virtual and _VPP_WORLD
            and (_VPP_RANK or 0) != _VPP_WORLD - 1):
        return False
    r = get_pipeline_model_parallel_rank()
    return r == get_pipeline_model_parallel_world_size() - 1
