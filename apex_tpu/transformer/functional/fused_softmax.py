"""Fused scale+mask+softmax.

Reference: apex/transformer/functional/fused_softmax.py —
FusedScaleMaskSoftmax dispatches between the megatron CUDA kernels
(scaled_masked_softmax_cuda, scaled_upper_triang_masked_softmax_cuda; csrc/
megatron/scaled_masked_softmax.h) and a torch fallback, by dtype/shape limits.

TPU design: BOTH N8 kernels are Pallas. The causal variant routes to
apex_tpu.kernels.causal_softmax (k-chunk triangular compute skip, fp32
math) and the generic-mask variant to apex_tpu.kernels.masked_softmax
(mask tile in VMEM, broadcast folded into the block index map) when
shapes align, with the jnp composition as fallback (which XLA fuses into
the surrounding matmuls). Kernel semantics are kept either way (half I/O allowed,
softmax math in fp32 when softmax_in_fp32, additive -10000 masking for the
padding mask, strict upper-triangular causal mask). The module class keeps
the reference's constructor surface so Megatron-style blocks port unchanged.
Callers wanting the softmax fused BETWEEN the attention GEMMs (the even
bigger win) should use kernels.flash_attention — the N11/N12 path.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..enums import AttnMaskType

__all__ = ["scaled_masked_softmax", "scaled_upper_triang_masked_softmax",
           "FusedScaleMaskSoftmax"]

_MASK_VALUE = -10000.0


def _softmax_fp32(x, out_dtype):
    x32 = jnp.asarray(x, jnp.float32)
    y = jnp.exp(x32 - jnp.max(x32, axis=-1, keepdims=True))
    y = y / jnp.sum(y, axis=-1, keepdims=True)
    return jnp.asarray(y, out_dtype)


def scaled_masked_softmax(x, mask, scale: float = 1.0,
                          softmax_in_fp32: bool = True):
    """x: [..., sq, sk]; mask: broadcastable bool (True = masked out).
    Reference kernel: scaled_masked_softmax_warp_forward. Dispatches to
    the Pallas masked-softmax kernel when softmax_in_fp32 (the kernel's
    only mode, matching the CUDA kernel's fp32 accumulation); the kernel
    itself falls back to the jnp composition on unaligned shapes or
    non-prefix mask broadcasts."""
    if softmax_in_fp32 and mask is not None:
        from apex_tpu.kernels.masked_softmax import masked_softmax
        return masked_softmax(x, jnp.asarray(mask, jnp.bool_), scale)
    out_dtype = x.dtype
    x = jnp.asarray(x, jnp.float32) * scale
    if mask is not None:
        x = jnp.where(mask, _MASK_VALUE, x)
    if softmax_in_fp32:
        return _softmax_fp32(x, out_dtype)
    return _softmax_fp32(jnp.asarray(x, out_dtype), out_dtype)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0,
                                       softmax_in_fp32: bool = True):
    """Causal: strictly-upper-triangular entries masked (reference kernel:
    scaled_upper_triang_masked_softmax_warp_forward). Dispatches to the
    Pallas causal-softmax kernel when softmax_in_fp32 (the kernel's only
    mode, matching the CUDA kernel's fp32 accumulation); the
    not-softmax_in_fp32 oddity keeps the jnp path."""
    if softmax_in_fp32:
        from apex_tpu.kernels.causal_softmax import causal_softmax
        return causal_softmax(x, scale)
    sq, sk = x.shape[-2], x.shape[-1]
    causal = jnp.triu(jnp.ones((sq, sk), jnp.bool_), k=1)
    return scaled_masked_softmax(x, causal, scale, softmax_in_fp32)


class FusedScaleMaskSoftmax:
    """Reference: fused_softmax.py — class FusedScaleMaskSoftmax. The
    is_kernel_available dispatch is moot under XLA (always "fused"); kept
    fields mirror the reference so configs port."""

    def __init__(self, input_in_fp16: bool = False,
                 input_in_bf16: bool = True,
                 attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func: Optional[Callable] = None,
                 softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        if input_in_fp16 and input_in_bf16:
            raise ValueError("both fp16 and bf16 flags set")
        if scale is not None and not softmax_in_fp32:
            raise ValueError("softmax should be in fp32 when scaled")
        self.attn_mask_type = attn_mask_type
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale if scale is not None else 1.0

    def __call__(self, x, mask=None):
        if self.attn_mask_type == AttnMaskType.causal:
            return scaled_upper_triang_masked_softmax(
                x, self.scale, self.softmax_in_fp32)
        if mask is not None and self.mask_func is not None:
            x32 = self.mask_func(jnp.asarray(x, jnp.float32), mask)
            return scaled_masked_softmax(x32, None, self.scale,
                                         self.softmax_in_fp32)
        return scaled_masked_softmax(x, mask, self.scale,
                                     self.softmax_in_fp32)
