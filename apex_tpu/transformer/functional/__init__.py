"""Reference: apex/transformer/functional/ (fused_softmax)."""

from .fused_softmax import (FusedScaleMaskSoftmax, scaled_masked_softmax,
                            scaled_upper_triang_masked_softmax)

__all__ = ["FusedScaleMaskSoftmax", "scaled_masked_softmax",
           "scaled_upper_triang_masked_softmax"]
