"""apex_tpu.fused_dense — GEMM+bias(+GELU) fused linears.

Reference: ``apex/fused_dense/fused_dense.py — class FusedDense,
class FusedDenseGeluDense, class DenseNoBias`` over ``fused_dense_cuda``
(``csrc/fused_dense.cpp``, ``fused_dense_cuda.cu — linear_bias_forward,
linear_gelu_linear_forward``), which uses cublasLt epilogues to fuse the bias
add and GELU into the GEMM.

On TPU that fusion is XLA's default behavior: a ``dot_general`` followed by a
broadcast add and ``gelu`` lowers to one fused MXU computation, and the
backward pass similarly fuses dgelu into the wgrad/dgrad GEMMs. These classes
therefore carry the reference's API and weight layout (torch Linear
``(out, in)``), with fp32 accumulation forced via ``preferred_element_type``.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["FusedDense", "DenseNoBias", "FusedDenseGeluDense",
           "fused_dense_function", "fused_dense_gelu_dense_function",
           "torch_linear_init"]


def torch_linear_init(in_features: int):
    """uniform(-1/sqrt(in), 1/sqrt(in)) — torch Linear's reset_parameters,
    which apex's fused_dense/mlp modules inherit."""
    bound = 1.0 / (in_features ** 0.5)
    init = nn.initializers.uniform(scale=2 * bound)

    def shifted(key, shape, dtype):
        return init(key, shape, dtype) - bound

    return shifted


def _linear_fp32(x, weight, bias=None):
    # GEMM with fp32 accumulation + fp32 bias add; caller decides the output
    # dtype (matches cublasLt: epilogues run on the fp32 accumulator).
    y = jnp.dot(x, jnp.asarray(weight, x.dtype).T,
                preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + jnp.asarray(bias, jnp.float32)
    return y


def _gemm_in(x):
    # O1 engine: 'linear' is FP16_FUNCS — under an active autocast policy the
    # GEMM input (and the weight, via the cast-to-x.dtype in _linear_fp32)
    # drops to the half dtype; accumulation stays fp32.
    from apex_tpu.amp.autocast import op_compute_dtype

    d = op_compute_dtype("linear")
    return x if d is None else jnp.asarray(x, d)


def fused_dense_function(x, weight, bias=None):
    """y = x @ W.T + b (reference: fused_dense_cuda.linear_bias_forward)."""
    x = _gemm_in(x)
    return jnp.asarray(_linear_fp32(x, weight, bias), x.dtype)


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2):
    """Linear→GELU→Linear in one trace.

    Reference: fused_dense_cuda.linear_gelu_linear_forward. GELU (exact/erf,
    apex uses CUBLASLT_EPILOGUE_GELU) is applied to the fp32 accumulator
    before any output-dtype conversion, as the cublasLt epilogue does.
    """
    x = _gemm_in(x)
    # gelu is an FP32 classification (amp/lists.py); it runs on the fp32
    # accumulator here regardless, matching the cublasLt epilogue.
    h = jax.nn.gelu(_linear_fp32(x, weight1, bias1), approximate=False)
    h = jnp.asarray(h, x.dtype)
    return fused_dense_function(h, weight2, bias2)


class FusedDense(nn.Module):
    """Linear with fused bias (reference: fused_dense.py — class FusedDense)."""

    in_features: int
    out_features: int
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.dtype is not None:
            x = jnp.asarray(x, self.dtype)
        init = torch_linear_init(self.in_features)
        w = self.param("weight", init, (self.out_features, self.in_features),
                       self.param_dtype)
        b = (self.param("bias", init, (self.out_features,), self.param_dtype)
             if self.use_bias else None)
        return fused_dense_function(x, w, b)


class DenseNoBias(FusedDense):
    """Bias-free variant (reference: fused_dense.py — class DenseNoBias)."""

    use_bias: bool = False


class FusedDenseGeluDense(nn.Module):
    """Linear+GELU+Linear block (reference: class FusedDenseGeluDense)."""

    in_features: int
    intermediate_features: int
    out_features: int
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.dtype is not None:
            x = jnp.asarray(x, self.dtype)
        init1 = torch_linear_init(self.in_features)
        init2 = torch_linear_init(self.intermediate_features)
        w1 = self.param("weight1", init1,
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = self.param("bias1", init1,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("weight2", init2,
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = self.param("bias2", init2,
                        (self.out_features,), self.param_dtype)
        return fused_dense_gelu_dense_function(x, w1, b1, w2, b2)
