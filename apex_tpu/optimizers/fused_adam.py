"""FusedAdam — parity with apex/optimizers/fused_adam.py — class FusedAdam.

Reference semantics: Adam/AdamW over the whole parameter list in one
multi_tensor launch per step (FusedAdam.step →
multi_tensor_applier(amp_C.multi_tensor_adam, …)); fp32 exp_avg/exp_avg_sq
state; ``adam_w_mode`` selects decoupled decay (default True, so apex's
FusedAdam is AdamW by default); ``bias_correction`` toggleable.

TPU shape: an optax ``GradientTransformation`` with fp32 (m, v) state. Two
layouts:

- ``layout="tree"`` (default): per-leaf state, one fused-by-XLA update per
  step via kernels.multi_tensor.adam_tree_step — the TPU-native layout,
  measured 3.6x faster than the superbuffer at 125M params on v5e
  (BASELINE.md round-5 kernel tier: flatten/unflatten copies, not kernel
  launches, are what a whole-model update pays for under jit). Per-tensor
  state is also what apex's own FusedAdam keeps (exp_avg per param).
- ``layout="flat"``: the round-1..4 superbuffer (one flat fp32 buffer
  through the Pallas multi_tensor kernel) — kept for checkpoints that
  stored flat state and for callers that shard the buffer itself.

Both layouts produce bitwise-identical parameter trajectories
(tests/L0/test_fused_optimizers.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

from ..kernels.multi_tensor import adam_tree_step, fused_adam_step
from ._surface import current_transform, group_property, install_torch_surface
from ..utils.pytree import flatten


class FusedAdamState(NamedTuple):
    count: jnp.ndarray     # i32 step counter
    m: Any                 # fp32 first moment — pytree (layout="tree",
    v: Any                 # default) or flat array (layout="flat")


ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], Any]]


def _lr_at(learning_rate: ScalarOrSchedule, count):
    if callable(learning_rate):
        return learning_rate(count)
    return learning_rate


def _flat32(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return flatten([jnp.ravel(x).astype(jnp.float32) for x in leaves])


def _unflatten_like(flat, tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    outs = []
    offset = 0
    for leaf in leaves:
        n = leaf.size
        outs.append(flat[offset:offset + n].reshape(leaf.shape)
                    .astype(leaf.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, outs)


def fused_adam(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9,
               beta2: float = 0.999, eps: float = 1e-8,
               weight_decay: float = 0.0, adam_w_mode: bool = True,
               bias_correction: bool = True,
               layout: str = "tree") -> optax.GradientTransformation:
    """Optax-compatible fused Adam/AdamW (apex FusedAdam defaults).

    ``layout``: "tree" (default — per-leaf state, XLA-fused update; see
    module docstring for the v5e measurement) or "flat" (superbuffer
    through the Pallas multi_tensor kernel)."""
    if layout not in ("tree", "flat"):
        raise ValueError(f"layout must be 'tree' or 'flat', got {layout!r}")

    def init_fn(params):
        if layout == "tree":
            zeros = jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, jnp.float32), params)
            return FusedAdamState(count=jnp.zeros((), jnp.int32),
                                  m=zeros,
                                  v=jax.tree_util.tree_map(jnp.copy, zeros))
        n = sum(x.size for x in jax.tree_util.tree_leaves(params))
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              m=jnp.zeros((n,), jnp.float32),
                              v=jnp.zeros((n,), jnp.float32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        if layout == "tree":
            p32 = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.float32), params)
            new_p, new_m, new_v = adam_tree_step(
                p32, state.m, state.v, updates, lr=lr, beta1=beta1,
                beta2=beta2, eps=eps, weight_decay=weight_decay, step=count,
                adam_w_mode=adam_w_mode, bias_correction=bias_correction)
            # delta in fp32 then cast — the exact arithmetic the flat
            # layout performs (subtract on the fp32 buffer, cast per leaf)
            delta = jax.tree_util.tree_map(
                lambda np_, pp, leaf: (np_ - pp).astype(leaf.dtype),
                new_p, p32, params)
            return delta, FusedAdamState(count=count, m=new_m, v=new_v)
        flat_p = _flat32(params)
        flat_g = _flat32(updates)
        new_p, new_m, new_v = fused_adam_step(
            flat_p, state.m, state.v, flat_g, lr=lr, beta1=beta1, beta2=beta2,
            eps=eps, weight_decay=weight_decay, step=count,
            adam_w_mode=adam_w_mode, bias_correction=bias_correction)
        delta = _unflatten_like(new_p - flat_p, params)
        return delta, FusedAdamState(count=count, m=new_m, v=new_v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam:
    """apex-shaped stateful wrapper (apex/optimizers/fused_adam.py —
    class FusedAdam). ``step(grads, params) -> new_params`` since JAX params
    are explicit; betas/eps/weight_decay/adam_w_mode keep apex names."""

    lr = group_property("lr")
    weight_decay = group_property("weight_decay")

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")  # apex raises the same

        def factory(lr, bias_correction, betas, eps, adam_w_mode,
                    weight_decay):
            return fused_adam(lr, betas[0], betas[1], eps, weight_decay,
                              adam_w_mode, bias_correction)

        self.transform = fused_adam(lr, betas[0], betas[1], eps, weight_decay,
                                    adam_w_mode, bias_correction)
        self.state = self.transform.init(params)
        self.params = params
        install_torch_surface(self, params, factory, dict(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            adam_w_mode=adam_w_mode, weight_decay=weight_decay))

    def step(self, grads, params=None):
        params = self.params if params is None else params
        tx = current_transform(self)
        updates, self.state = tx.update(grads, self.state, params)
        self.params = optax.apply_updates(params, updates)
        return self.params

    def state_dict(self):
        return {"count": int(self.state.count),
                "m": self.state.m, "v": self.state.v}

    def load_state_dict(self, sd):
        self.state = FusedAdamState(count=jnp.asarray(sd["count"], jnp.int32),
                                    m=sd["m"], v=sd["v"])
