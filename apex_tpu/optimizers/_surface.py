"""torch.optim.Optimizer-shaped surface for the apex-shaped classes.

The reference optimizers inherit torch.optim.Optimizer, so apex code reads
AND WRITES ``opt.param_groups[0]["lr"]`` (lr schedules) and apex LARC zeroes
``group["weight_decay"]`` around the inner step. Here the update math lives
in an optax transform built from the hyperparameters, so the surface is kept
live by rebuilding the transform whenever param_groups values change
(rebuild is trivia — a closure construction; state is carried unchanged
because optax state layout doesn't depend on scalar hyperparameters).
"""

from __future__ import annotations

from typing import Callable


def install_torch_surface(opt, params, factory: Callable, defaults: dict):
    """Attach defaults/param_groups and the transform factory.

    ``factory(**hyper) -> optax.GradientTransformation`` must accept exactly
    the keys of ``defaults``.
    """
    opt._factory = factory
    opt._built_with = dict(defaults)
    opt.defaults = dict(defaults)
    opt.param_groups = [dict(defaults, params=params)]


def current_transform(opt):
    """The transform matching param_groups[0]'s CURRENT hyperparameters —
    rebuilt on change so writes to param_groups take effect like torch."""
    hyper = {k: v for k, v in opt.param_groups[0].items() if k != "params"}
    if hyper != opt._built_with:
        opt.transform = opt._factory(**hyper)
        opt._built_with = dict(hyper)
    return opt.transform


def group_property(key: str):
    """Class-level property aliasing param_groups[0][key] (torch exposes
    both spellings; LARC reads opt.lr / opt.weight_decay)."""

    def _get(self):
        return self.param_groups[0][key]

    def _set(self, value):
        self.param_groups[0][key] = value

    return property(_get, _set)
