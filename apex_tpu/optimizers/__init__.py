"""apex_tpu.optimizers — fused optimizers (reference: apex/optimizers/).

Each optimizer exists in two shapes:
- a lowercase optax ``GradientTransformation`` factory (``fused_adam(...)``)
  for functional training loops (composes with apex_tpu.amp.make_train_step);
- an apex-shaped stateful class (``FusedAdam``) mirroring the reference
  constructor signature for recipe parity.
"""

from .fused_adam import FusedAdam, FusedAdamState, fused_adam  # noqa: F401
from .fused_adagrad import (FusedAdagrad, FusedAdagradState,  # noqa: F401
                            fused_adagrad)
from .fused_lamb import FusedLAMB, FusedLAMBState, fused_lamb  # noqa: F401
from .fused_novograd import (FusedNovoGrad, FusedNovoGradState,  # noqa: F401
                             fused_novograd)
from .fused_sgd import FusedSGD, FusedSGDState, fused_sgd  # noqa: F401

__all__ = [
    "FusedAdam", "fused_adam", "FusedAdamState",
    "FusedSGD", "fused_sgd", "FusedSGDState",
    "FusedLAMB", "fused_lamb", "FusedLAMBState",
    "FusedNovoGrad", "fused_novograd", "FusedNovoGradState",
    "FusedAdagrad", "fused_adagrad", "FusedAdagradState",
]
