"""FusedLAMB — parity with apex/optimizers/fused_lamb.py — class FusedLAMB.

Reference semantics (csrc/multi_tensor_lamb.cu — LAMBStage1Functor,
LAMBStage2Functor, driven by FusedLAMB.step):

1. global grad norm via multi_tensor_l2norm over every grad;
2. if global_norm > max_grad_norm: all grads divided by
   global_norm / max_grad_norm (clipped_global_grad_norm);
3. stage 1 per tensor: Adam-style moments (grad_averaging selects the
   (1-beta1) factor), bias correction, update = mhat/(sqrt(vhat)+eps) + wd*p;
4. stage 2 per tensor: trust ratio = ||p|| / ||update|| when both norms are
   nonzero else 1.0; when weight_decay == 0 the ratio is forced to 1.0
   unless ``use_nvlamb`` (matching the kernel's NVLAMB switch);
5. p -= lr * ratio * update.

Per-tensor trust ratios make a flat superbuffer awkward; the tree-level
formulation below keeps the exact math, with the l2norm reductions running
through the fused kernel. XLA fuses the per-tensor elementwise chains, so the
launch-count motivation for the CUDA two-stage kernel does not apply.
"""

from __future__ import annotations

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp
import optax

from ..kernels.multi_tensor import fused_l2norm
from ._surface import current_transform, group_property, install_torch_surface
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedLAMBState(NamedTuple):
    count: jnp.ndarray
    m: Any   # per-tensor fp32 pytree
    v: Any


def fused_lamb(learning_rate: ScalarOrSchedule = 1e-3,
               beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-6,
               weight_decay: float = 0.01, bias_correction: bool = True,
               grad_averaging: bool = True, max_grad_norm: float = 1.0,
               use_nvlamb: bool = False) -> optax.GradientTransformation:
    """Optax-compatible fused LAMB (apex FusedLAMB defaults)."""

    def init_fn(params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedLAMBState(count=jnp.zeros((), jnp.int32), m=zeros,
                              v=jax.tree_util.tree_map(jnp.copy, zeros))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        count = state.count + 1
        countf = count.astype(jnp.float32)
        lr = _lr_at(learning_rate, count)

        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), updates)
        # (1)+(2) global-norm clip, exactly the kernel's formulation
        global_sq = sum(jnp.sum(g * g)
                        for g in jax.tree_util.tree_leaves(g32))
        global_norm = jnp.sqrt(global_sq)
        clip = jnp.where(global_norm > max_grad_norm,
                         global_norm / max_grad_norm, 1.0)
        beta1_grad = (1.0 - beta1) if grad_averaging else 1.0
        if bias_correction:
            bc1 = 1.0 - beta1 ** countf
            bc2 = 1.0 - beta2 ** countf
        else:
            bc1 = bc2 = 1.0

        def one(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g = g / clip
            m_new = beta1 * m + beta1_grad * g
            v_new = beta2 * v + (1.0 - beta2) * g * g
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            update = update + weight_decay * p32
            w_norm = fused_l2norm(jnp.ravel(p32))
            u_norm = fused_l2norm(jnp.ravel(update))
            ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm,
                              1.0)
            if weight_decay == 0.0 and not use_nvlamb:
                ratio = 1.0  # kernel skips trust ratio for undecayed params
            delta = (-lr * ratio * update).astype(p.dtype)
            return delta, m_new, v_new

        out = jax.tree_util.tree_map(one, params, g32, state.m, state.v)
        delta = jax.tree_util.tree_map(lambda t: t[0], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        m_new = jax.tree_util.tree_map(lambda t: t[1], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        v_new = jax.tree_util.tree_map(lambda t: t[2], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return delta, FusedLAMBState(count=count, m=m_new, v=v_new)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedLAMB:
    """apex-shaped stateful wrapper (apex/optimizers/fused_lamb.py)."""

    lr = group_property("lr")
    weight_decay = group_property("weight_decay")

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0, use_nvlamb=False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        def factory(lr, bias_correction, betas, eps, weight_decay,
                    grad_averaging, max_grad_norm, use_nvlamb):
            return fused_lamb(lr, betas[0], betas[1], eps, weight_decay,
                              bias_correction, grad_averaging,
                              max_grad_norm, use_nvlamb)

        self.transform = fused_lamb(lr, betas[0], betas[1], eps, weight_decay,
                                    bias_correction, grad_averaging,
                                    max_grad_norm, use_nvlamb)
        self.state = self.transform.init(params)
        self.params = params
        install_torch_surface(self, params, factory, dict(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, grad_averaging=grad_averaging,
            max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb))

    def step(self, grads, params=None):
        params = self.params if params is None else params
        tx = current_transform(self)
        updates, self.state = tx.update(grads, self.state, params)
        self.params = optax.apply_updates(params, updates)
        return self.params
