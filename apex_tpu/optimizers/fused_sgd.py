"""FusedSGD — parity with apex/optimizers/fused_sgd.py — class FusedSGD.

Reference semantics: torch.optim.SGD formula (momentum, dampening, nesterov,
L2 weight_decay) executed for the whole model via
multi_tensor_applier(amp_C.multi_tensor_sgd); ``wd_after_momentum`` variant
exposed; momentum buffers fp32.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..kernels.multi_tensor import fused_sgd_step, sgd_tree_step
from ._surface import current_transform, group_property, install_torch_surface
from .fused_adam import ScalarOrSchedule, _flat32, _lr_at, _unflatten_like


class FusedSGDState(NamedTuple):
    count: jnp.ndarray
    momentum_buf: Any  # fp32 — pytree (layout="tree", default) or flat
    #                    array (layout="flat")


def fused_sgd(learning_rate: ScalarOrSchedule, momentum: float = 0.0,
              dampening: float = 0.0, weight_decay: float = 0.0,
              nesterov: bool = False, wd_after_momentum: bool = False,
              layout: str = "tree") -> optax.GradientTransformation:
    """Optax-compatible fused SGD (apex/optimizers/fused_sgd.py —
    FusedSGD defaults: torch-style momentum buffer, optional Nesterov,
    ``wd_after_momentum`` ordering flag).

    ``layout``: "tree" (default — per-leaf momentum state, XLA-fused
    update; see fused_adam's module docstring for the v5e measurement
    behind the round-5 default) or "flat" (superbuffer through the Pallas
    multi_tensor kernel). Bitwise-identical trajectories."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero "
                         "dampening")  # torch/apex validation
    if layout not in ("tree", "flat"):
        raise ValueError(f"layout must be 'tree' or 'flat', got {layout!r}")

    def init_fn(params):
        if layout == "tree":
            buf = jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, jnp.float32), params)
            return FusedSGDState(count=jnp.zeros((), jnp.int32),
                                 momentum_buf=buf)
        n = sum(x.size for x in jax.tree_util.tree_leaves(params))
        return FusedSGDState(count=jnp.zeros((), jnp.int32),
                             momentum_buf=jnp.zeros((n,), jnp.float32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)
        if layout == "tree":
            p32 = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.float32), params)
            new_p, new_buf = sgd_tree_step(
                p32, state.momentum_buf, updates, lr=lr, momentum=momentum,
                dampening=dampening, weight_decay=weight_decay,
                nesterov=nesterov, wd_after_momentum=wd_after_momentum)
            delta = jax.tree_util.tree_map(
                lambda np_, pp, leaf: (np_ - pp).astype(leaf.dtype),
                new_p, p32, params)
            return delta, FusedSGDState(count=count, momentum_buf=new_buf)
        flat_p = _flat32(params)
        flat_g = _flat32(updates)
        new_p, new_buf = fused_sgd_step(
            flat_p, state.momentum_buf, flat_g, lr=lr, momentum=momentum,
            dampening=dampening, weight_decay=weight_decay, nesterov=nesterov,
            wd_after_momentum=wd_after_momentum)
        delta = _unflatten_like(new_p - flat_p, params)
        return delta, FusedSGDState(count=count, momentum_buf=new_buf)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedSGD:
    """apex-shaped stateful wrapper (apex/optimizers/fused_sgd.py)."""

    lr = group_property("lr")
    weight_decay = group_property("weight_decay")

    def __init__(self, params, lr, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, wd_after_momentum=False,
                 materialize_master_grads=True, set_grad_none=False):

        def factory(lr, momentum, dampening, weight_decay, nesterov,
                    wd_after_momentum):
            return fused_sgd(lr, momentum, dampening, weight_decay,
                             nesterov, wd_after_momentum)

        self.transform = fused_sgd(lr, momentum, dampening, weight_decay,
                                   nesterov, wd_after_momentum)
        self.state = self.transform.init(params)
        self.params = params
        install_torch_surface(self, params, factory, dict(
            lr=lr, momentum=momentum, dampening=dampening,
            weight_decay=weight_decay, nesterov=nesterov,
            wd_after_momentum=wd_after_momentum))

    def step(self, grads, params=None):
        params = self.params if params is None else params
        tx = current_transform(self)
        updates, self.state = tx.update(grads, self.state, params)
        self.params = optax.apply_updates(params, updates)
        return self.params
