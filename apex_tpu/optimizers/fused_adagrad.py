"""FusedAdagrad — parity with apex/optimizers/fused_adagrad.py.

Reference semantics (csrc/multi_tensor_adagrad.cu — AdagradFunctor):
  h += g^2 ; p -= lr * g / (sqrt(h) + eps)
with ``adagrad_w_mode`` selecting decoupled weight decay (mode 1) vs L2 into
the grad (mode 0, apex default False → L2).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ._surface import current_transform, group_property, install_torch_surface
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    sum: Any   # per-tensor fp32 accumulator pytree


def fused_adagrad(learning_rate: ScalarOrSchedule = 1e-2, eps: float = 1e-10,
                  weight_decay: float = 0.0,
                  adagrad_w_mode: bool = False) -> optax.GradientTransformation:

    def init_fn(params):
        acc = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdagradState(count=jnp.zeros((), jnp.int32), sum=acc)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")
        count = state.count + 1
        lr = _lr_at(learning_rate, count)

        def one(p, g, h):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            if not adagrad_w_mode:
                g32 = g32 + weight_decay * p32
            h_new = h + g32 * g32
            upd = g32 / (jnp.sqrt(h_new) + eps)
            if adagrad_w_mode:
                upd = upd + weight_decay * p32
            return (-lr * upd).astype(p.dtype), h_new

        out = jax.tree_util.tree_map(one, params, updates, state.sum)
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), FusedAdagradState(count=count, sum=pick(1))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdagrad:
    """apex-shaped stateful wrapper."""

    lr = group_property("lr")
    weight_decay = group_property("weight_decay")

    def __init__(self, params, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 set_grad_none=True, adagrad_w_mode=False):
        def factory(lr, eps, weight_decay, adagrad_w_mode):
            return fused_adagrad(lr, eps, weight_decay, adagrad_w_mode)

        self.transform = fused_adagrad(lr, eps, weight_decay, adagrad_w_mode)
        self.state = self.transform.init(params)
        self.params = params
        install_torch_surface(self, params, factory, dict(
            lr=lr, eps=eps, weight_decay=weight_decay,
            adagrad_w_mode=adagrad_w_mode))

    def step(self, grads, params=None):
        params = self.params if params is None else params
        tx = current_transform(self)
        updates, self.state = tx.update(grads, self.state, params)
        self.params = optax.apply_updates(params, updates)
        return self.params
