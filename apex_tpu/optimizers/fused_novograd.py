"""FusedNovoGrad — parity with apex/optimizers/fused_novograd.py.

Reference semantics (csrc/multi_tensor_novograd.cu + FusedNovoGrad.step):
NovoGrad keeps a per-TENSOR scalar second moment (the squared L2 norm of the
layer's grad), not a per-element one:

  first step:   v_t = ||g||^2            (init_zero=False default)
  later:        v_t = b2*v + (1-b2)*||g||^2
  m_t = b1*m + (1-b1 if grad_averaging else 1) * (g/(sqrt(v_t)+eps) + wd*p)
  p  -= lr * m_t            (bias correction optional, reg_inside_moment on)
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ._surface import current_transform, group_property, install_torch_surface
from .fused_adam import ScalarOrSchedule, _lr_at


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    m: Any          # per-tensor fp32 pytree
    v: Any          # per-tensor scalar fp32 pytree


def fused_novograd(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.95,
                   beta2: float = 0.98, eps: float = 1e-8,
                   weight_decay: float = 0.0, grad_averaging: bool = True,
                   init_zero: bool = False,
                   bias_correction: bool = False) -> optax.GradientTransformation:

    def init_fn(params):
        m = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        v = jax.tree_util.tree_map(lambda p: jnp.zeros((), jnp.float32),
                                   params)
        return FusedNovoGradState(count=jnp.zeros((), jnp.int32), m=m, v=v)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        count = state.count + 1
        countf = count.astype(jnp.float32)
        lr = _lr_at(learning_rate, count)
        beta1_grad = (1.0 - beta1) if grad_averaging else 1.0
        if bias_correction:
            bc1 = 1.0 - beta1 ** countf
            bc2 = 1.0 - beta2 ** countf
        else:
            bc1 = bc2 = 1.0

        def one(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            norm_sq = jnp.sum(g32 * g32)
            v_new = jnp.where(
                (count == 1) & (not init_zero),
                norm_sq, beta2 * v + (1.0 - beta2) * norm_sq)
            denom = jnp.sqrt(v_new / bc2) + eps
            m_new = beta1 * m + beta1_grad * (g32 / denom +
                                              weight_decay * p32)
            delta = (-lr * m_new / bc1).astype(p.dtype)
            return delta, m_new, v_new

        out = jax.tree_util.tree_map(one, params, updates, state.m, state.v)
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), FusedNovoGradState(count=count, m=pick(1), v=pick(2))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedNovoGrad:
    """apex-shaped stateful wrapper."""

    lr = group_property("lr")
    weight_decay = group_property("weight_decay")

    def __init__(self, params, lr=1e-3, bias_correction=False,
                 betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                 grad_averaging=True, init_zero=False, set_grad_none=True,
                 amsgrad=False, reg_inside_moment=True, norm_type=2):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        if norm_type != 2:
            raise ValueError("FusedNovoGrad only supports the L2 norm")
        def factory(lr, bias_correction, betas, eps, weight_decay,
                    grad_averaging, init_zero):
            return fused_novograd(lr, betas[0], betas[1], eps, weight_decay,
                                  grad_averaging, init_zero, bias_correction)

        self.transform = fused_novograd(lr, betas[0], betas[1], eps,
                                        weight_decay, grad_averaging,
                                        init_zero, bias_correction)
        self.state = self.transform.init(params)
        self.params = params
        install_torch_surface(self, params, factory, dict(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, grad_averaging=grad_averaging,
            init_zero=init_zero))

    def step(self, grads, params=None):
        params = self.params if params is None else params
        tx = current_transform(self)
        updates, self.state = tx.update(grads, self.state, params)
        self.params = optax.apply_updates(params, updates)
        return self.params
