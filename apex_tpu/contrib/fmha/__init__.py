"""Varlen packed flash-MHA (MLPerf BERT).

Reference: apex/contrib/fmha/fmha.py — class FMHAFun (fmhalib.fwd/bwd):
packed QKV [total_tokens, 3, heads, d] with cu_seqlens delimiting sequences,
max seqlen ≤ 512. TPU: the same flash kernel with segment ids — cu_seqlens
converts to a per-token segment id; no separate kernel needed (the SURVEY
§3.2 N12 mapping).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention

__all__ = ["fmha", "cu_seqlens_to_segment_ids"]


def cu_seqlens_to_segment_ids(cu_seqlens, total: int):
    """[n+1] cumulative lengths -> [total] segment ids (0..n-1)."""
    positions = jnp.arange(total)
    # segment of token t = number of boundaries <= t
    return jnp.searchsorted(cu_seqlens[1:-1], positions, side="right") \
        if cu_seqlens.shape[0] > 2 else jnp.zeros((total,), jnp.int32)


def fmha(qkv, cu_seqlens, *, heads: int, causal: bool = False):
    """qkv: [total, 3, heads, d] packed (reference layout). Returns
    [total, heads, d]."""
    total, three, h, d = qkv.shape
    assert three == 3 and h == heads
    seg = cu_seqlens_to_segment_ids(jnp.asarray(cu_seqlens), total)
    q = qkv[:, 0].transpose(1, 0, 2)[None]   # [1, H, total, d]
    k = qkv[:, 1].transpose(1, 0, 2)[None]
    v = qkv[:, 2].transpose(1, 0, 2)[None]
    out = flash_attention(q, k, v, causal=causal,
                          segment_ids=seg[None, :])
    return out[0].transpose(1, 0, 2)         # [total, H, d]
