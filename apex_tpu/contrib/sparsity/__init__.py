"""ASP — automatic 2:4 structured sparsity.

Reference: apex/contrib/sparsity/asp.py — class ASP
(init_model_for_pruning / init_optimizer_for_pruning /
compute_sparse_masks / prune_trained_model) and sparse_masklib.py —
create_mask (m-of-n magnitude masks), plus permutation_search_kernels
(channel permutation preserving accuracy, N15).

TPU design: masks are pytrees applied functionally — instead of
monkey-patching optimizer.step (torch), ``apply_masks`` multiplies params
after each update (compose with optax via ``masked_update``). The mask math
(2:4 by magnitude along the input dim) is identical; the permutation search
is the greedy column-permutation from the reference's kernels, in jnp
(CPU-ok per SURVEY §3.2 N15).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

__all__ = ["create_mask", "compute_sparse_masks", "apply_masks",
           "masked_update", "permutation_search", "ASP"]


def create_mask(w, pattern: str = "m4n2_1d"):
    """2:4 (n of m) magnitude mask along the last dim (reference:
    sparse_masklib.create_mask; default pattern m4n2_1d). Returns bool mask
    with True = keep."""
    if pattern not in ("m4n2_1d", "m4n2"):
        raise ValueError(f"unsupported pattern {pattern!r}")
    m, n = 4, 2
    orig = w.shape
    last = orig[-1]
    if last % m:
        return jnp.ones(orig, bool)  # unprunable shape → dense (reference
        # skips layers whose dims don't fit the pattern)
    g = jnp.abs(jnp.asarray(w, jnp.float32)).reshape(-1, m)
    # keep exactly the top-n of each group of m; the index-scaled epsilon
    # breaks ties deterministically like the reference kernels do
    idx = jnp.argsort(jnp.argsort(-g - jnp.arange(m) * 1e-12, axis=-1),
                      axis=-1)
    mask = idx < n
    return mask.reshape(orig)


def _prunable(path_names, leaf) -> bool:
    shape = jnp.shape(leaf)
    if len(shape) < 2:
        return False
    name = path_names[-1] if path_names else ""
    return name in ("kernel", "embedding", "w", "weight") \
        and shape[-1] % 4 == 0


def _path_names(path):
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        else:
            names.append(str(p))
    return names


def compute_sparse_masks(params, allowed_layer_names: Optional[Callable] =
                         None, pattern: str = "m4n2_1d"):
    """Masks for every prunable weight (reference:
    ASP.compute_sparse_masks). ``allowed_layer_names(path_names, leaf)``
    overrides the default kernel/embedding rule."""
    pred = allowed_layer_names or _prunable

    def one(path, leaf):
        if pred(_path_names(path), leaf):
            return create_mask(leaf, pattern)
        return jnp.ones(jnp.shape(leaf), bool)

    return jax.tree_util.tree_map_with_path(one, params)


def apply_masks(params, masks):
    return jax.tree_util.tree_map(
        lambda p, m: jnp.where(m, p, jnp.zeros_like(p)), params, masks)


def masked_update(masks) -> optax.GradientTransformation:
    """Optax component zeroing masked updates — the functional equivalent of
    the reference's patched optimizer.step re-applying masks after the
    update (ASP.init_optimizer_for_pruning)."""

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        return jax.tree_util.tree_map(
            lambda u, m: jnp.where(m, u, jnp.zeros_like(u)), updates, masks
        ), state

    return optax.GradientTransformation(init_fn, update_fn)


def permutation_search(w, n_iter: int = 100, seed: int = 0):
    """Greedy input-channel permutation maximizing retained magnitude under
    2:4 (reference: permutation_search_kernels — channel swaps accepted when
    they increase the kept-magnitude sum). Returns (perm, gain)."""
    w = np.abs(np.asarray(w, np.float32))
    if w.ndim != 2 or w.shape[1] % 4:
        return np.arange(w.shape[-1]), 0.0
    cols = w.shape[1]
    rng = np.random.default_rng(seed)
    perm = np.arange(cols)

    def kept(mat):
        g = mat.reshape(mat.shape[0], -1, 4)
        top = np.sort(g, axis=-1)[:, :, 2:]
        return float(top.sum())

    best = kept(w[:, perm])
    base = best
    for _ in range(n_iter):
        i, j = rng.integers(0, cols, 2)
        if i == j:
            continue
        cand = perm.copy()
        cand[[i, j]] = cand[[j, i]]
        score = kept(w[:, cand])
        if score > best:
            best, perm = score, cand
    return perm, best - base


class ASP:
    """Stateful facade mirroring the reference classmethod API."""

    _masks = None
    _pattern = "m4n2_1d"

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator: str = "m4n2_1d",
                               **_ignored):
        cls._pattern = mask_calculator
        cls._masks = compute_sparse_masks(params, pattern=mask_calculator)
        return cls._masks

    @classmethod
    def init_optimizer_for_pruning(cls, optimizer:
                                   optax.GradientTransformation):
        if cls._masks is None:
            raise RuntimeError("call init_model_for_pruning first")
        return optax.chain(optimizer, masked_update(cls._masks))

    @classmethod
    def compute_sparse_masks(cls, params):
        cls._masks = compute_sparse_masks(params, pattern=cls._pattern)
        return cls._masks

    @classmethod
    def prune_trained_model(cls, params, optimizer:
                            optax.GradientTransformation):
        """One-shot recipe (reference: ASP.prune_trained_model): compute
        masks, apply to params, wrap optimizer."""
        masks = compute_sparse_masks(params)
        cls._masks = masks
        return apply_masks(params, masks), \
            optax.chain(optimizer, masked_update(masks))
