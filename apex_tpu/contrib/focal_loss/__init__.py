"""Fused focal loss (detection).

Reference: apex/contrib/focal_loss/focal_loss.py — class FocalLoss /
focal_loss_cuda.forward (fused sigmoid focal loss with bwd-in-fwd). The
standard RetinaNet-style formulation: per-anchor sigmoid CE modulated by
(1-p_t)^gamma and alpha class balance; label == num_classes (or < 0) means
background/ignore handling lives in the caller recipes.

TPU: one jnp expression under custom_vjp (the analytic gradient is the
bwd-in-fwd the CUDA kernel computes), fp32 math with half I/O.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "FocalLoss"]


def _fl_terms(logits, targets_onehot, alpha, gamma):
    lg = jnp.asarray(logits, jnp.float32)
    p = jax.nn.sigmoid(lg)
    ce = jnp.logaddexp(0.0, lg) - lg * targets_onehot  # BCE with logits
    p_t = p * targets_onehot + (1.0 - p) * (1.0 - targets_onehot)
    alpha_t = alpha * targets_onehot + (1.0 - alpha) * (1.0 - targets_onehot)
    mod = (1.0 - p_t) ** gamma
    return p, p_t, alpha_t, mod, ce


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def focal_loss(logits, targets_onehot, alpha: float = 0.25,
               gamma: float = 2.0):
    """Per-element sigmoid focal loss. logits/targets_onehot: [..., C]."""
    _, _, alpha_t, mod, ce = _fl_terms(logits, targets_onehot, alpha, gamma)
    return alpha_t * mod * ce


def _fl_fwd(logits, targets_onehot, alpha, gamma):
    return focal_loss(logits, targets_onehot, alpha, gamma), \
        (logits, targets_onehot)


def _fl_bwd(alpha, gamma, res, g):
    logits, t = res
    p, p_t, alpha_t, mod, ce = _fl_terms(logits, t, alpha, gamma)
    # d/dx [ (1-pt)^g * ce ] = (1-pt)^g * dce + g(1-pt)^(g-1) * (-dpt) * ce
    dce = p - t                                   # d BCE / d logits
    dpt_dx = (2.0 * t - 1.0) * p * (1.0 - p)      # d p_t / d logits
    dmod = -gamma * (1.0 - p_t) ** (gamma - 1.0) * dpt_dx
    grad = alpha_t * (mod * dce + dmod * ce)
    return (jnp.asarray(grad * g, jnp.asarray(logits).dtype),
            jnp.zeros_like(t))


focal_loss.defvjp(_fl_fwd, _fl_bwd)


class FocalLoss:
    """Module-shaped wrapper (reference exposes focal_loss.FocalLoss)."""

    def __init__(self, alpha: float = 0.25, gamma: float = 2.0,
                 reduction: str = "mean"):
        self.alpha, self.gamma, self.reduction = alpha, gamma, reduction

    def __call__(self, logits, targets_onehot):
        l = focal_loss(logits, targets_onehot, self.alpha, self.gamma)
        if self.reduction == "mean":
            return jnp.mean(l)
        if self.reduction == "sum":
            return jnp.sum(l)
        return l
