"""Fused gather-multiply.

Reference: apex/contrib/index_mul_2d/index_mul_2d.py — index_mul_2d
(apex/contrib/csrc/index_mul_2d/index_mul_2d_cuda.cu): out = in1[idx] * in2
fwd, with fused scatter-accumulate bwd. XLA fuses gather×mul and its
transpose (scatter-add) natively, so this is the API with jnp internals —
exactly the §3.2 mapping table's note for N20.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1, in2, idx):
    """out[i, :] = in1[idx[i], :] * in2[i, :]. Differentiable (autodiff
    produces the fused scatter-add the CUDA bwd kernel hand-writes)."""
    return jnp.take(in1, idx, axis=0) * in2
