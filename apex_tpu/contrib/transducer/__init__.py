"""RNN-T transducer joint and loss.

Reference: apex/contrib/transducer/transducer.py — class TransducerJoint
(fused f+g broadcast-add with optional relu/dropout and packing, N21 joint
kernel) and class TransducerLoss (alpha-beta forward-backward DP loss, N21
loss kernel with bwd-in-fwd).

TPU design: the joint is a broadcast add XLA fuses. The loss is the
classic RNN-T log-likelihood: alphas computed with a ``lax.scan`` over the
anti-diagonal recursion (t dimension scanned, u dimension vectorized — the
wavefront trick the CUDA kernel parallelizes the same way), gradients via
autodiff of the scan (exact, replacing the hand-written backward kernel).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss"]

_NEG = -1e30


def transducer_joint(f, g, *, relu: bool = False):
    """f: [B, T, H] (encoder), g: [B, U, H] (predictor) →
    joint [B, T, U, H] (reference: transducer_joint_cuda.forward; the
    pack/unpack variants operate on the same math)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jnp.maximum(out, 0)
    return out


class TransducerJoint:
    """Ctor mirrors the reference (pack_output, relu, dropout ignored or
    handled functionally)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0, **_ignored):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA memory-layout optimization; TPU "
                "keeps the dense [B,T,U,H] layout")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g):
        return transducer_joint(f, g, relu=self.relu)


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log-likelihood.

    log_probs: [B, T, U+1, V] log-softmax outputs; labels: [B, U] int;
    f_len: [B] valid T per sample; y_len: [B] valid U per sample.
    (Reference: transducer_loss_cuda.forward — alphas/betas; here alphas by
    scan over t with u vectorized; grads by autodiff.)
    """
    b, t_max, u1, v = log_probs.shape
    u_max = u1 - 1
    lp = jnp.asarray(log_probs, jnp.float32)

    # per (t, u): blank prob and emit prob of labels[u]
    blank = lp[..., blank_idx]                                  # [B, T, U+1]
    emit = jnp.take_along_axis(
        lp[:, :, :u_max, :], labels[:, None, :, None], axis=-1)[..., 0]
    emit = jnp.pad(emit, ((0, 0), (0, 0), (0, 1)),
                   constant_values=_NEG)                        # [B, T, U+1]

    us = jnp.arange(u1)

    def step_t(alpha_prev, t):
        # alpha[t, u] = logsumexp(alpha[t-1, u] + blank[t-1, u],
        #                         alpha[t, u-1] + emit[t, u-1])
        horiz = alpha_prev + blank[:, t - 1, :]

        def step_u(carry, u):
            # left-to-right dependency in u at fixed t
            left = carry
            val = jnp.where(
                u == 0, horiz[:, 0],
                jnp.logaddexp(horiz[:, u],
                              left + emit[:, t, u - 1]))
            # t == 0 row: only emit transitions from u-1
            val0 = jnp.where(u == 0, 0.0, left + emit[:, 0, u - 1])
            val = jnp.where(t == 0, val0, val)
            return val, val

        _, cols = jax.lax.scan(step_u, jnp.full((b,), _NEG), us)
        alpha_t = cols.T                                        # [B, U+1]
        return alpha_t, alpha_t

    alpha0 = jnp.full((b, u1), _NEG)
    _, alphas = jax.lax.scan(step_t, alpha0, jnp.arange(t_max))
    alphas = alphas.transpose(1, 0, 2)                          # [B, T, U+1]

    # ll = alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    bi = jnp.arange(b)
    a_final = alphas[bi, f_len - 1, y_len]
    ll = a_final + blank[bi, f_len - 1, y_len]
    return -ll


class TransducerLoss:
    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False, **_ignored):
        if packed_input:
            raise NotImplementedError("packed input is CUDA-layout only")

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
