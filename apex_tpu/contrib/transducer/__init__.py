"""RNN-T transducer joint and loss.

Reference: apex/contrib/transducer/transducer.py — class TransducerJoint
(fused f+g broadcast-add with optional relu/dropout and packing, N21 joint
kernel) and class TransducerLoss (alpha-beta forward-backward DP loss, N21
loss kernel with bwd-in-fwd).

TPU design: the joint is a broadcast add XLA fuses. The loss is the
classic RNN-T log-likelihood with the CUDA kernel's wavefront
parallelization expressed to the compiler: elements on anti-diagonal
d = t+u depend only on diagonal d-1, so (blank, emit) are re-laid-out
diagonally once and alphas advance with ONE ``lax.scan`` of T+U steps of
[B, U+1] vector ops — versus T·U sequential steps for the textbook
row-by-row recursion. Gradients come from autodiff of the scan (exact,
replacing the hand-written backward kernel). A Pallas kernel buys nothing
here: the bottleneck is the sequential diagonal dependency, which no
launch structure removes — the win is the wavefront vectorization itself
(the "Pallas alpha-beta scan" N21 mapping resolves to this).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss"]

_NEG = -1e30


def transducer_joint(f, g, *, relu: bool = False):
    """f: [B, T, H] (encoder), g: [B, U, H] (predictor) →
    joint [B, T, U, H] (reference: transducer_joint_cuda.forward; the
    pack/unpack variants operate on the same math)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jnp.maximum(out, 0)
    return out


class TransducerJoint:
    """Ctor mirrors the reference (pack_output, relu, dropout ignored or
    handled functionally)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0, **_ignored):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA memory-layout optimization; TPU "
                "keeps the dense [B,T,U,H] layout")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g):
        return transducer_joint(f, g, relu=self.relu)


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log-likelihood.

    log_probs: [B, T, U+1, V] log-softmax outputs; labels: [B, U] int;
    f_len: [B] valid T per sample; y_len: [B] valid U per sample.
    (Reference: transducer_loss_cuda.forward — alphas/betas; here one scan
    over the T+U anti-diagonals with the whole diagonal vectorized — see
    the module docstring; grads by autodiff.)
    """
    b, t_max, u1, v = log_probs.shape
    u_max = u1 - 1
    lp = jnp.asarray(log_probs, jnp.float32)

    # per (t, u): blank prob and emit prob of labels[u]
    blank = lp[..., blank_idx]                                  # [B, T, U+1]
    emit = jnp.take_along_axis(
        lp[:, :, :u_max, :], labels[:, None, :, None], axis=-1)[..., 0]
    emit = jnp.pad(emit, ((0, 0), (0, 0), (0, 1)),
                   constant_values=_NEG)                        # [B, T, U+1]

    # diagonal re-layout: X_diag[d, u] = X[d - u, u] (t = d - u), the
    # wavefront coordinates. One gather each; invalid t → -inf.
    us = jnp.arange(u1)                                         # [U+1]
    n_diag = t_max + u1 - 1                                     # d = t + u
    t_idx = jnp.arange(n_diag)[:, None] - us[None, :]           # [D, U+1]
    t_ok = (t_idx >= 0) & (t_idx < t_max)
    t_clip = jnp.clip(t_idx, 0, t_max - 1)

    def to_diag(x):                                             # [B,T,U+1]
        g = x[:, t_clip, us[None, :]]                           # [B,D,U+1]
        return jnp.where(t_ok[None], g, _NEG)

    blank_diag = to_diag(blank)
    emit_diag = to_diag(emit)

    def step_d(alpha_prev, d):
        # alpha_d[u] = logaddexp(alpha_{d-1}[u]   + blank_diag[d-1, u],
        #                        alpha_{d-1}[u-1] + emit_diag[d-1, u-1])
        # (the t=0 row falls out automatically: its t-1 parent sits at an
        # invalid diagonal slot already masked to -inf)
        horiz = alpha_prev + blank_diag[:, d - 1, :]
        diag = jnp.concatenate(
            [jnp.full((b, 1), _NEG),
             alpha_prev[:, :-1] + emit_diag[:, d - 1, :-1]], axis=1)
        alpha_d = jnp.logaddexp(horiz, diag)
        valid = (us[None] <= d) & (d - us[None] <= t_max - 1)
        alpha_d = jnp.where(valid, alpha_d, _NEG)
        return alpha_d, alpha_d

    alpha0 = jnp.full((b, u1), _NEG).at[:, 0].set(0.0)          # alpha[0,0]
    _, alphas = jax.lax.scan(step_d, alpha0, jnp.arange(1, n_diag))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)    # [D,B,U+1]

    # ll = alpha[f_len-1, y_len] + blank[f_len-1, y_len]; in diagonal
    # coordinates alpha[t, u] lives at (d = t + u, u)
    bi = jnp.arange(b)
    a_final = alphas[f_len - 1 + y_len, bi, y_len]
    ll = a_final + blank[bi, f_len - 1, y_len]
    return -ll


class TransducerLoss:
    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False, **_ignored):
        if packed_input:
            raise NotImplementedError("packed input is CUDA-layout only")

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
