"""ResNet bottleneck blocks, incl. spatially-parallel (halo) convolution.

Reference: apex/contrib/bottleneck/bottleneck.py — class Bottleneck (cuDNN
v8 fused conv+scale+relu graphs, N16) and class SpatialBottleneck (H-dim
sharded conv with peer-memory halo exchange; halo_exchangers.py). TPU:
XLA fuses conv+bn+relu on its own, so Bottleneck is a plain flax block kept
for API parity; SpatialBottleneck shards H over a mesh axis and calls
halo_exchange_1d around each 3x3 conv — the ppermute ride on ICI replaces
the IPC peer writes.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.contrib.peer_memory import halo_exchange_1d

__all__ = ["Bottleneck", "SpatialBottleneck"]


class Bottleneck(nn.Module):
    """ResNet v1.5 bottleneck, NHWC (reference: bottleneck.py — Bottleneck;
    the fused conv_bias_relu epilogues are XLA fusions here)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: Any = jnp.float32
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(self.norm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5)
        residual = x
        y = conv(self.bottleneck_channels, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.bottleneck_channels, (3, 3),
                 (self.stride, self.stride), padding=[(1, 1), (1, 1)],
                 name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.out_channels, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.out_channels, (1, 1),
                            (self.stride, self.stride),
                            name="downsample_conv")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class SpatialBottleneck(nn.Module):
    """Bottleneck with the H dimension sharded over ``axis_name``
    (reference: SpatialBottleneck + HaloExchangerPeer). Runs inside
    shard_map; each rank holds H/world rows and exchanges 1-row halos
    around the 3x3 conv."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    axis_name: str = "data"
    dtype: Any = jnp.float32
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(self.norm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5)
        residual = x
        y = conv(self.bottleneck_channels, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        # 3x3 with halo: pad H by the neighbours' rows, then VALID-conv in H
        y = halo_exchange_1d(y, 1, self.axis_name, dim=1)
        y = conv(self.bottleneck_channels, (3, 3),
                 (self.stride, self.stride),
                 padding=[(0, 0), (1, 1)], name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.out_channels, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.out_channels, (1, 1),
                            (self.stride, self.stride),
                            name="downsample_conv")(residual)
            residual = norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)
