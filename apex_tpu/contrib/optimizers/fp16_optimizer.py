"""Deprecated contrib FP16_Optimizer (API-parity surface).

Reference: apex/contrib/optimizers/fp16_optimizer.py — class FP16_Optimizer,
the deprecated wrapper that drove the old ``fused_adam_cuda``/
``fused_lamb_cuda`` extensions (SURVEY N7, behind
``--deprecated_fused_adam``). Upstream apex deprecates it in favor of
apex.fp16_utils.FP16_Optimizer / amp; this module preserves the import
path and forwards to the maintained implementation, whose semantics
(master weights, static/dynamic scaler, skip-on-overflow) already match —
the N7 kernels' math lives in the N2 superbuffer harness here.
"""

from __future__ import annotations

import warnings

from apex_tpu.fp16_utils import FP16_Optimizer as _FP16_Optimizer

__all__ = ["FP16_Optimizer"]


class FP16_Optimizer(_FP16_Optimizer):
    """Deprecated alias of :class:`apex_tpu.fp16_utils.FP16_Optimizer`
    (the reference prints the same deprecation pointer)."""

    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FP16_Optimizer is deprecated; use "
            "apex_tpu.fp16_utils.FP16_Optimizer or apex_tpu.amp",
            DeprecationWarning, stacklevel=2)
        super().__init__(*args, **kwargs)
