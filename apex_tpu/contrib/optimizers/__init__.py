"""Distributed (ZeRO-style) fused optimizers.

Reference: apex/contrib/optimizers/distributed_fused_adam.py — class
DistributedFusedAdam (~3000 LoC: grads reduce-scattered in buckets across the
DP group, each rank owns a shard of the fp32 optimizer state + master params,
params all-gathered after the step, with pipelined overlap) and
distributed_fused_lamb.py — class DistributedFusedLAMB (MLPerf BERT).

TPU design: the whole mechanism collapses to three collectives on the flat
superbuffer under shard_map over the ``data`` axis —
``psum_scatter(grads)`` → shard-local fused update on 1/world of the (m, v)
state → ``all_gather(updates)`` — which IS ZeRO-1/2 semantics; the
reference's bucketing/pipelining machinery exists to overlap NCCL with
backward, which XLA's scheduler does on its own. Outside shard_map (axis
unbound) they degrade to the single-process fused optimizers.

LAMB's per-tensor trust ratios are applied after the gather (they need whole
tensors); the state (m, v) stays fully sharded, matching the reference's
"each rank owns a state shard" memory profile. Its stage-1 math (global
grad-norm clip → moments → update direction) is identical to
apex_tpu.optimizers.fused_lamb for the same constructor args.

Checkpoint/topology changes (reference: DistributedFusedAdam.state_dict
reconstitution — SURVEY P32, §6 checkpoint (c)): state is checkpointed in
*concatenated* form (rank shards in order + old-world tail padding) and
re-partitioned for a new world size by :func:`reshard_zero_state`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu import comm
from apex_tpu.comm import AXIS_DATA
from apex_tpu.kernels.multi_tensor import fused_adam_step
from apex_tpu.optimizers.fused_adam import (_flat32, _lr_at, _unflatten_like)

__all__ = ["distributed_fused_adam", "distributed_fused_lamb",
           "DistributedFusedAdam", "DistributedFusedLAMB",
           "reshard_zero_state", "FP16_Optimizer", "FusedSGD"]

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], Any]]

# deprecated API-parity surface (reference: contrib/optimizers/
# fp16_optimizer.py + fused_sgd.py, SURVEY P32) — import lazily-cheap
# forwarding classes; each warns on construction
from apex_tpu.contrib.optimizers.fp16_optimizer import FP16_Optimizer  # noqa: E402,F401
from apex_tpu.contrib.optimizers.fused_sgd import FusedSGD  # noqa: E402,F401


class DistAdamState(NamedTuple):
    count: jnp.ndarray
    m_shard: jnp.ndarray   # fp32, [padded_n / world]
    v_shard: jnp.ndarray


def _axis_bound(axis_name):
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def _padded(n, world):
    return ((n + world - 1) // world) * world


def _num_params(params):
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def _zero_init(params, world):
    """Shard-sized zero (m, v) state — each rank owns padded_n/world."""
    shard = _padded(_num_params(params), world) // world
    return DistAdamState(count=jnp.zeros((), jnp.int32),
                         m_shard=jnp.zeros((shard,), jnp.float32),
                         v_shard=jnp.zeros((shard,), jnp.float32))


def _check_world(axis_name, world, opt_name):
    """Validate mesh-vs-state agreement; returns whether the update runs
    sharded. Trace-time axis size is authoritative: a mismatch against the
    shard-sized state (init used comm.axis_size/world_size) means the mesh
    changed between init and update — fail loud."""
    bound = _axis_bound(axis_name)
    if bound:
        traced_world = jax.lax.psum(1, axis_name)
        if isinstance(traced_world, int) and traced_world != world:
            raise ValueError(
                f"axis {axis_name!r} has size {traced_world} under "
                f"shard_map but optimizer state was initialized for "
                f"world {world}")
    elif world > 1:
        raise RuntimeError(
            f"{opt_name}(world_size={world}) must run inside "
            f"shard_map/pmap with axis {axis_name!r} bound; the "
            f"shard-sized state cannot be updated unsharded")
    return bound and world > 1


def _shard_grads_and_params(flat_g, flat_p, axis_name, world, sharded):
    """ZeRO entry: mean-reduce-scatter grads; slice own param shard."""
    if not sharded:
        return flat_g, flat_p
    g_shard = jax.lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                   tiled=True) / world
    rank = jax.lax.axis_index(axis_name)
    shard = flat_p.shape[0] // world
    p_shard = jax.lax.dynamic_slice_in_dim(flat_p, rank * shard, shard)
    return g_shard, p_shard


def distributed_fused_adam(
        learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9,
        beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
        adam_w_mode: bool = True, bias_correction: bool = True,
        axis_name: str = AXIS_DATA,
        world_size: Optional[int] = None) -> optax.GradientTransformation:
    """ZeRO-sharded fused Adam over ``axis_name``. The shard size comes
    from the installed mesh (comm.axis_size) or an explicit ``world_size``,
    so init (outside shard_map) and update (inside) agree; grads are
    per-rank local (the transformation does the mean-reduce-scatter itself,
    like the reference does its own reductions)."""

    def _world():
        return world_size if world_size is not None \
            else comm.axis_size(axis_name)

    def init_fn(params):
        return _zero_init(params, _world())

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        world = _world()
        sharded = _check_world(axis_name, world, "distributed_fused_adam")
        count = state.count + 1
        flat_p = _flat32(params)
        flat_g = _flat32(updates)
        n = flat_p.shape[0]
        pad = _padded(n, world) - n
        flat_p = jnp.pad(flat_p, (0, pad))
        flat_g = jnp.pad(flat_g, (0, pad))
        g_shard, p_shard = _shard_grads_and_params(
            flat_g, flat_p, axis_name, world, sharded)
        lr = _lr_at(learning_rate, count)
        new_p, new_m, new_v = fused_adam_step(
            p_shard, state.m_shard, state.v_shard, g_shard, lr=lr,
            beta1=beta1, beta2=beta2, eps=eps, weight_decay=weight_decay,
            step=count, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction)
        delta_shard = new_p - p_shard
        if sharded:
            delta = jax.lax.all_gather(delta_shard, axis_name, axis=0,
                                       tiled=True)
        else:
            delta = delta_shard
        new_updates = _unflatten_like(delta[:n], params)
        return new_updates, DistAdamState(count, new_m, new_v)

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_fused_lamb(
        learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9,
        beta2: float = 0.999, eps: float = 1e-6, weight_decay: float = 0.01,
        bias_correction: bool = True, grad_averaging: bool = True,
        max_grad_norm: float = 1.0, use_nvlamb: bool = False,
        max_coeff: float = 10.0, min_coeff: float = 0.01,
        axis_name: str = AXIS_DATA,
        world_size: Optional[int] = None) -> optax.GradientTransformation:
    """ZeRO-sharded LAMB (reference: DistributedFusedLAMB). The stage-1 math
    (global-grad-norm clip → moments → Adam-style update direction) is
    IDENTICAL to :func:`apex_tpu.optimizers.fused_lamb` for the same
    constructor args — the reference kernel is the same multi_tensor_lamb.cu
    either way; only the state placement differs. Moments live sharded
    (each rank owns 1/world of fp32 m, v); the per-tensor trust ratio runs
    post-gather because it needs whole-tensor norms (NVLAMB stage 2 /
    LAMBStage2Functor). ``max_coeff``/``min_coeff`` bound the trust ratio
    (the reference DistributedFusedLAMB constructor args of the same names);
    ``use_nvlamb=False`` forces ratio 1.0 for undecayed params exactly as
    fused_lamb does.

    Grad-norm clip note: the clip stage sees the *mean* gradient (grads are
    reduce-scatter-averaged first), so the clipped quantity matches the
    single-process fused_lamb applied to the DP-mean gradient — the
    reference's clipped_global_grad_norm over the reduced grads."""

    def _world():
        return world_size if world_size is not None \
            else comm.axis_size(axis_name)

    def init_fn(params):
        return _zero_init(params, _world())

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_lamb requires params")
        world = _world()
        sharded = _check_world(axis_name, world, "distributed_fused_lamb")
        count = state.count + 1
        countf = count.astype(jnp.float32)
        lr = _lr_at(learning_rate, count)
        flat_p = _flat32(params)
        flat_g = _flat32(updates)
        n = flat_p.shape[0]
        pad = _padded(n, world) - n
        flat_p = jnp.pad(flat_p, (0, pad))
        flat_g = jnp.pad(flat_g, (0, pad))
        g_shard, p_shard = _shard_grads_and_params(
            flat_g, flat_p, axis_name, world, sharded)

        # stage 0: global-norm clip of the (mean) gradient — the kernel's
        # clipped_global_grad_norm; padding contributes zeros to the norm
        local_sq = jnp.sum(g_shard * g_shard)
        global_sq = jax.lax.psum(local_sq, axis_name) if sharded else local_sq
        global_norm = jnp.sqrt(global_sq)
        clip = jnp.where(global_norm > max_grad_norm,
                         global_norm / max_grad_norm, 1.0)
        g_shard = g_shard / clip

        # stage 1 on the shard: moments + Adam-style update direction
        beta1_grad = (1.0 - beta1) if grad_averaging else 1.0
        m_new = beta1 * state.m_shard + beta1_grad * g_shard
        v_new = beta2 * state.v_shard + (1.0 - beta2) * g_shard * g_shard
        if bias_correction:
            bc1 = 1.0 - beta1 ** countf
            bc2 = 1.0 - beta2 ** countf
        else:
            bc1 = bc2 = 1.0
        u_shard = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) \
            + weight_decay * p_shard

        if sharded:
            u = jax.lax.all_gather(u_shard, axis_name, axis=0, tiled=True)
        else:
            u = u_shard
        # unflatten into an fp32 tree: the update direction must stay fp32
        # through the norm/ratio stage (half params would otherwise quantize
        # it before u_norm, breaking parity with fused_lamb)
        f32_tmpl = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32), params)
        update_tree = _unflatten_like(u[:n], f32_tmpl)

        # stage 2 per tensor: trust ratio on whole-tensor norms
        def per_tensor(u32, p):
            p32 = jnp.asarray(p, jnp.float32)
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(u32 * u32))
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            if weight_decay == 0.0 and not use_nvlamb:
                ratio = 1.0  # fused_lamb parity: no ratio for undecayed
            return (-lr * ratio * u32).astype(jnp.asarray(p).dtype)

        delta = jax.tree_util.tree_map(per_tensor, update_tree, params)
        return delta, DistAdamState(count, m_new, v_new)

    return optax.GradientTransformation(init_fn, update_fn)


def reshard_zero_state(state: DistAdamState, num_params: int,
                       new_world: int,
                       old_world: Optional[int] = None) -> DistAdamState:
    """Re-partition concatenated ZeRO optimizer state for a different world
    size (reference: DistributedFusedAdam.state_dict/load_state_dict
    reconstitute sharded state across topology changes — SURVEY P32, §6
    checkpoint (c)).

    ``state`` holds the *concatenated* shards (the representation produced
    by gathering with out_specs=P(axis) — rank shards in order, old-world
    padding at the tail). Strips the old padding, re-pads for ``new_world``.
    Pass ``old_world`` when known: the expected concatenated length is then
    checked exactly, catching a per-rank shard passed by mistake even when
    the shard happens to be longer than ``num_params``.
    """
    def repad(flat):
        if old_world is not None:
            expect = _padded(num_params, old_world)
            if flat.shape[0] != expect:
                raise ValueError(
                    f"state of length {flat.shape[0]} is not the "
                    f"concatenated world-{old_world} state for "
                    f"{num_params} params (expected {expect}) — gather "
                    f"shards (out_specs=P(axis)) before resharding")
        elif flat.shape[0] < num_params:
            raise ValueError(
                f"state of length {flat.shape[0]} is a single shard, not "
                f"the concatenated state for {num_params} params — gather "
                f"shards (out_specs=P(axis)) before resharding")
        flat = flat[:num_params]
        return jnp.pad(flat, (0, _padded(num_params, new_world) - num_params))

    return DistAdamState(count=state.count, m_shard=repad(state.m_shard),
                         v_shard=repad(state.v_shard))


class _DistributedOptimizer:
    """Shared wrapper behavior: step, and topology-aware checkpointing.

    ``state_dict``/``load_state_dict`` mirror the reference's state
    reconstitution. The checkpointable representation is the CONCATENATED
    state: at world 1 that is what the instance holds; at world>1 the caller
    must first gather the per-rank shards (out_specs=P(axis)) and assign the
    result back to ``.state`` — ``state_dict`` verifies the length and
    refuses a single shard. ``load_state_dict`` rebuilds the transformation
    for the new world so subsequent shard sizes agree with the restored
    state (a stale world here would trip _check_world on the next step).
    """

    def _setup(self, params, axis_name, world_size, factory, factory_kwargs):
        self._axis_name = axis_name
        self._factory = factory
        self._factory_kwargs = factory_kwargs
        self._world = world_size if world_size is not None \
            else comm.axis_size(axis_name)
        self.tx = factory(axis_name=axis_name, world_size=self._world,
                          **factory_kwargs)
        self.state = self.tx.init(params)
        self._num_params = _num_params(params)

    def step(self, grads, params):
        upd, self.state = self.tx.update(grads, self.state, params)
        return optax.apply_updates(params, upd)

    def state_dict(self):
        expect = _padded(self._num_params, self._world)
        if self.state.m_shard.shape[0] != expect:
            raise ValueError(
                f"state holds a per-rank shard of length "
                f"{self.state.m_shard.shape[0]}; checkpointing at world "
                f"{self._world} requires the concatenated state of length "
                f"{expect} — gather shards (out_specs=P(axis)) and assign "
                f"to .state first")
        return {"state": self.state, "num_params": self._num_params,
                "world": self._world}

    def load_state_dict(self, sd, new_world: int):
        self._world = new_world
        self.tx = self._factory(axis_name=self._axis_name,
                                world_size=new_world,
                                **self._factory_kwargs)
        self.state = reshard_zero_state(sd["state"], sd["num_params"],
                                        new_world, old_world=sd["world"])


class DistributedFusedAdam(_DistributedOptimizer):
    """Class-shaped wrapper mirroring the reference constructor; holds the
    optax transformation plus step/init helpers."""

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                 axis_name: str = AXIS_DATA, world_size=None, **_ignored):
        self._setup(params, axis_name, world_size, distributed_fused_adam,
                    dict(learning_rate=lr, beta1=betas[0], beta2=betas[1],
                         eps=eps, weight_decay=weight_decay,
                         adam_w_mode=adam_w_mode,
                         bias_correction=bias_correction))


class DistributedFusedLAMB(_DistributedOptimizer):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 grad_averaging=True, max_grad_norm=1.0, use_nvlamb=False,
                 max_coeff=10.0, min_coeff=0.01,
                 axis_name: str = AXIS_DATA, world_size=None, **_ignored):
        self._setup(params, axis_name, world_size, distributed_fused_lamb,
                    dict(learning_rate=lr, beta1=betas[0], beta2=betas[1],
                         eps=eps, weight_decay=weight_decay,
                         bias_correction=bias_correction,
                         grad_averaging=grad_averaging,
                         max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb,
                         max_coeff=max_coeff, min_coeff=min_coeff))
