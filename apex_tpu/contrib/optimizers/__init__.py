"""Distributed (ZeRO-style) fused optimizers.

Reference: apex/contrib/optimizers/distributed_fused_adam.py — class
DistributedFusedAdam (~3000 LoC: grads reduce-scattered in buckets across the
DP group, each rank owns a shard of the fp32 optimizer state + master params,
params all-gathered after the step, with pipelined overlap) and
distributed_fused_lamb.py — class DistributedFusedLAMB (MLPerf BERT).

TPU design: the whole mechanism collapses to three collectives on the flat
superbuffer under shard_map over the ``data`` axis —
``psum_scatter(grads)`` → shard-local fused update on 1/world of the (m, v)
state → ``all_gather(updates)`` — which IS ZeRO-1/2 semantics; the
reference's bucketing/pipelining machinery exists to overlap NCCL with
backward, which XLA's scheduler does on its own. Outside shard_map (axis
unbound) they degrade to the single-process fused optimizers.

LAMB's per-tensor trust ratios are applied after the gather (they need whole
tensors); the state (m, v) stays fully sharded, matching the reference's
"each rank owns a state shard" memory profile.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu import comm
from apex_tpu.comm import AXIS_DATA
from apex_tpu.kernels.multi_tensor import fused_adam_step
from apex_tpu.optimizers.fused_adam import (_flat32, _lr_at, _unflatten_like)

__all__ = ["distributed_fused_adam", "distributed_fused_lamb",
           "DistributedFusedAdam", "DistributedFusedLAMB"]

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], Any]]


class DistAdamState(NamedTuple):
    count: jnp.ndarray
    m_shard: jnp.ndarray   # fp32, [padded_n / world]
    v_shard: jnp.ndarray


def _axis_bound(axis_name):
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def _padded(n, world):
    return ((n + world - 1) // world) * world


def distributed_fused_adam(
        learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9,
        beta2: float = 0.999, eps: float = 1e-8, weight_decay: float = 0.0,
        adam_w_mode: bool = True, bias_correction: bool = True,
        axis_name: str = AXIS_DATA,
        world_size: Optional[int] = None) -> optax.GradientTransformation:
    """ZeRO-sharded fused Adam over ``axis_name``. The shard size comes
    from the installed mesh (comm.axis_size) or an explicit ``world_size``,
    so init (outside shard_map) and update (inside) agree; grads are
    per-rank local (the transformation does the mean-reduce-scatter itself,
    like the reference does its own reductions)."""

    def _world():
        return world_size if world_size is not None \
            else comm.axis_size(axis_name)

    def init_fn(params):
        n = sum(x.size for x in jax.tree_util.tree_leaves(params))
        world = _world()
        shard = _padded(n, world) // world
        return DistAdamState(count=jnp.zeros((), jnp.int32),
                             m_shard=jnp.zeros((shard,), jnp.float32),
                             v_shard=jnp.zeros((shard,), jnp.float32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        world = _world()
        bound = _axis_bound(axis_name)
        if bound:
            # trace-time axis size is authoritative; a mismatch against the
            # shard-sized state (init used comm.axis_size/world_size) means
            # the mesh changed between init and update — fail loud.
            traced_world = jax.lax.psum(1, axis_name)
            if isinstance(traced_world, int) and traced_world != world:
                raise ValueError(
                    f"axis {axis_name!r} has size {traced_world} under "
                    f"shard_map but optimizer state was initialized for "
                    f"world {world}")
        elif world > 1:
            raise RuntimeError(
                f"distributed_fused_adam(world_size={world}) must run "
                f"inside shard_map/pmap with axis {axis_name!r} bound; the "
                f"shard-sized state cannot be updated unsharded")
        count = state.count + 1
        flat_p = _flat32(params)
        flat_g = _flat32(updates)
        n = flat_p.shape[0]
        pn = _padded(n, world)
        pad = pn - n
        flat_p = jnp.pad(flat_p, (0, pad))
        flat_g = jnp.pad(flat_g, (0, pad))
        if bound and world > 1:
            # ZeRO: mean-reduce-scatter grads; slice own param shard
            g_shard = jax.lax.psum_scatter(flat_g, axis_name,
                                           scatter_dimension=0,
                                           tiled=True) / world
            rank = jax.lax.axis_index(axis_name)
            shard = pn // world
            p_shard = jax.lax.dynamic_slice_in_dim(flat_p, rank * shard,
                                                   shard)
        else:
            g_shard, p_shard = flat_g, flat_p
        lr = _lr_at(learning_rate, count)
        new_p, new_m, new_v = fused_adam_step(
            p_shard, state.m_shard, state.v_shard, g_shard, lr=lr,
            beta1=beta1, beta2=beta2, eps=eps, weight_decay=weight_decay,
            step=count, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction)
        delta_shard = new_p - p_shard
        if bound and world > 1:
            delta = jax.lax.all_gather(delta_shard, axis_name, axis=0,
                                       tiled=True)
        else:
            delta = delta_shard
        delta = delta[:n]
        new_updates = _unflatten_like(delta, params)
        return new_updates, DistAdamState(count, new_m, new_v)

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_fused_lamb(
        learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9,
        beta2: float = 0.999, eps: float = 1e-6, weight_decay: float = 0.01,
        max_coeff: float = 10.0, min_coeff: float = 0.01,
        axis_name: str = AXIS_DATA) -> optax.GradientTransformation:
    """ZeRO-sharded LAMB (reference: DistributedFusedLAMB). Sharded Adam-ish
    moment update; trust ratio per tensor applied post-gather, matching
    NVLAMB stage-2 (multi_tensor_lamb's per-chunk ratio application)."""

    base = distributed_fused_adam(
        learning_rate=1.0,  # lr applied inside trust-ratio stage
        beta1=beta1, beta2=beta2, eps=eps, weight_decay=weight_decay,
        adam_w_mode=True, bias_correction=True, axis_name=axis_name)

    def init_fn(params):
        return base.init(params)

    def update_fn(updates, state, params=None):
        raw_updates, new_state = base.update(updates, state, params)
        lr = _lr_at(learning_rate, new_state.count)

        def per_tensor(u, p):
            p32 = jnp.asarray(p, jnp.float32)
            u32 = jnp.asarray(u, jnp.float32)
            w_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(u32 * u32))
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            return (lr * ratio * u32).astype(jnp.asarray(u).dtype)

        scaled = jax.tree_util.tree_map(per_tensor, raw_updates, params)
        return scaled, new_state

    return optax.GradientTransformation(init_fn, update_fn)


class DistributedFusedAdam:
    """Class-shaped wrapper mirroring the reference constructor; holds the
    optax transformation plus step/init helpers."""

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                 axis_name: str = AXIS_DATA, **_ignored):
        self.tx = distributed_fused_adam(
            lr, betas[0], betas[1], eps, weight_decay, adam_w_mode,
            bias_correction, axis_name)
        self.state = self.tx.init(params)

    def step(self, grads, params):
        upd, self.state = self.tx.update(grads, self.state, params)
        return optax.apply_updates(params, upd)


class DistributedFusedLAMB:
    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-6,
                 weight_decay=0.01, max_coeff=10.0, min_coeff=0.01,
                 axis_name: str = AXIS_DATA, **_ignored):
        self.tx = distributed_fused_lamb(
            lr, betas[0], betas[1], eps, weight_decay, max_coeff, min_coeff,
            axis_name)
        self.state = self.tx.init(params)

    def step(self, grads, params):
        upd, self.state = self.tx.update(grads, self.state, params)
        return optax.apply_updates(params, upd)
