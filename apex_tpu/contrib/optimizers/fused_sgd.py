"""Deprecated contrib FusedSGD (API-parity surface).

Reference: apex/contrib/optimizers/fused_sgd.py — the deprecated FusedSGD
variant kept for old recipes (SURVEY P32). Forwards to the maintained
apex_tpu.optimizers.FusedSGD, which implements the same multi_tensor_sgd
semantics (momentum, wd_after_momentum, materialize_master_grads) on the
superbuffer harness.
"""

from __future__ import annotations

import warnings

from apex_tpu.optimizers.fused_sgd import FusedSGD as _FusedSGD
from apex_tpu.optimizers.fused_sgd import fused_sgd  # noqa: F401

__all__ = ["FusedSGD", "fused_sgd"]


class FusedSGD(_FusedSGD):
    """Deprecated alias of :class:`apex_tpu.optimizers.FusedSGD`."""

    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FusedSGD is deprecated; use "
            "apex_tpu.optimizers.FusedSGD",
            DeprecationWarning, stacklevel=2)
        super().__init__(*args, **kwargs)
