"""Group (multi-device) BatchNorm, NHWC, with fused add+ReLU.

Reference: apex/contrib/groupbn/batch_norm.py — class BatchNorm2d_NHWC
(``bnp`` cuDNN NHWC kernels with fused residual-add+ReLU and cross-GPU
"group" stat exchange, N14/N22). TPU mapping (SURVEY §3.2): SyncBatchNorm's
Welford-psum covers the stat exchange; this module adds the fused
add+ReLU epilogue the MLPerf ResNet blocks use.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["BatchNorm2d_NHWC"]


class BatchNorm2d_NHWC(nn.Module):
    """BN over NHWC with optional fused residual add + ReLU
    (reference: bn_addrelu path selected by fuse_relu/bn_group kwargs).
    ``bn_group`` > 1 syncs stats over ``axis_name`` (the reference's
    group-of-GPUs semantic; here the mesh axis defines the group)."""

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    use_running_average: Optional[bool] = None

    @nn.compact
    def __call__(self, x, z=None, use_running_average: Optional[bool] = None):
        if self.bn_group > 1 and self.axis_name is None:
            raise ValueError(
                "bn_group > 1 requires axis_name (the mesh axis defining "
                "the sync group); otherwise stats would silently stay local")
        axis = self.axis_name if self.bn_group > 1 else None
        y = SyncBatchNorm(
            use_running_average=self.use_running_average
            if use_running_average is None else use_running_average,
            momentum=self.momentum, epsilon=self.epsilon, dtype=self.dtype,
            axis_name=axis, name="bn")(x)
        if z is not None:
            y = y + jnp.asarray(z, y.dtype)   # fused residual add
        if self.fuse_relu:
            y = jnp.maximum(y, 0)
        return y
