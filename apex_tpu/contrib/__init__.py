"""apex_tpu.contrib — production kernel grab-bag (reference: apex/contrib/).

Each subpackage mirrors one reference contrib package. Where the reference
gates on "extension built?" (SkipTest on ImportError), the TPU equivalents
are always available — the Pallas kernels fall back to jnp paths off the
aligned hot path.
"""

from . import bottleneck  # noqa: F401
from . import clip_grad  # noqa: F401
from . import conv_bias_relu  # noqa: F401
from . import cudnn_gbn  # noqa: F401
from . import fmha  # noqa: F401
from . import focal_loss  # noqa: F401
from . import gpu_direct_storage  # noqa: F401
from . import group_norm  # noqa: F401
from . import groupbn  # noqa: F401
from . import index_mul_2d  # noqa: F401
from . import layer_norm  # noqa: F401
from . import multihead_attn  # noqa: F401
from . import nccl_allocator  # noqa: F401
from . import openfold_triton  # noqa: F401
from . import optimizers  # noqa: F401
from . import peer_memory  # noqa: F401
from . import sparsity  # noqa: F401
from . import transducer  # noqa: F401
from . import xentropy  # noqa: F401

__all__ = ["bottleneck", "clip_grad", "conv_bias_relu", "cudnn_gbn", "fmha",
           "focal_loss", "gpu_direct_storage", "group_norm", "groupbn",
           "index_mul_2d", "layer_norm", "multihead_attn", "nccl_allocator",
           "openfold_triton", "optimizers", "peer_memory", "sparsity",
           "transducer", "xentropy"]
