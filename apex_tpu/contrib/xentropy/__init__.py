"""Fused softmax cross-entropy with label smoothing.

Reference: apex/contrib/xentropy/softmax_xentropy.py — class
SoftmaxCrossEntropyLoss (calls xentropy_cuda.forward/backward). The kernel
lives in apex_tpu.kernels.xentropy; this wrapper keeps the reference's
call shape (padding index, half-to-float option).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.kernels.xentropy import (
    softmax_cross_entropy_loss as _kernel_xent)

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]


def softmax_cross_entropy_loss(logits, labels, smoothing: float = 0.0):
    """Policy-aware CE: 'cross_entropy' is an FP32_FUNCS entry. The kernel
    already does fp32 math internally for any input dtype, so honoring the
    O1 table only means pinning the *observable* loss dtype — cast the [N]
    losses, never the [N, V] logits (an fp32 logits copy would be the
    largest tensor in an LM step for zero numerical effect)."""
    from apex_tpu.amp.autocast import op_compute_dtype

    losses = _kernel_xent(logits, labels, smoothing=smoothing)
    target = op_compute_dtype("cross_entropy")
    if target is not None:
        losses = jnp.asarray(losses, target)
    return losses


class SoftmaxCrossEntropyLoss:
    """Callable matching the reference autograd Function's apply signature:
    ``SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing, padding_idx,
    half_to_float)``."""

    @staticmethod
    def apply(logits, labels, smoothing: float = 0.0, padding_idx: int = 0,
              half_to_float: bool = False):
        losses = softmax_cross_entropy_loss(logits, labels,
                                            smoothing=smoothing)
        if padding_idx is not None:
            # reference zeroes losses at padded positions (labels == padding
            # treated as ignore when padding_idx >= 0 in caller recipes)
            losses = jnp.where(labels == padding_idx,
                               jnp.zeros_like(losses), losses) \
                if padding_idx >= 0 else losses
        if half_to_float:
            losses = jnp.asarray(losses, jnp.float32)
        return losses

    def __call__(self, logits, labels, smoothing: float = 0.0):
        return softmax_cross_entropy_loss(logits, labels, smoothing=smoothing)
