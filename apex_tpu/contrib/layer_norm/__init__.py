"""'Fast layer norm' (persistent LN to 64k hidden).

Reference: apex/contrib/layer_norm/layer_norm.py — class FastLayerNorm
(fast_layer_norm.ln_fwd/ln_bwd). The SURVEY §3.2 N13 mapping folds this into
the one Pallas LN kernel (row-blocked over hidden), so FastLayerNorm is the
FusedLayerNorm module under the contrib name.
"""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm

__all__ = ["FastLayerNorm"]
