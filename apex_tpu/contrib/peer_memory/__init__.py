"""Halo exchange for spatial parallelism.

Reference: apex/contrib/peer_memory/peer_memory.py — class PeerMemoryPool
(CUDA IPC buffers) + class PeerHaloExchanger1d (direct P2P stores of halo
rows, N17). On TPU there are no user-managed peer buffers — XLA owns all
memory and ``ppermute`` IS the direct chip-to-chip write over ICI (SURVEY
§3.2 N17 mapping) — so the pool is not needed and the exchanger is a
function. The reference's ``nccl_p2p`` fallback (N18) is the same call.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.comm import AXIS_DATA

__all__ = ["halo_exchange_1d", "PeerHaloExchanger1d"]


def halo_exchange_1d(x, halo: int, axis_name: str, *, dim: int = 1,
                     wrap: bool = False):
    """Exchange ``halo`` rows along spatial ``dim`` with both mesh-axis
    neighbours; returns x padded to size + 2*halo along ``dim``.

    Matches PeerHaloExchanger1d semantics: each rank sends its top rows to
    the previous rank's bottom halo and its bottom rows to the next rank's
    top halo; edge ranks get zeros unless ``wrap``.
    """
    try:
        world = jax.lax.psum(1, axis_name)
    except NameError as e:
        raise RuntimeError("halo_exchange_1d must run under shard_map with "
                           f"axis {axis_name!r} bound") from e
    rank = jax.lax.axis_index(axis_name)

    top = jax.lax.slice_in_dim(x, 0, halo, axis=dim)
    bot = jax.lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim],
                               axis=dim)
    perm_fwd = [(i, (i + 1) % world) for i in range(world)]
    perm_bwd = [(i, (i - 1) % world) for i in range(world)]
    from_prev = jax.lax.ppermute(bot, axis_name, perm_fwd)   # prev's bottom
    from_next = jax.lax.ppermute(top, axis_name, perm_bwd)   # next's top
    if not wrap:
        zero = jnp.zeros_like(from_prev)
        from_prev = jnp.where(rank == 0, zero, from_prev)
        from_next = jnp.where(rank == world - 1, zero, from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=dim)


class PeerHaloExchanger1d:
    """Reference ctor: PeerHaloExchanger1d(ranks, rank_in_group, pool,
    half_halo). Pool is meaningless on TPU; kept kwargs ignored."""

    def __init__(self, axis_name: str = AXIS_DATA, half_halo: int = 1,
                 **_ignored):
        self.axis_name = axis_name
        self.half_halo = half_halo

    def __call__(self, x, dim: int = 1, wrap: bool = False):
        return halo_exchange_1d(x, self.half_halo, self.axis_name, dim=dim,
                                wrap=wrap)
