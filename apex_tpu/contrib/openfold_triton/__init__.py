"""OpenFold fused kernels — TPU equivalents of the Triton set.

Reference: apex/contrib/openfold_triton/ — Triton kernels used by the
OpenFold (AlphaFold2) MLPerf submission: fused LayerNorm variants and a
fused multi-head attention for the evoformer's gated attention
(SURVEY P37 [vintage?]). TPU mapping: LayerNorm binds to the Pallas kernel
(kernels/layer_norm.py); the evoformer attention rides the Pallas flash
kernel's additive-bias path (kernels/flash_attention.py — ``bias=``) at
block-aligned shapes, falling back to the fp32 jnp reference otherwise —
either way the pair bias is added to the scaled logits pre-softmax and the
sigmoid gate multiplies the output, per the evoformer block.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention
from apex_tpu.kernels.layer_norm import layer_norm

__all__ = ["LayerNormSmallShapeOptImpl", "layer_norm_small",
           "evoformer_attention"]


def layer_norm_small(x, weight, bias, eps: float = 1e-5):
    """Reference: LayerNormSmallShapeOptImpl — the small-hidden fast path.
    The Pallas LN already blocks over hidden; one entry covers all shapes."""
    return layer_norm(x, weight, bias, eps=eps)


LayerNormSmallShapeOptImpl = layer_norm_small


def evoformer_attention(q, k, v, bias: Optional[jnp.ndarray] = None,
                        gate: Optional[jnp.ndarray] = None,
                        scale: Optional[float] = None):
    """Gated, pair-biased MHA (reference: openfold_triton MHA). q/k/v are
    [..., heads, seq, head_dim] — OpenFold's evoformer passes 5D tensors
    like [batch, n_seq, heads, n_res, c], so arbitrary leading dims are
    collapsed into the kernel's batch; ``bias`` broadcasts onto the
    [..., heads, q_len, k_len] logits; ``gate`` (same shape as the output)
    is passed through a sigmoid and multiplied in, per the evoformer block.
    Rides the blockwise flash kernel (bias path) when shapes are
    block-aligned."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    *lead, h, s, d = q.shape
    sk = k.shape[-2]
    batch = 1
    for n in lead:
        batch *= n
    q4 = q.reshape(batch, h, s, d)
    k4 = k.reshape(batch, h, sk, d)
    v4 = v.reshape(batch, h, sk, v.shape[-1])
    bias4 = None
    if bias is not None:
        # normalize to rank len(lead)+3; leading dims must be 1 or match —
        # all-1 stays size-1 (kernel broadcasts over batch, no copy), a full
        # match collapses, anything mixed is materialized by broadcast
        want = tuple(lead) + (bias.shape[-3],) + (s, sk)
        bias = jnp.reshape(bias, (1,) * (len(want) - bias.ndim) + bias.shape)
        blead = bias.shape[:-3]
        if all(n == 1 for n in blead):
            bias4 = bias.reshape(1, *bias.shape[-3:])
        elif blead == tuple(lead):
            bias4 = bias.reshape(batch, *bias.shape[-3:])
        else:
            bias4 = jnp.broadcast_to(
                bias, tuple(lead) + bias.shape[-3:]).reshape(
                    batch, *bias.shape[-3:])
    out = flash_attention(q4, k4, v4, scale=scale, bias=bias4)
    out = out.reshape(*lead, h, s, v.shape[-1])
    if gate is not None:
        out = out * jax.nn.sigmoid(jnp.asarray(gate, out.dtype))
    return out
