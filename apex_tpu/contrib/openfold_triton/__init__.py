"""OpenFold fused kernels — TPU equivalents of the Triton set.

Reference: apex/contrib/openfold_triton/ — Triton kernels used by the
OpenFold (AlphaFold2) MLPerf submission: fused LayerNorm variants and a
fused multi-head attention for the evoformer's gated attention
(SURVEY P37 [vintage?]). TPU mapping: LayerNorm binds to the Pallas kernel
(kernels/layer_norm.py); the evoformer attention is plain fused-by-XLA
attention — it materializes the [..., heads, q, k] logits in fp32, which is
the right call at evoformer sequence lengths (hundreds of residues); for
long-sequence attention use kernels/flash_attention.py, which is blockwise
but has no pair-bias input.

``AttnBiasJIT``-style evoformer attention takes a pair bias term added to
the logits pre-softmax and a sigmoid gate on the output.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.kernels.layer_norm import layer_norm

__all__ = ["LayerNormSmallShapeOptImpl", "layer_norm_small",
           "evoformer_attention"]


def layer_norm_small(x, weight, bias, eps: float = 1e-5):
    """Reference: LayerNormSmallShapeOptImpl — the small-hidden fast path.
    The Pallas LN already blocks over hidden; one entry covers all shapes."""
    return layer_norm(x, weight, bias, eps=eps)


LayerNormSmallShapeOptImpl = layer_norm_small


def evoformer_attention(q, k, v, bias: Optional[jnp.ndarray] = None,
                        gate: Optional[jnp.ndarray] = None,
                        scale: Optional[float] = None):
    """Gated, pair-biased MHA (reference: openfold_triton MHA). q/k/v are
    [..., heads, seq, head_dim]; ``bias`` broadcasts onto the [..., heads,
    q_len, k_len] logits; ``gate`` (same shape as the output) is passed
    through a sigmoid and multiplied in, per the evoformer block."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("...qd,...kd->...qk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + jnp.asarray(bias, logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("...qk,...kd->...qd", probs.astype(v.dtype), v)
    if gate is not None:
        out = out * jax.nn.sigmoid(jnp.asarray(gate, out.dtype))
    return out
