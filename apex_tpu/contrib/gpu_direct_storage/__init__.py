"""Direct device↔disk tensor I/O (GDS flavor) over the async host path.

Reference: apex/contrib/csrc/gpu_direct_storage/ — cuFile-based
``save_data``/``load_data`` moving tensors GPU↔disk without a host bounce
(SURVEY N24). TPU mapping (SURVEY §3.2 N24): there is no user-controlled DMA
path to disk on TPU — the equivalent is the same host-staging copy the
checkpoint pipeline uses: device→host, then one guaranteed-copy pass through
``utils.pytree.host_flatten`` (the native ``apex_tpu._C`` GIL-released
memcpy when the extension is built), then a single contiguous write. This
module keeps the reference's flat per-tensor save/load surface on top of
that path; whole-pytree and overlapped-with-training saves live in
``utils/checkpoint.py — AsyncCheckpointer``.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from apex_tpu.utils.pytree import host_flatten

__all__ = ["save_data", "load_data", "save_data_no_gds", "load_data_no_gds"]


def save_data(filename: str, tensor: Any) -> None:
    """Reference: gds.save_data(filename, tensor) — direct-to-disk write.
    Device→host transfer, guaranteed-copy staging (np.asarray of a
    CPU-backend jax array can alias the XLA buffer — see
    utils/checkpoint._snapshot), then a single contiguous write.

    Stored as npz of (raw bytes, dtype name, shape): ml_dtypes such as
    bfloat16 — the default AMP dtype on TPU — do not round-trip through the
    plain npy descr (they serialize as void and refuse to cast back)."""
    arr = np.asarray(jax.device_get(tensor))
    arr = host_flatten([arr]).reshape(arr.shape)
    raw = arr.reshape(-1).view(np.uint8)
    tmp = f"{filename}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, raw=raw, dtype=np.str_(arr.dtype.name),
                 shape=np.asarray(arr.shape, np.int64))
    os.replace(tmp, filename)


def load_data(filename: str, tensor: Any) -> Any:
    """Reference: gds.load_data(filename, tensor) — reads INTO the passed
    tensor, so shape AND dtype must match exactly (a mismatch is a hard
    error, never a silent cast). Functional here: returns the loaded array
    placed on the argument's device."""
    with np.load(filename) as z:
        dtype = np.dtype(str(z["dtype"]))
        shape = tuple(int(d) for d in z["shape"])
        arr = z["raw"].view(dtype).reshape(shape)
    want_shape = getattr(tensor, "shape", None)
    want_dtype = getattr(tensor, "dtype", None)
    if want_shape is not None and shape != tuple(want_shape):
        raise ValueError(
            f"load_data: file shape {shape} != tensor shape {want_shape}")
    if want_dtype is not None and dtype != np.dtype(want_dtype):
        raise ValueError(
            f"load_data: file dtype {dtype} != tensor dtype {want_dtype}")
    dev = None
    try:
        dev = list(getattr(tensor, "devices", lambda: [])())[0]
    except (IndexError, TypeError):
        pass
    return jax.device_put(arr, dev) if dev is not None else jax.device_put(arr)


# The reference exposes explicit bounce-buffer variants for comparison
# benchmarks; on TPU both paths are the same host-staged copy.
save_data_no_gds = save_data
load_data_no_gds = load_data
