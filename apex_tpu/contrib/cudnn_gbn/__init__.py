"""Group BatchNorm (cuDNN-graph flavor) — NHWC, multi-device stat groups.

Reference: apex/contrib/cudnn_gbn/batch_norm.py — class GroupBatchNorm
(``cudnn_gbn_lib`` fused graphs, SURVEY N22). TPU mapping (SURVEY §3.2 N22):
"covered by SyncBN psum" — the stat exchange is a Welford psum over the mesh
axis and XLA fuses the normalize+affine epilogue, so this module is a
signature-parity front over :mod:`apex_tpu.contrib.groupbn`.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

__all__ = ["GroupBatchNorm2d"]


class GroupBatchNorm2d(nn.Module):
    """Reference signature: GroupBatchNorm2d(num_features, group_size=...).
    ``group_size`` > 1 syncs stats across ``axis_name`` (the mesh axis is the
    device group; subgroup selection is the axis_index_groups mechanism on
    SyncBatchNorm — see parallel/sync_batchnorm.create_syncbn_process_group).
    """

    num_features: int
    group_size: int = 1
    axis_name: Optional[str] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    use_running_average: Optional[bool] = None

    @nn.compact
    def __call__(self, x, z=None, use_running_average: Optional[bool] = None):
        return BatchNorm2d_NHWC(
            num_features=self.num_features,
            fuse_relu=False,
            bn_group=self.group_size,
            axis_name=self.axis_name,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            use_running_average=self.use_running_average
            if use_running_average is None else use_running_average,
            name="gbn")(x, z=z)
