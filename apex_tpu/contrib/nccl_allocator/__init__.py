"""NCCL-registered buffer allocator — API-parity no-op on TPU.

Reference: apex/contrib/nccl_allocator/NCCLAllocator.cpp — ``init()`` installs
a pluggable CUDA allocator backed by ``ncclMemAlloc`` and ``nccl_mem()`` is a
context manager under which tensor allocations land in NCCL-registered
(user-buffer) memory, letting NCCL skip staging copies (SURVEY N24).

TPU mapping (SURVEY §3.2 N24): "n/a on TPU (XLA owns buffers)" — every XLA
buffer is already placed and registered by the runtime, and ICI collectives
operate on device buffers directly; there is no user-visible allocator to
swap. The API is preserved so reference callers run unchanged: ``init()``
records availability, ``nccl_mem()`` is a no-op context manager, and both
warn once at first use that registration is implicit on this backend.
"""

from __future__ import annotations

import contextlib
import warnings

__all__ = ["init", "nccl_mem", "is_initialized"]

_initialized = False
_warned = False


def _warn_once():
    global _warned
    if not _warned:
        warnings.warn(
            "apex_tpu.contrib.nccl_allocator: buffer registration is "
            "implicit under XLA (the runtime owns and registers all device "
            "buffers); init()/nccl_mem() are no-ops kept for API parity.",
            stacklevel=3)
        _warned = True


def init() -> None:
    """Reference: nccl_allocator.init(). No-op: XLA owns the allocator."""
    global _initialized
    _warn_once()
    _initialized = True


def is_initialized() -> bool:
    return _initialized


@contextlib.contextmanager
def nccl_mem(enabled: bool = True):
    """Reference: ``with nccl_allocator.nccl_mem():`` — allocations inside
    are NCCL-registered. Here: every buffer already is; yields unchanged."""
    _warn_once()
    yield
