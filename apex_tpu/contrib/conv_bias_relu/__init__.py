"""Fused Conv+Bias(+ReLU / +Mask+ReLU / frozen scale-bias) blocks, NHWC.

Reference: apex/contrib/conv_bias_relu/conv_bias_relu.py — ConvBiasReLU,
ConvBias, ConvBiasMaskReLU, ConvFrozenScaleBiasReLU (cudnn_frontend v8 fused
graphs via the ``fused_conv_bias_relu`` ext, SURVEY N16). TPU mapping
(SURVEY §3.2 N16): XLA fuses conv epilogues natively — these are jittable
functions whose bodies XLA compiles to a single fused conv; the module keeps
the reference's call signatures (NHWC activations, OIHW-style weights are
accepted as HWIO here, stride/padding ints) so callers port unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv_bias", "conv_bias_relu", "conv_bias_mask_relu",
           "conv_frozen_scale_bias_relu",
           "ConvBias", "ConvBiasReLU", "ConvBiasMaskReLU",
           "ConvFrozenScaleBiasReLU"]


def _conv_nhwc(x, weight, stride, padding):
    """NHWC x HWIO conv. int padding means symmetric SAME-style explicit pad
    (the reference passes cudnn-style int pad)."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    return lax.conv_general_dilated(
        x, jnp.asarray(weight, x.dtype), window_strides=stride,
        padding=padding, dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)


def conv_bias(x, weight, bias, padding=0, stride=1):
    """Conv + bias epilogue (reference: ConvBias.apply)."""
    y = _conv_nhwc(x, weight, stride, padding)
    return jnp.asarray(y + jnp.asarray(bias, y.dtype), x.dtype)


def conv_bias_relu(x, weight, bias, padding=0, stride=1):
    """Conv + bias + ReLU (reference: ConvBiasReLU.apply)."""
    y = _conv_nhwc(x, weight, stride, padding)
    return jnp.asarray(jnp.maximum(y + jnp.asarray(bias, y.dtype), 0), x.dtype)


def conv_bias_mask_relu(x, weight, bias, mask, padding=0, stride=1):
    """Conv + bias + elementwise mask + ReLU (reference: ConvBiasMaskReLU —
    the mask is the ReLU bitmask of a parallel branch)."""
    y = _conv_nhwc(x, weight, stride, padding)
    y = (y + jnp.asarray(bias, y.dtype)) * jnp.asarray(mask, y.dtype)
    return jnp.asarray(jnp.maximum(y, 0), x.dtype)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, padding=0, stride=1):
    """Conv + frozen-BN affine (scale, bias treated as constants: no grad
    flows to them — reference ConvFrozenScaleBiasReLU marks them
    non-differentiable) + ReLU."""
    scale = lax.stop_gradient(jnp.asarray(scale))
    bias = lax.stop_gradient(jnp.asarray(bias))
    y = _conv_nhwc(x, weight, stride, padding)
    y = y * jnp.asarray(scale, y.dtype) + jnp.asarray(bias, y.dtype)
    return jnp.asarray(jnp.maximum(y, 0), x.dtype)


class _FnApply:
    """Reference parity: apex exposes these as autograd Functions used via
    ``.apply(...)``; grads come for free from jax AD here."""

    def __init__(self, fn):
        self._fn = fn

    def apply(self, *args):
        return self._fn(*args)

    __call__ = apply


ConvBias = _FnApply(conv_bias)
ConvBiasReLU = _FnApply(conv_bias_relu)
ConvBiasMaskReLU = _FnApply(conv_bias_mask_relu)
ConvFrozenScaleBiasReLU = _FnApply(conv_frozen_scale_bias_relu)
