"""NHWC GroupNorm (+ fused SiLU) — diffusion workloads.

Reference: apex/contrib/group_norm/group_norm.py — GroupNorm
(group_norm_nhwc kernels, N23). The compute lives in
apex_tpu.kernels.group_norm: a two-pass Pallas kernel pair (sum-pass →
normalize-pass with the SiLU epilogue fused, custom_vjp backward with the
same structure) on lane-aligned channel counts, jnp fallback otherwise.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.kernels.group_norm import group_norm_nhwc

__all__ = ["GroupNorm", "group_norm_nhwc"]


class GroupNorm(nn.Module):
    """Reference ctor shape: GroupNorm(num_groups, num_channels, eps, affine,
    act)."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: Optional[str] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        weight = bias = None
        if self.affine:
            weight = self.param("scale", nn.initializers.ones,
                                (self.num_channels,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros,
                              (self.num_channels,), self.param_dtype)
        return group_norm_nhwc(x, self.num_groups, weight, bias,
                               eps=self.eps, act=self.act)
