"""NHWC GroupNorm (+ fused SiLU) — diffusion workloads.

Reference: apex/contrib/group_norm/group_norm.py — GroupNorm
(group_norm_nhwc kernels, N23). NHWC is TPU's native conv layout, so the
math is one fp32-accumulated jnp expression XLA fuses; ``act="silu"``
mirrors the kernel's fused activation.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["GroupNorm", "group_norm_nhwc"]


def group_norm_nhwc(x, num_groups: int, weight=None, bias=None,
                    eps: float = 1e-5, act: Optional[str] = None):
    """x: [N, H, W, C]; stats per (sample, group) in fp32."""
    n, h, w, c = x.shape
    if c % num_groups:
        raise ValueError(f"channels {c} not divisible by groups {num_groups}")
    x32 = jnp.asarray(x, jnp.float32).reshape(n, h, w, num_groups,
                                              c // num_groups)
    mean = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=(1, 2, 4), keepdims=True)
    y = ((x32 - mean) * jax.lax.rsqrt(var + eps)).reshape(n, h, w, c)
    if weight is not None:
        y = y * jnp.asarray(weight, jnp.float32)
    if bias is not None:
        y = y + jnp.asarray(bias, jnp.float32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    elif act not in (None, "identity", ""):
        raise ValueError(f"unsupported act {act!r}")
    return jnp.asarray(y, x.dtype)


class GroupNorm(nn.Module):
    """Reference ctor shape: GroupNorm(num_groups, num_channels, eps, affine,
    act)."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: Optional[str] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        weight = bias = None
        if self.affine:
            weight = self.param("scale", nn.initializers.ones,
                                (self.num_channels,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros,
                              (self.num_channels,), self.param_dtype)
        return group_norm_nhwc(x, self.num_groups, weight, bias,
                               eps=self.eps, act=self.act)
