"""Fused multihead attention modules.

Reference: apex/contrib/multihead_attn/ — SelfMultiheadAttn /
EncdecMultiheadAttn with impl='fast' (fast_multihead_attn ext: packed QKV
strided GEMMs + fused softmax(+dropout) + out proj, optional pre-LN +
residual-add fusion — the *_norm_add_* kernel variants) and impl='default'
(pure-torch reference alongside).

TPU: one flax module per reference class; the fused attention core is the
flash-attention Pallas kernel; pre-LN fusion is the fused LN kernel. Like
the reference, ``impl`` selects the engine: 'fast' (default) runs the flash
kernel — including fused softmax-dropout with hardware-PRNG replay, additive
masks, and key-padding masks (as additive key bias) — and 'default' keeps
the explicit-probs softmax composition (the reference's python impls; same
math, materialized probabilities, flax-rng dropout stream).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention
from apex_tpu.normalization import FusedLayerNorm

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _split_heads(x, heads):
    # [S, B, E] -> [B, H, S, D]
    s, b, e = x.shape
    d = e // heads
    return x.reshape(s, b, heads, d).transpose(1, 2, 0, 3)


def _merge_heads(x):
    # [B, H, S, D] -> [S, B, E]
    b, h, s, d = x.shape
    return x.transpose(2, 0, 1, 3).reshape(s, b, h * d)


def _attend(module, qh, kh, vh, *, causal, scale, key_padding_mask,
            dropout, is_training, attn_mask=None):
    """Fused path (impl='fast'): everything — softmax+dropout with in-kernel
    philox-replay semantics, additive masks, AND key-padding masks — runs
    through the flash kernel; a padding mask becomes an additive −inf bias
    on the masked KEYS, which is exactly the reference's semantics (padded
    queries still attend normally; their outputs are garbage the caller
    masks, same as apex). impl='default' keeps the explicit-probs softmax
    composition, like the reference's python fallback impls.

    ``attn_mask`` is the ADDITIVE float mask of the reference's
    *_additive_mask_* variants ([b|1, h|1, sq, sk], added to the scaled
    logits)."""
    if module.impl not in ("fast", "default"):
        raise ValueError(
            f"impl must be 'fast' or 'default', got {module.impl!r} "
            "(the reference asserts the same)")
    use_dropout = dropout > 0.0 and is_training
    if module.impl == "fast":
        bias = attn_mask
        if key_padding_mask is not None:
            b = qh.shape[0]
            sq, sk = qh.shape[2], kh.shape[2]
            # full [sq, sk] plane (kernel bias contract) — b×sq×sk fp32,
            # h× smaller than the explicit path's per-head prob matrix
            pad = jnp.where(key_padding_mask[:, None, None, :], -1e30, 0.0)
            pad = jnp.broadcast_to(pad.astype(jnp.float32), (b, 1, sq, sk))
            bias = pad if bias is None else jnp.asarray(bias,
                                                        jnp.float32) + pad
        seed = None
        rate = 0.0
        if use_dropout:
            rate = dropout
            seed = jax.random.randint(
                module.make_rng("dropout"), (), 0, 2 ** 31 - 1, jnp.int32)
        return flash_attention(qh, kh, vh, causal=causal, scale=scale,
                               bias=bias, dropout_rate=rate,
                               dropout_seed=seed)
    s = jnp.einsum("bhqd,bhkd->bhqk", jnp.asarray(qh, jnp.float32),
                   jnp.asarray(kh, jnp.float32)) * scale
    if attn_mask is not None:
        s = s + jnp.asarray(attn_mask, jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, -1e30)
    if key_padding_mask is not None:
        s = jnp.where(key_padding_mask[:, None, None, :], -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    if use_dropout:
        # dropout on the softmax probabilities, like fast_self_attn's fused
        # softmax-dropout (reference: self_multihead_attn_func.py applies
        # dropout to attn weights before the PV matmul)
        p = module._prob_dropout(p)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, jnp.asarray(vh, jnp.float32))
    return jnp.asarray(out, qh.dtype)


class SelfMultiheadAttn(nn.Module):
    """Self-attention block, [seq, batch, embed] layout like the reference.

    Reference: self_multihead_attn.py — class SelfMultiheadAttn(embed_dim,
    num_heads, dropout, bias, include_norm_add, impl, separate_qkv_params).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    # None → consult the O1 engine ('linear' is FP16_FUNCS), else fp32 —
    # the same None semantics as every GEMM-family module (models, TP
    # layers): the pre-engine default was fp32, so no-policy behavior is
    # unchanged. (Norm modules differ deliberately: their None follows the
    # input dtype, since they are dtype-preserving ops in apex.)
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key=None, value=None, *,
                 mask_future_timesteps: bool = False,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 attn_mask: Optional[jnp.ndarray] = None,
                 is_training: bool = True):
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        x = jnp.asarray(query, dtype)
        residual = x
        if self.include_norm_add:
            # *_norm_add_* variants: pre-LN fused into the block, residual
            # added at the end (reference: self_multihead_attn_norm_add func)
            x = FusedLayerNorm(normalized_shape=self.embed_dim,
                               dtype=self.dtype, name="lyr_norm")(x)
        qkv = nn.Dense(3 * self.embed_dim, use_bias=self.use_bias,
                       dtype=dtype, param_dtype=self.param_dtype,
                       name="qkv_proj")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        qh, kh, vh = (_split_heads(t, self.num_heads) for t in (q, k, v))

        scale = 1.0 / (self.embed_dim // self.num_heads) ** 0.5
        out = _attend(self, qh, kh, vh, causal=mask_future_timesteps,
                      scale=scale, key_padding_mask=key_padding_mask,
                      dropout=self.dropout, is_training=is_training,
                      attn_mask=attn_mask)
        y = _merge_heads(out)
        y = nn.Dense(self.embed_dim, use_bias=self.use_bias,
                     dtype=dtype, param_dtype=self.param_dtype,
                     name="out_proj")(y)
        if self.include_norm_add:
            # *_norm_add_* fuses dropout into the residual add
            # (reference: fast_self_multihead_attn_norm_add — dropout_add)
            if self.dropout > 0.0 and is_training:
                y = nn.Dropout(rate=self.dropout, deterministic=False)(y)
            y = y + residual
        return y

    def _prob_dropout(self, p):
        return nn.Dropout(rate=self.dropout, deterministic=False)(p)


class EncdecMultiheadAttn(nn.Module):
    """Encoder-decoder (cross) attention.

    Reference: encdec_multihead_attn.py — class EncdecMultiheadAttn (q from
    decoder, packed kv from encoder output).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    # None → consult the O1 engine ('linear' is FP16_FUNCS), else fp32 —
    # the same None semantics as every GEMM-family module (models, TP
    # layers): the pre-engine default was fp32, so no-policy behavior is
    # unchanged. (Norm modules differ deliberately: their None follows the
    # input dtype, since they are dtype-preserving ops in apex.)
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, value=None, *,
                 key_padding_mask: Optional[jnp.ndarray] = None,
                 attn_mask: Optional[jnp.ndarray] = None,
                 is_training: bool = True):
        from apex_tpu.amp.autocast import resolve_dtype
        dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        q_in = jnp.asarray(query, dtype)
        kv_in = jnp.asarray(key, dtype)
        residual = q_in
        if self.include_norm_add:
            q_in = FusedLayerNorm(normalized_shape=self.embed_dim,
                                  dtype=self.dtype, name="lyr_norm")(q_in)
        q = nn.Dense(self.embed_dim, use_bias=self.use_bias,
                     dtype=dtype, param_dtype=self.param_dtype,
                     name="q_proj")(q_in)
        kv = nn.Dense(2 * self.embed_dim, use_bias=self.use_bias,
                      dtype=dtype, param_dtype=self.param_dtype,
                      name="kv_proj")(kv_in)
        k, v = jnp.split(kv, 2, axis=-1)
        qh, kh, vh = (_split_heads(t, self.num_heads) for t in (q, k, v))
        scale = 1.0 / (self.embed_dim // self.num_heads) ** 0.5
        out = _attend(self, qh, kh, vh, causal=False, scale=scale,
                      key_padding_mask=key_padding_mask,
                      dropout=self.dropout, is_training=is_training,
                      attn_mask=attn_mask)
        y = _merge_heads(out)
        y = nn.Dense(self.embed_dim, use_bias=self.use_bias,
                     dtype=dtype, param_dtype=self.param_dtype,
                     name="out_proj")(y)
        if self.include_norm_add:
            if self.dropout > 0.0 and is_training:
                y = nn.Dropout(rate=self.dropout, deterministic=False)(y)
            y = y + residual
        return y

    def _prob_dropout(self, p):
        return nn.Dropout(rate=self.dropout, deterministic=False)(p)
