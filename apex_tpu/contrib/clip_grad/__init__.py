"""clip_grad_norm_ drop-in.

Reference: apex/contrib/clip_grad/clip_grad.py — clip_grad_norm_ (uses
multi_tensor_l2norm + multi_tensor_scale to do the whole model in two
launches). Here: one fused global-norm over the flattened pytree + one fused
scale — same two-pass semantics, jit-friendly (returns the clipped tree
functionally instead of mutating .grad).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import flatten_tree, unflatten_tree
from apex_tpu.kernels.multi_tensor import fused_l2norm, fused_scale

__all__ = ["clip_grad_norm_", "clip_grad_norm"]


def clip_grad_norm(grads, max_norm: float, norm_type: float = 2.0):
    """Returns (clipped_grads, total_norm). norm_type=2 uses the fused
    l2norm kernel; other norms use jnp (reference falls back to a python
    loop identically)."""
    flat, spec = flatten_tree(grads)
    if norm_type == 2.0:
        total_norm = fused_l2norm(flat)
    else:
        x32 = jnp.asarray(flat, jnp.float32)
        total_norm = jnp.sum(jnp.abs(x32) ** norm_type) ** (1.0 / norm_type)
    clip_coef = max_norm / (total_norm + 1e-6)
    coef = jnp.minimum(clip_coef, 1.0)
    clipped, _ = fused_scale(flat, coef)
    return unflatten_tree(clipped, spec), total_norm


# reference-named alias (the underscore name mutates in torch; here it
# returns, like every jax transform)
clip_grad_norm_ = clip_grad_norm
