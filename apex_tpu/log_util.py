"""Package-wide logging — the promotion of transformer/log_util.py.

Reference: apex/transformer/log_util.py — get_transformer_logger /
set_logging_level, which apex scopes to the transformer subtree only.
Here the same two-function surface owns the whole ``apex_tpu`` logger
namespace, so every subsystem (telemetry, checkpointing, amp, fp16_utils)
shares one diagnostics path instead of bare ``print`` — enforced by
tests/L0/test_no_stray_prints.py. The transformer helpers survive as thin
aliases (apex_tpu/transformer/log_util.py).
"""

from __future__ import annotations

import logging

__all__ = ["get_logger", "set_logging_level"]

_ROOT = "apex_tpu"

# Visible-by-default diagnostics: the reference apex prints its banners
# ("=> saved step ...", overflow warnings) unconditionally, and Python's
# unconfigured logging would swallow anything below WARNING — so the
# package logger gets one stderr handler at INFO unless the embedding
# application already installed its own. Silence with
# set_logging_level(logging.WARNING) or replace the handler; propagation
# stays off so an app-level basicConfig doesn't double-print.
_root_logger = logging.getLogger(_ROOT)
if not _root_logger.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(logging.Formatter("%(name)s: %(message)s"))
    _root_logger.addHandler(_handler)
    _root_logger.setLevel(logging.INFO)
    _root_logger.propagate = False


def get_logger(name: str = "") -> logging.Logger:
    """Logger under the ``apex_tpu`` namespace: ``get_logger("amp")`` →
    ``apex_tpu.amp``; no argument → the root package logger."""
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def set_logging_level(verbosity) -> None:
    """Set the package root logger level (ints or level names, same as
    the reference's set_logging_level)."""
    logging.getLogger(_ROOT).setLevel(verbosity)
