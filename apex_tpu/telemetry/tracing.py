"""Request-level distributed tracing for the serving stack.

The serving tier (engine → scheduler → router, PRs 11–15) reports
itself through aggregate counters/gauges/histograms — enough to see
THAT p99 TTFT spiked, never WHICH stage ate the time. This module adds
the per-request timeline those aggregates integrate over: one
:class:`Trace` per request ``uid``, made of :class:`Span` records for
every lifecycle stage (``submit`` → ``route`` → ``queue_wait`` →
``admit`` → ``prefill_chunk``* → ``heartbeat``* / ``draft`` /
``verify`` → ``swap_out`` / ``swap_in`` → terminal ``finish`` /
``expired`` / ``failed``, with ``quarantine`` sub-spans on faults —
the full taxonomy is documented in docs/serving.md and pinned by the
span-name lint in tests/L0/test_serving_metrics_lint.py).

Design constraints, in order:

- **Off is free.** ``tracer=None`` (the default everywhere) allocates
  no span objects and changes no tokens — every hook in the serving
  code is a ``if tracer is not None`` guard around pure host-clock
  reads. Pinned bitwise (identical greedy streams, zero new compiled
  programs) by tests/L0/test_tracing.py.
- **No new forced reads.** Span timestamps are host ``perf_counter``
  clocks; device time is attributed from the already-charged
  ``Engine.device_wait_s`` deltas the PR 11 heartbeat split computes
  anyway. The recording methods (:meth:`Tracer.event` and friends)
  are covered by the force-early AST lint — they run inside the
  dispatch-ahead regions' dynamic extent, so they must never call
  ``int()`` / ``np.asarray`` / ``jax.device_get``.
- **Threads are first-class.** The tracer is lock-protected and every
  span records the emitting thread's name, so work the
  ``DraftWorker`` / ``SwapWorker`` daemon threads perform lands in
  the right trace with honest attribution (one Chrome ``tid`` per
  thread). Cross-component context threads two ways: explicitly
  (``trace_id`` captured into worker closures at dispatch) and via
  :meth:`Tracer.bind`, a thread-local binding the scheduler wraps
  around admission so engine-level swap spans — which never see a
  request — attach to the admitting request's trace.
- **Bounded memory.** Completed traces live in a ring of the last
  ``max_traces``; live traces are evicted oldest-first past the same
  bound (a leak-proof default for long-running fleets).

Exporters: :meth:`Tracer.export_chrome_trace` writes Chrome
trace-event JSON (loadable at https://ui.perfetto.dev — one ``pid``
per replica, one ``tid`` per thread) and
:meth:`Tracer.export_jsonl` streams one record per span through the
existing sink machinery (tag ``serving.trace``), which
``python -m apex_tpu.telemetry trace`` summarizes (per-stage
p50/p99, critical-path breakdown, join with ``serving.request``
completion records via their ``trace_id`` field).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from .sinks import Sink, make_sink

__all__ = ["Span", "Trace", "Tracer", "TRACE_TAG"]

#: ``tag`` of every JSONL record :meth:`Tracer.export_jsonl` writes
TRACE_TAG = "serving.trace"


class Span:
    """One lifecycle stage of one request: a named interval with host
    timestamps (``perf_counter`` seconds), the replica (``pid``) and
    thread (``tid``) it ran on, and a flat dict of annotations
    (chosen replica, bytes moved, drafted/accepted counts, fault
    kind, ...)."""

    __slots__ = ("name", "t0", "dur", "pid", "tid", "args")

    def __init__(self, name, t0, dur, pid, tid, args):
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.pid = pid
        self.tid = tid
        self.args = args

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, t0={self.t0:.6f}, "
                f"dur={self.dur:.6f}, pid={self.pid}, tid={self.tid!r}, "
                f"args={self.args!r})")


class Trace:
    """All spans recorded for one request ``uid`` (the trace id), in
    emission order. ``terminal`` is the name of the trace's single
    terminal span (``finish`` / ``expired`` / ``failed``) once
    :meth:`Tracer.end_trace` sealed it, else None."""

    __slots__ = ("trace_id", "spans", "terminal")

    def __init__(self, trace_id):
        self.trace_id = trace_id
        self.spans: List[Span] = []
        self.terminal: Optional[str] = None

    def by_name(self, name: str) -> List[Span]:
        """The trace's spans named ``name``, in emission order."""
        return [s for s in self.spans if s.name == name]


class _BoundTracer:
    """A :class:`Tracer` view with a fixed default ``pid`` (replica
    index) — what :meth:`Tracer.for_replica` hands each replica's
    scheduler/engine so every span they emit lands under that
    replica's Chrome process without threading ``pid`` through call
    sites."""

    __slots__ = ("_tracer", "pid")

    def __init__(self, tracer: "Tracer", pid: int):
        self._tracer = tracer
        self.pid = pid

    def now(self):
        return self._tracer.now()

    def begin(self, trace_id):
        self._tracer.begin(trace_id)

    def event(self, trace_id, name, *, t0=None, dur=0.0, pid=None,
              **args):
        self._tracer.event(trace_id, name, t0=t0, dur=dur,
                           pid=self.pid if pid is None else pid, **args)

    def event_current(self, name, *, t0=None, dur=0.0, **args):
        self._tracer.event_current(name, t0=t0, dur=dur, **args)

    def end_trace(self, trace_id, name, *, t0=None, dur=0.0, **args):
        self._tracer.end_trace(trace_id, name, t0=t0, dur=dur,
                               pid=self.pid, **args)

    def bind(self, trace_id):
        return self._tracer.bind(trace_id, pid=self.pid)

    def current(self):
        return self._tracer.current()

    def for_replica(self, pid: int) -> "_BoundTracer":
        return self._tracer.for_replica(pid)


class Tracer:
    """Thread-safe span recorder: one :class:`Trace` per request uid,
    a bounded ring of completed traces, exporters.

    Attach with ``Scheduler(tracer=...)`` or ``Router(tracer=...)``;
    the router hands each replica a :meth:`for_replica` view so spans
    carry the replica index as their Chrome ``pid``. The default
    ``tracer=None`` everywhere is the zero-cost off switch — see the
    module docstring's contract.
    """

    def __init__(self, max_traces: int = 1024, clock=time.perf_counter):
        if max_traces < 1:
            raise ValueError("max_traces must be >= 1")
        self.max_traces = max_traces
        self._clock = clock
        self._lock = threading.Lock()
        # live (un-sealed) traces, insertion-ordered for bounded
        # eviction; sealed traces ride the ring + an id index so late
        # worker-thread spans (a swap store completing after its
        # request finished) still find their trace
        self._live: "OrderedDict[Any, Trace]" = OrderedDict()
        self._done: deque = deque()
        self._done_index: Dict[Any, Trace] = {}
        self._local = threading.local()

    # ------------------------------------------------------------ recording
    def now(self) -> float:
        """The tracer's clock (``time.perf_counter`` by default) —
        hooks use it so spans and a custom test clock agree."""
        return self._clock()

    def _get_locked(self, trace_id) -> Trace:
        t = self._live.get(trace_id)
        if t is None:
            t = self._done_index.get(trace_id)
        if t is None:
            t = Trace(trace_id)
            self._live[trace_id] = t
            while len(self._live) > self.max_traces:
                self._live.popitem(last=False)
        return t

    def begin(self, trace_id) -> None:
        """Ensure a live trace exists for ``trace_id`` (idempotent;
        every recording method auto-begins, this just marks intent)."""
        with self._lock:
            self._get_locked(trace_id)

    def event(self, trace_id, name, *, t0=None, dur=0.0, pid=None,
              **args) -> None:
        """Record one span. ``t0`` defaults to now (an instantaneous
        marker); ``dur`` is seconds; ``pid`` is the replica index
        (defaults to the thread's :meth:`bind` binding, else 0); the
        emitting thread's name is recorded as ``tid``; remaining
        keywords become the span's annotations."""
        clock_now = self._clock()
        if pid is None:
            bound = getattr(self._local, "stack", None)
            pid = bound[-1][1] if bound else 0
        span = Span(name, clock_now if t0 is None else t0, dur, pid,
                    threading.current_thread().name, args)
        with self._lock:
            self._get_locked(trace_id).spans.append(span)

    def event_current(self, name, *, t0=None, dur=0.0, **args) -> None:
        """Record a span on the thread's CURRENTLY BOUND trace (see
        :meth:`bind`); a silent no-op when nothing is bound — engine
        internals call this without knowing whether a request context
        exists."""
        bound = getattr(self._local, "stack", None)
        if not bound:
            return
        trace_id, pid = bound[-1]
        self.event(trace_id, name, t0=t0, dur=dur, pid=pid, **args)

    def end_trace(self, trace_id, name, *, t0=None, dur=0.0, pid=None,
                  **args) -> None:
        """Record the TERMINAL span (``finish`` / ``expired`` /
        ``failed``) and seal the trace into the completed ring.
        Sealing twice keeps the first terminal (one terminal per
        trace — the chaos composition pin's invariant)."""
        clock_now = self._clock()
        if pid is None:
            bound = getattr(self._local, "stack", None)
            pid = bound[-1][1] if bound else 0
        span = Span(name, clock_now if t0 is None else t0, dur, pid,
                    threading.current_thread().name, args)
        with self._lock:
            t = self._live.pop(trace_id, None)
            if t is None:
                t = self._done_index.get(trace_id)
                if t is not None:
                    # already sealed: keep the first terminal
                    return
                t = Trace(trace_id)
            t.spans.append(span)
            t.terminal = name
            self._done.append(t)
            self._done_index[trace_id] = t
            while len(self._done) > self.max_traces:
                old = self._done.popleft()
                self._done_index.pop(old.trace_id, None)

    def bind(self, trace_id, pid: int = 0):
        """Context manager binding ``trace_id`` (and default ``pid``)
        to the current thread — the scheduler wraps admission in it so
        engine-level spans (:meth:`event_current` from swap paths,
        which never see a request) land in the admitting request's
        trace. Re-entrant (a stack): swap-outs triggered inside a
        swap-in stay correctly attributed."""
        return _Binding(self._local, trace_id, pid)

    def current(self):
        """The thread's currently bound trace id, or None — captured
        into worker closures at dispatch time so completion spans
        emitted on the worker thread join the right trace."""
        bound = getattr(self._local, "stack", None)
        return bound[-1][0] if bound else None

    def for_replica(self, pid: int) -> _BoundTracer:
        """A view of this tracer whose spans default to Chrome process
        ``pid`` — one per replica, handed out by the router."""
        return _BoundTracer(self, pid)

    # ------------------------------------------------------------ reading
    def traces(self) -> List[Trace]:
        """Snapshot of the COMPLETED traces (oldest first)."""
        with self._lock:
            return list(self._done)

    def live_traces(self) -> List[Trace]:
        """Snapshot of the still-open traces (submitted/unfinished
        requests), oldest first."""
        with self._lock:
            return list(self._live.values())

    def find(self, trace_id) -> Optional[Trace]:
        """The trace for ``trace_id`` (live or completed), or None."""
        with self._lock:
            return self._live.get(trace_id) \
                or self._done_index.get(trace_id)

    def _all_spans(self) -> List[tuple]:
        with self._lock:
            traces = list(self._done) + list(self._live.values())
        out = []
        for t in traces:
            for s in t.spans:
                out.append((t.trace_id, s))
        return out

    # ------------------------------------------------------------ exporters
    def export_chrome_trace(self, path: str) -> int:
        """Write Chrome trace-event JSON (the Perfetto/chrome://tracing
        format): every span becomes a complete (``"ph": "X"``) event
        with microsecond timestamps, ``pid`` = replica index (named
        ``replica<i>`` via process metadata), ``tid`` = a stable
        small integer per emitting thread (named via thread
        metadata), and the span's annotations + ``trace_id`` under
        ``args``. Events are sorted by timestamp within each thread
        lane. Returns the number of span events written."""
        spans = self._all_spans()
        pids = sorted({s.pid for _, s in spans})
        tid_names = sorted({s.tid for _, s in spans})
        tid_of = {name: i + 1 for i, name in enumerate(tid_names)}
        events = []
        for pid in pids:
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": f"replica{pid}"}})
            for name in tid_names:
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tid_of[name],
                               "args": {"name": name}})
        span_events = []
        for trace_id, s in spans:
            span_events.append({
                "name": s.name, "cat": "serving", "ph": "X",
                "ts": int(round(s.t0 * 1e6)),
                "dur": int(round(s.dur * 1e6)),
                "pid": s.pid, "tid": tid_of[s.tid],
                "args": {"trace_id": trace_id, **s.args},
            })
        span_events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
        events.extend(span_events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return len(span_events)

    def export_jsonl(self, spec_or_sink) -> int:
        """Stream one record per span through the sink machinery:
        ``spec_or_sink`` is a :class:`~apex_tpu.telemetry.Sink` or a
        :func:`~apex_tpu.telemetry.make_sink` spec (JSONL path /
        ``"stdout"`` / ``"null"``). Records carry ``tag`` =
        :data:`TRACE_TAG` plus ``trace_id`` / ``span`` / ``ts_s`` /
        ``dur_s`` / ``replica`` / ``thread`` and the span's
        annotations — the shape ``python -m apex_tpu.telemetry
        trace`` consumes. Returns the number of records written; a
        sink this call opened is closed before returning."""
        owns = not isinstance(spec_or_sink, Sink)
        sink = make_sink(spec_or_sink) if owns else spec_or_sink
        n = 0
        try:
            for trace_id, s in self._all_spans():
                sink.emit({"tag": TRACE_TAG, "trace_id": trace_id,
                           "span": s.name, "ts_s": s.t0,
                           "dur_s": s.dur, "replica": s.pid,
                           "thread": s.tid, **s.args})
                n += 1
        finally:
            if owns:
                sink.close()
        return n


class _Binding:
    """The :meth:`Tracer.bind` context manager (tiny and allocation-
    light: one tuple push/pop on a thread-local stack)."""

    __slots__ = ("_local", "_item")

    def __init__(self, local, trace_id, pid):
        self._local = local
        self._item = (trace_id, pid)

    def __enter__(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(self._item)
        return self

    def __exit__(self, *exc):
        self._local.stack.pop()
        return False
