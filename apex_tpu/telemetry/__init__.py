"""apex_tpu.telemetry — unified in-jit training telemetry.

The reference apex observes training with NVTX ranges and recipe-level
``print``; this subsystem is the structured counterpart the TPU port
needs before multi-chip runs can be debugged (SURVEY §6): every signal a
jitted train step computes — loss, grad norm, ``found_inf``, the loss-
scale trajectory — streams to the host as it happens, lands in one
process-local :class:`MetricsRegistry`, and fans out to pluggable sinks
(JSONL file, stdout line protocol, in-memory spy, null).

Layers:

- metrics core (:mod:`.core`) — counters, gauges, streaming histograms
  (p50/p95/p99), per-step :data:`StepRecord` ring buffer, sink fan-out.
- sinks (:mod:`.sinks`) — :class:`JsonlSink` / :class:`StdoutSink` /
  :class:`NullSink` / :class:`MemorySink`.
- in-jit emission (:mod:`.emit`) — :func:`emit_metrics`: ONE
  ``jax.debug.callback`` per step bundles all metric scalars; wired into
  ``amp.make_train_step(telemetry=...)``. Enabled-ness is read at trace
  time (same contract as ``pyprof.init``); sinks/registry resolve at
  callback time.
- comm health (:func:`account_collective`) — bytes/calls/leaves counters
  for every ``apex_tpu.comm`` collective and the DDP grad allreduce;
  device latency joins in through the profiler
  (``summarize --trace``).
- request tracing (:mod:`.tracing`) — :class:`Tracer`: span-based
  per-request lifecycle traces for the serving stack (``submit`` →
  ``route`` → ``admit`` → ``prefill_chunk``/``heartbeat`` → terminal),
  Chrome-trace/Perfetto + JSONL exporters; attached via
  ``Scheduler(tracer=)`` / ``Router(tracer=)``, zero-cost when off.
- CLI (:mod:`.__main__`) — ``python -m apex_tpu.telemetry summarize
  run.jsonl [--trace DIR]``: per-metric count/mean/p50/p95/p99 plus the
  device step-time breakdown joined from a ``pyprof.trace`` capture;
  ``python -m apex_tpu.telemetry trace spans.jsonl``: per-stage span
  latency + critical-path breakdown of a request-trace file.

Quick start::

    from apex_tpu import amp, telemetry

    telemetry.start_run("run.jsonl")            # JSONL sink on default reg
    init_fn, step_fn = amp.make_train_step(loss_fn, opt, policy,
                                           telemetry=True)
    ...train...
    telemetry.get_registry().emit_snapshot()    # final aggregate line
    telemetry.get_registry().close()

Then ``python -m apex_tpu.telemetry summarize run.jsonl``.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import List, Optional

from ..log_util import get_logger
from .core import MetricsRegistry, StepRecord, StreamingHistogram
from .emit import (account_collective, collective_bytes, emit_metrics,
                   global_norm)
from .sinks import (JsonlSink, MemorySink, NullSink, Sink, StdoutSink,
                    make_sink)
from .tracing import Span, Trace, Tracer

__all__ = [
    "MetricsRegistry", "StepRecord", "StreamingHistogram",
    "Sink", "JsonlSink", "StdoutSink", "NullSink", "MemorySink",
    "make_sink",
    "Span", "Trace", "Tracer",
    "emit_metrics", "account_collective", "collective_bytes", "global_norm",
    "enable", "enabled", "get_registry", "set_registry", "configure",
    "start_run", "from_env", "timed", "guard_bench_main",
]

ENV_VAR = "APEX_TPU_TELEMETRY"

_logger = get_logger("telemetry")

_enabled = True
_registry: Optional[MetricsRegistry] = None


def enable(on: bool = True) -> None:
    """Global switch. In-jit emission reads it at TRACE time (flip before
    the first call of a jitted step, or ``jax.clear_caches()``); host-side
    accounting reads it per call."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def get_registry() -> MetricsRegistry:
    """The process-default registry (created lazily, sink-less)."""
    global _registry
    if _registry is None:
        _registry = MetricsRegistry()
    return _registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    global _registry
    _registry = registry
    return registry


def configure(sinks: Optional[List[Sink]] = None, ring_size: int = 1024,
              reservoir_size: int = 4096) -> MetricsRegistry:
    """Install a FRESH default registry with the given sinks (the previous
    default, if any, is left for its holders but no longer receives
    emissions routed through the default)."""
    return set_registry(MetricsRegistry(ring_size=ring_size, sinks=sinks,
                                        reservoir_size=reservoir_size))


def start_run(spec: str, **configure_kw) -> MetricsRegistry:
    """One-call run setup: ``spec`` is a JSONL path, ``"stdout"``, or
    ``"null"`` (see :func:`make_sink`); returns the fresh default
    registry."""
    reg = configure(sinks=[make_sink(spec)], **configure_kw)
    _logger.info("telemetry run started (sink=%s)", spec)
    return reg


def from_env(var: str = ENV_VAR) -> Optional[MetricsRegistry]:
    """Opt-in via environment: ``APEX_TPU_TELEMETRY=run.jsonl`` (or
    ``stdout``/``null``) starts a run; unset/empty returns None and
    changes nothing. The bench drivers call this so any bench run can
    stream step telemetry without a flag plumb-through."""
    spec = os.environ.get(var)
    if not spec:
        return None
    return start_run(spec)


@contextlib.contextmanager
def timed(name: str, registry: Optional[MetricsRegistry] = None):
    """Host-side latency observation: wall seconds of the block go into
    histogram ``name`` (+ counter ``name.calls``) — for eager sections
    (checkpoint saves, eval passes) the in-jit path can't time."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if _enabled:
            reg = registry if registry is not None else get_registry()
            reg.observe(name, time.perf_counter() - t0)
            reg.counter_inc(f"{name}.calls")


# Error-text markers of TRANSIENT infrastructure failures (tunnel drops,
# remote-compile hiccups, backend races) — worth one retry before the
# failure line erases a canonical perf record. Substring-matched,
# case-insensitive, against ``{type}: {message}``.
_TRANSIENT_MARKERS = (
    "remote_compile", "read body", "unavailable", "deadline_exceeded",
    "deadline exceeded", "connection reset", "connection refused",
    "broken pipe", "socket closed", "transient", "temporarily",
)


def _is_transient_error(err: str) -> bool:
    low = err.lower()
    return any(m in low for m in _TRANSIENT_MARKERS)


# Backoff before each transient retry: _RETRY_BACKOFF_S * 2**n, capped.
# Module-level so tests (and desperate operators) can zero it.
_RETRY_BACKOFF_S = 0.5
_RETRY_BACKOFF_CAP_S = 8.0


def _env_retries(default: int = 1) -> int:
    """``APEX_TPU_BENCH_RETRIES`` (>= 0), or ``default``. A malformed
    value must degrade to the default, never crash the bench before its
    guard is even armed."""
    raw = os.environ.get("APEX_TPU_BENCH_RETRIES")
    if raw is None or not raw.strip():
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        _logger.warning("APEX_TPU_BENCH_RETRIES=%r is not an integer; "
                        "using %d", raw, default)
        return default


def guard_bench_main(main, metric: str, retries: Optional[int] = None):
    """Run a bench driver's ``main`` so that EVERY outcome ends in a final
    parseable JSON line on stdout.

    Success: ``main`` already printed its metric line — pass through.
    Any failure (backend init, compile, OOM, bad argv): the traceback
    goes to stderr, and the LAST stdout line is
    ``{"metric": ..., "error": "...", "rc": 1, "transient": ...}`` so
    harnesses that parse the final line (BENCH_r0*.json) never record
    ``"parsed": null`` again. Exits 1 on failure; KeyboardInterrupt
    passes through.

    Resilience (VERDICT r5 next-round #1): an error whose text matches a
    transient-infrastructure marker (``remote_compile: read body``,
    UNAVAILABLE, connection resets — :data:`_TRANSIENT_MARKERS`) gets
    ``retries`` fresh attempts of ``main`` before the failure line is
    emitted, so one tunnel flake cannot erase the round's canonical perf
    record. The final failure line carries ``"transient": true/false``
    — true means the retries were exhausted on flake-shaped errors and
    the record should be read as infrastructure noise, not a perf
    regression; deterministic failures (bad argv, OOM, real compile
    errors) never retry and tag false.

    A retry re-runs ``main`` FROM SCRATCH, so a multi-row driver
    (bench_schedule.py) that emitted rows before the flake emits them
    again on the retry. Before each retry a marker line
    ``{"metric": ..., "event": "transient_retry", "discard_preceding":
    true, ...}`` is written to stdout so row-aggregating harnesses can
    drop the partial first attempt; final-line parsers are unaffected
    (the marker is never last — a real row or the failure line follows).

    ``retries`` defaults from ``APEX_TPU_BENCH_RETRIES`` (else 1), so a
    flaky round can be re-driven with more attempts without touching
    every bench driver (BENCH_r05 burned its single retry on
    back-to-back ``remote_compile`` resets). Retries sleep a short
    exponential backoff first (0.5 s, 1 s, 2 s, ... capped at 8 s) —
    back-to-back retries land inside the same infrastructure hiccup;
    a beat of patience is what actually clears tunnel resets.
    """
    import traceback

    if retries is None:
        retries = _env_retries()

    def _fail(err: str):
        # drain in-flight debug callbacks BEFORE writing the line that
        # must be last on stdout — a step that died mid-loop can still
        # have queued emissions (a StdoutSink printing after the JSON
        # line would break the contract). jax may itself be the thing
        # that failed to import/init, so best-effort.
        try:
            import jax

            jax.effects_barrier()
        except BaseException:
            pass
        _logger.error("bench %s failed: %s", metric, err)
        line = json.dumps({"metric": metric, "error": err, "rc": 1,
                           "transient": _is_transient_error(err)})
        sys.stdout.write(line + "\n")
        sys.stdout.flush()
        raise SystemExit(1)

    attempts_left = int(retries)
    while True:
        try:
            return main()
        except KeyboardInterrupt:
            raise
        except SystemExit as e:
            if e.code in (None, 0):
                raise
            traceback.print_exc(file=sys.stderr)
            err = str(e.code) if not isinstance(e.code, int) \
                else f"SystemExit: {e.code}"
        except BaseException as e:  # noqa: BLE001 — the contract is total
            traceback.print_exc(file=sys.stderr)
            err = f"{type(e).__name__}: {e}"
        if attempts_left > 0 and _is_transient_error(err):
            attempts_left -= 1
            n_retried = int(retries) - attempts_left - 1
            delay = min(_RETRY_BACKOFF_CAP_S,
                        _RETRY_BACKOFF_S * (2 ** n_retried))
            _logger.warning("bench %s hit a transient error (%s); "
                            "retrying in %.1fs — %d retry(ies) remain "
                            "after this", metric, err, delay,
                            attempts_left)
            if delay > 0:
                time.sleep(delay)
            # multi-row drivers re-emit their rows on the retry: mark the
            # boundary so row aggregators can discard the partial attempt
            sys.stdout.write(json.dumps({
                "metric": metric, "event": "transient_retry",
                "error": err, "discard_preceding": True}) + "\n")
            sys.stdout.flush()
            continue
        _fail(err)
