"""Run-summary CLI:

    python -m apex_tpu.telemetry summarize run.jsonl [--tag T] [--json]
                                                      [--trace DIR]
    python -m apex_tpu.telemetry trace spans.jsonl [--requests RUN]
                                                    [--json]

``summarize`` renders per-metric count/mean/p50/p95/p99 aggregates of a
telemetry JSONL run file; ``--trace`` additionally joins a
``pyprof.trace`` capture into a device step-time breakdown (ms/step per
HLO category, collective-op latency).

``trace`` summarizes a request-trace JSONL file (what
:meth:`~apex_tpu.telemetry.Tracer.export_jsonl` wrote): per-stage span
latency p50/p99, the critical-path breakdown, and — via ``--requests``
(defaults to the same file, since one sink may carry both streams) —
the join with ``serving.request`` completion records on ``trace_id``.

``--json`` emits the machine form instead of the tables.
"""

from __future__ import annotations

import argparse
import json
import sys

from .summarize import (load_records, render_breakdown, render_summary,
                        render_trace_summary, summarize_records,
                        summarize_trace, trace_breakdown)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.telemetry",
        description="apex_tpu telemetry tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize",
                       help="aggregate a telemetry JSONL run file")
    s.add_argument("run", help="JSONL file a JsonlSink wrote")
    s.add_argument("--tag", default=None,
                   help="only records with this tag (default: all)")
    s.add_argument("--trace", default=None, metavar="DIR",
                   help="join a pyprof.trace capture: device step-time "
                        "breakdown + collective latency")
    s.add_argument("--json", action="store_true",
                   help="machine-readable output instead of tables")
    t = sub.add_parser("trace",
                       help="summarize a serving request-trace JSONL "
                            "file (Tracer.export_jsonl output)")
    t.add_argument("run", help="JSONL file Tracer.export_jsonl wrote")
    t.add_argument("--requests", default=None, metavar="RUN",
                   help="JSONL with serving.request completion records "
                        "to join on trace_id (default: the trace file "
                        "itself)")
    t.add_argument("--json", action="store_true",
                   help="machine-readable output instead of tables")
    args = p.parse_args(argv)

    if args.cmd == "trace":
        return _main_trace(args)
    try:
        records = load_records(args.run)
    except OSError as e:
        raise SystemExit(str(e))
    if not records:
        raise SystemExit(f"no telemetry records in {args.run!r}")
    summary = summarize_records(records, tag=args.tag)

    breakdown = None
    if args.trace:
        n_steps = max(summary["steps"].values(), default=0) \
            if summary["steps"] else 0
        try:
            breakdown = trace_breakdown(args.trace, n_steps)
        except FileNotFoundError as e:
            raise SystemExit(str(e))

    if args.json:
        out = dict(summary)
        if breakdown is not None:
            out["device_breakdown"] = breakdown
        print(json.dumps(out))
    else:
        print(render_summary(summary))
        if breakdown is not None:
            print()
            print(render_breakdown(breakdown))
    return 0


def _main_trace(args):
    try:
        records = load_records(args.run)
    except OSError as e:
        raise SystemExit(str(e))
    if not any(r.get("tag") == "serving.trace" for r in records):
        raise SystemExit(f"no serving.trace records in {args.run!r} — "
                         "is this a Tracer.export_jsonl file?")
    if args.requests is None:
        request_records = records
    else:
        try:
            request_records = load_records(args.requests)
        except OSError as e:
            raise SystemExit(str(e))
    summary = summarize_trace(records, request_records)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render_trace_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
