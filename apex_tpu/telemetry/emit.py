"""Device→host metric emission and trace-time comm accounting.

:func:`emit_metrics` is the in-jit primitive: called inside a jitted train
step it schedules exactly ONE ``jax.debug.callback`` per executed step,
carrying every metric scalar in a single host transfer — no extra device
syncs, no per-metric callbacks. The host side lands the bundle in the
:class:`~apex_tpu.telemetry.MetricsRegistry` (ring buffer + histograms +
sinks) via ``record_step``.

Trace-time caveat (same rule as ``pyprof.init``): whether telemetry is
enabled is read when the step is TRACED and baked into the cached
executable. Flip :func:`apex_tpu.telemetry.enable` (or pass
``telemetry=`` to ``amp.make_train_step``) before the first call of a
jitted function, or ``jax.clear_caches()`` after flipping. The sinks and
the registry, by contrast, are resolved at CALLBACK time, so they can be
swapped between steps without retracing.

Under ``shard_map``/``pmap`` the callback fires once per mesh shard (each
rank reports its local values); the one-callback-per-step contract is a
per-device statement there.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["emit_metrics", "account_collective", "collective_bytes",
           "global_norm"]


def emit_metrics(metrics: Dict[str, Any], tag: str = "train",
                 registry=None) -> None:
    """Emit ``{name: scalar}`` from inside (or outside) jit to the
    registry — one host callback per executed step.

    Values may be traced jax scalars, concrete arrays, or Python numbers.
    ``registry=None`` resolves the process default at callback time.
    No-op (nothing staged into the trace at all) while telemetry is
    disabled at trace time.
    """
    import apex_tpu.telemetry as _t

    if not _t.enabled():
        return
    names = tuple(sorted(metrics))
    vals = [jnp.asarray(metrics[k]) for k in names]

    def _land(*host_vals):
        reg = registry if registry is not None else _t.get_registry()
        reg.record_step(dict(zip(names, host_vals)), tag=tag)

    jax.debug.callback(_land, *vals)


def global_norm(tree) -> jnp.ndarray:
    """fp32 global L2 norm over a pytree's floating leaves — the
    grad-norm series the reference recipes compute ad hoc (and apex's
    ``clip_grad_norm`` computes internally), as one fused reduction."""
    leaves = [leaf for leaf in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]
    if not leaves:
        return jnp.float32(0.0)
    total = sum(jnp.sum(jnp.square(jnp.asarray(leaf, jnp.float32)))
                for leaf in leaves)
    return jnp.sqrt(total)


def collective_bytes(tree) -> int:
    """Payload bytes of one execution of a collective over ``tree`` —
    computed from static shapes/dtypes, so it works on tracers during
    jit tracing with zero runtime cost."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def account_collective(op: str, tree, registry: Optional[Any] = None) -> None:
    """Comm-health accounting for one collective call site.

    Counters written (names prefixed ``comm.``):

    - ``comm.<op>.calls``  — traced call sites. Incremented at TRACE
      time: under jit this counts once per compilation, so after a train
      step is traced the counter reads the collectives of ONE step's
      program, not calls × steps.
    - ``comm.<op>.bytes``  — payload bytes those calls move per
      execution of their traced program.
    - ``comm.<op>.leaves`` — pytree leaves handed to the op (bucketing
      evidence: XLA's combiner merges per-leaf psums — see
      bench_schedule.py ddp).

    Per-execution device LATENCY for the same ops comes from the
    profiler join: ``python -m apex_tpu.telemetry summarize run.jsonl
    --trace DIR`` aggregates the device-lane spans of collective
    categories into latency stats (docs/telemetry.md §comm health).
    """
    import apex_tpu.telemetry as _t

    if not _t.enabled():
        return
    reg = registry if registry is not None else _t.get_registry()
    leaves = jax.tree_util.tree_leaves(tree)
    reg.counter_inc(f"comm.{op}.calls")
    reg.counter_inc(f"comm.{op}.bytes", collective_bytes(tree))
    reg.counter_inc(f"comm.{op}.leaves", len(leaves))
