"""Run-file aggregation behind ``python -m apex_tpu.telemetry summarize``.

Consumes the JSONL a :class:`~apex_tpu.telemetry.JsonlSink` wrote (one
record per step + optional snapshot records) and renders per-metric
aggregates — count/mean/p50/p95/p99/min/max, through the same
:class:`~apex_tpu.telemetry.StreamingHistogram` the live registry uses,
so offline and online numbers agree.

With ``--trace DIR`` it joins a ``pyprof.trace`` capture: the device
lanes' per-op spans (``pyprof.analyze``) are grouped by HLO category into
a step-time breakdown (ms/step per category, using the run's step count),
and collective categories are split out as device-side comm latency —
the latency half of the comm-health story whose bytes half lives in the
``comm.*`` counters.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .core import META_KEYS, StreamingHistogram

__all__ = ["load_records", "summarize_records", "render_summary",
           "trace_breakdown", "render_breakdown",
           "summarize_trace", "render_trace_summary"]

#: hlo_category substrings that identify collective/communication ops
COMM_CATEGORIES = ("all-reduce", "all-gather", "all-to-all",
                   "reduce-scatter", "collective", "copy", "send", "recv")


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL file; non-JSON and non-dict lines are
    skipped (a crashed run may end mid-write — the contract is that every
    complete line is usable)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _is_snapshot(rec: Dict[str, Any]) -> bool:
    return "counters" in rec or "histograms" in rec


def summarize_records(records: List[Dict[str, Any]],
                      tag: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate step records into per-metric summaries.

    Returns ``{"metrics": {"<tag>.<name>": summary_dict},
    "counters": {...}, "steps": {tag: n}}``. ``step_time_s`` (stamped by
    the registry host-side) aggregates like any other series. Counters
    come from the LAST snapshot record, if the run emitted one."""
    hists: Dict[str, StreamingHistogram] = {}
    steps: Dict[str, int] = {}
    counters: Dict[str, float] = {}
    for rec in records:
        if _is_snapshot(rec):
            counters = dict(rec.get("counters", {}))
            continue
        rtag = rec.get("tag", "train")
        if tag is not None and rtag != tag:
            continue
        steps[rtag] = steps.get(rtag, 0) + 1
        for k, v in rec.items():
            if k in META_KEYS and k != "step_time_s":
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            key = f"{rtag}.{k}"
            h = hists.get(key)
            if h is None:
                h = hists[key] = StreamingHistogram()
            h.observe(v)
    return {
        "metrics": {k: hists[k].summary() for k in sorted(hists)},
        "counters": counters,
        "steps": steps,
    }


def render_summary(summary: Dict[str, Any]) -> str:
    """Aligned text table of :func:`summarize_records` output."""
    lines = []
    steps = summary.get("steps", {})
    if steps:
        lines.append("steps: " + ", ".join(
            f"{t}={n}" for t, n in sorted(steps.items())))
        lines.append("")
    hdr = (f"{'metric':<32} {'count':>7} {'mean':>12} {'p50':>12} "
           f"{'p95':>12} {'p99':>12} {'min':>12} {'max':>12}")
    lines += [hdr, "-" * len(hdr)]
    for name, s in summary["metrics"].items():
        if s.get("count", 0) == 0:
            continue
        lines.append(
            f"{name[:32]:<32} {s['count']:>7} {s['mean']:>12.6g} "
            f"{s['p50']:>12.6g} {s['p95']:>12.6g} {s['p99']:>12.6g} "
            f"{s['min']:>12.6g} {s['max']:>12.6g}")
    if summary.get("counters"):
        lines += ["", f"{'counter':<48} {'value':>14}"]
        lines.append("-" * 63)
        for name in sorted(summary["counters"]):
            v = summary["counters"][name]
            lines.append(f"{name[:48]:<48} {v:>14,.0f}")
    return "\n".join(lines)


def summarize_trace(records: List[Dict[str, Any]],
                    request_records: Optional[List[Dict[str, Any]]] = None,
                    ) -> Dict[str, Any]:
    """Aggregate ``serving.trace`` span records (the JSONL
    :meth:`~apex_tpu.telemetry.Tracer.export_jsonl` writes) behind
    ``python -m apex_tpu.telemetry trace``.

    Returns::

        {"traces": n, "spans": {name: {count, mean, p50, p95, p99,
                                       min, max}},       # span DURATIONS
         "critical_path": {name: {"total_s", "per_request_s",
                                  "pct"}},               # where time went
         "requests": {...} | None}

    The critical path charges each stage's summed span durations
    against the fleet-wide total (heartbeat spans measure whole beats
    a slot participated in, so stages legitimately overlap — ``pct``
    reads as "fraction of summed stage time", not wall time).

    ``request_records`` (optional) are ``serving.request`` completion
    records (same JSONL or another run file): they join on their
    ``trace_id`` field — the summary then reports how many traces
    matched a completion record and the per-status request counts,
    the cross-check that the trace stream and the metrics stream
    describe the same requests."""
    spans = [r for r in records if r.get("tag") == "serving.trace"]
    hists: Dict[str, StreamingHistogram] = {}
    totals: Dict[str, float] = {}
    trace_ids = set()
    for r in spans:
        name = r.get("span")
        if not isinstance(name, str):
            continue
        trace_ids.add(r.get("trace_id"))
        dur = r.get("dur_s") or 0.0
        h = hists.get(name)
        if h is None:
            h = hists[name] = StreamingHistogram()
        h.observe(dur)
        totals[name] = totals.get(name, 0.0) + float(dur)
    n_traces = len(trace_ids)
    grand = sum(totals.values()) or 1.0
    critical = {
        name: {"total_s": totals[name],
               "per_request_s": totals[name] / max(n_traces, 1),
               "pct": 100.0 * totals[name] / grand}
        for name in sorted(totals, key=lambda k: -totals[k])}
    joined = None
    if request_records is not None:
        reqs = [r for r in request_records
                if r.get("tag") == "serving.request"]
        matched = [r for r in reqs if r.get("trace_id") in trace_ids]
        statuses: Dict[str, int] = {}
        for r in matched:
            s = str(r.get("status"))
            statuses[s] = statuses.get(s, 0) + 1
        joined = {"completion_records": len(reqs),
                  "matched": len(matched),
                  "unmatched_traces": n_traces - len({
                      r.get("trace_id") for r in matched}),
                  "statuses": statuses}
    return {"traces": n_traces,
            "spans": {k: hists[k].summary() for k in sorted(hists)},
            "critical_path": critical,
            "requests": joined}


def render_trace_summary(summary: Dict[str, Any]) -> str:
    """Aligned text tables of :func:`summarize_trace` output: the
    per-stage latency distribution, then the critical-path breakdown,
    then the completion-record join (when requested)."""
    lines = [f"traces: {summary['traces']}", ""]
    hdr = (f"{'span':<18} {'count':>7} {'mean':>12} {'p50':>12} "
           f"{'p95':>12} {'p99':>12} {'max':>12}")
    lines += [hdr, "-" * len(hdr)]
    for name, s in summary["spans"].items():
        if s.get("count", 0) == 0:
            continue
        lines.append(
            f"{name[:18]:<18} {s['count']:>7} {s['mean']:>12.6g} "
            f"{s['p50']:>12.6g} {s['p95']:>12.6g} {s['p99']:>12.6g} "
            f"{s['max']:>12.6g}")
    lines += ["", "critical path (summed stage time; stages overlap):"]
    hdr = f"{'span':<18} {'total_s':>12} {'per_req_s':>12} {'%':>6}"
    lines += [hdr, "-" * len(hdr)]
    for name, c in summary["critical_path"].items():
        lines.append(f"{name[:18]:<18} {c['total_s']:>12.6g} "
                     f"{c['per_request_s']:>12.6g} {c['pct']:>6.1f}")
    joined = summary.get("requests")
    if joined is not None:
        lines += ["", f"completion records: {joined['completion_records']}"
                  f" ({joined['matched']} matched by trace_id, "
                  f"{joined['unmatched_traces']} trace(s) unmatched)"]
        for s in sorted(joined["statuses"]):
            lines.append(f"  status {s}: {joined['statuses'][s]}")
    return "\n".join(lines)


def trace_breakdown(trace_dir: str, n_steps: int) -> Dict[str, Any]:
    """Join a ``pyprof.trace`` capture with a run's step count: device
    time per HLO category (total and ms/step) plus per-op latency stats
    for the collective categories."""
    from apex_tpu import pyprof

    rows = pyprof.analyze(trace_dir)
    by_cat: Dict[str, Dict[str, float]] = {}
    comm_ops = []
    for r in rows:
        cat = r.get("category") or "(uncategorized)"
        c = by_cat.setdefault(cat, {"total_ms": 0.0, "occurrences": 0})
        c["total_ms"] += r["total_ms"]
        c["occurrences"] += r["occurrences"]
        if any(s in cat.lower() or s in r["name"].lower()
               for s in COMM_CATEGORIES):
            comm_ops.append({"name": r["name"], "category": cat,
                             "occurrences": r["occurrences"],
                             "mean_ms": r["mean_ms"],
                             "total_ms": r["total_ms"]})
    total = sum(c["total_ms"] for c in by_cat.values()) or 1.0
    cats = [{"category": k, "total_ms": v["total_ms"],
             "occurrences": v["occurrences"],
             "ms_per_step": v["total_ms"] / max(n_steps, 1),
             "pct": 100.0 * v["total_ms"] / total}
            for k, v in by_cat.items()]
    cats.sort(key=lambda c: -c["total_ms"])
    comm_ops.sort(key=lambda c: -c["total_ms"])
    return {"n_steps": n_steps, "categories": cats, "comm_ops": comm_ops}


def render_breakdown(bd: Dict[str, Any]) -> str:
    lines = [f"device step-time breakdown ({bd['n_steps']} steps):"]
    hdr = (f"{'category':<28} {'n':>7} {'total_ms':>12} "
           f"{'ms/step':>10} {'%':>6}")
    lines += [hdr, "-" * len(hdr)]
    for c in bd["categories"]:
        lines.append(f"{c['category'][:28]:<28} {c['occurrences']:>7} "
                     f"{c['total_ms']:>12.3f} {c['ms_per_step']:>10.4f} "
                     f"{c['pct']:>6.1f}")
    if bd["comm_ops"]:
        lines += ["", "comm op device latency:"]
        hdr = f"{'op':<44} {'n':>7} {'mean_ms':>10} {'total_ms':>12}"
        lines += [hdr, "-" * len(hdr)]
        for c in bd["comm_ops"]:
            lines.append(f"{c['name'][:44]:<44} {c['occurrences']:>7} "
                         f"{c['mean_ms']:>10.4f} {c['total_ms']:>12.3f}")
    return "\n".join(lines)
