"""Pluggable record sinks — where :class:`~apex_tpu.telemetry.MetricsRegistry`
streams each step record.

The sink protocol is two methods: ``emit(record: dict)`` (called once per
record, possibly from a runtime callback thread — implementations must be
self-synchronizing or append-only) and ``close()``. Records are plain
JSON-able dicts (see ``core.StepRecord``). The serving tier's request
tracer (:mod:`apex_tpu.telemetry.tracing`) rides the same protocol: its
``export_jsonl`` streams one ``tag="serving.trace"`` record per span
through any sink built here, so trace and metric streams can share one
run file.

Built-ins:

- :class:`JsonlSink`   — one ``json.dumps`` line per record (the run file
  ``python -m apex_tpu.telemetry summarize`` consumes).
- :class:`StdoutSink`  — human-greppable ``key=value`` line protocol.
- :class:`NullSink`    — swallow everything (telemetry structurally wired
  but a run that wants zero output).
- :class:`MemorySink`  — append to a list; the test spy that counts
  callbacks per step.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import Any, Dict, List

__all__ = ["Sink", "JsonlSink", "StdoutSink", "NullSink", "MemorySink",
           "make_sink"]


class Sink:
    """Protocol base; subclasses override :meth:`emit`."""

    def emit(self, record: Dict[str, Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSink(Sink):
    def emit(self, record: Dict[str, Any]) -> None:
        pass


class MemorySink(Sink):
    """Append-only in-memory sink — the test spy."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def emit(self, record: Dict[str, Any]) -> None:
        self.records.append(record)


def _strict_jsonable(v):
    """Spec-valid JSON values only: Python's json module would emit bare
    ``Infinity``/``NaN`` tokens (which jq/pandas and every strict JSONL
    consumer reject), and the dynamic scaler guarantees an inf grad_norm
    on growth-probe overflow steps — so non-finite floats become
    ``null``."""
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return None
    if isinstance(v, dict):
        return {k: _strict_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_strict_jsonable(x) for x in v]
    return v


class JsonlSink(Sink):
    """One JSON line per record. ``path_or_file`` is a filesystem path
    (opened for append so crash-guarded reruns accumulate) or any
    writable file object. Flushes every line by default — the contract is
    that a crashed run's file is readable up to its last completed step."""

    def __init__(self, path_or_file, flush_every: int = 1):
        if hasattr(path_or_file, "write"):
            self._f = path_or_file
            self._owns = False
        else:
            self._f = open(path_or_file, "a")
            self._owns = True
        self._flush_every = max(int(flush_every), 1)
        self._n = 0
        self._lock = threading.Lock()

    def emit(self, record: Dict[str, Any]) -> None:
        line = json.dumps(_strict_jsonable(record), default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._n += 1
            if self._n % self._flush_every == 0:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            if self._owns:
                self._f.close()
                self._owns = False


class StdoutSink(Sink):
    """Line protocol on stdout: ``telemetry tag=train seq=3 loss=2.31 ...``
    — greppable live view without a file. (Writes through
    ``sys.stdout.write``; telemetry sinks and logging are the library's
    sanctioned output paths, see tests/L0/test_no_stray_prints.py.)"""

    def __init__(self, stream=None):
        self._stream = stream

    def emit(self, record: Dict[str, Any]) -> None:
        stream = self._stream or sys.stdout
        parts = ["telemetry"]
        for k, v in record.items():
            if isinstance(v, float):
                parts.append(f"{k}={v:.6g}")
            elif isinstance(v, dict):
                parts.append(f"{k}={json.dumps(v, default=str)}")
            else:
                parts.append(f"{k}={v}")
        stream.write(" ".join(parts) + "\n")
        stream.flush()


def make_sink(spec: str) -> Sink:
    """Sink from a CLI/env spec: ``"stdout"`` → :class:`StdoutSink`,
    ``"null"`` → :class:`NullSink`, anything else is a JSONL path."""
    if spec == "stdout":
        return StdoutSink()
    if spec == "null":
        return NullSink()
    return JsonlSink(spec)
