"""Metrics core: counters, gauges, streaming histograms, step records.

The reference apex has no structured telemetry at all — its observability
is NVTX ranges (pyprof) and ad-hoc ``print`` in the recipes. This core is
the missing piece SURVEY §6 calls out: one process-local registry that
owns every metric a training run produces (host- or device-originated),
an in-memory ring of per-step records, and pluggable sinks
(:mod:`apex_tpu.telemetry.sinks`) that stream each record out as it
lands. Device-side values arrive through
:func:`apex_tpu.telemetry.emit_metrics` (one ``jax.debug.callback`` per
step); host-side values through :meth:`MetricsRegistry.counter_inc` /
``gauge_set`` / ``observe`` directly.

Everything here is plain Python on the host — no jax imports — so the
registry can absorb callbacks from the runtime's callback threads
(hence the lock) and be unit-tested without a backend.
"""

from __future__ import annotations

import math
import random
import re
import threading
import time
from typing import Any, Dict, List, Optional

from .sinks import Sink

__all__ = ["StreamingHistogram", "StepRecord", "MetricsRegistry"]

#: reserved keys a StepRecord carries besides the caller's metrics
META_KEYS = ("tag", "seq", "time", "step_time_s")

#: a StepRecord is one JSON-able dict: META_KEYS + the step's metrics
StepRecord = Dict[str, Any]


class StreamingHistogram:
    """Bounded-memory distribution sketch: exact count/sum/min/max plus a
    seeded reservoir sample for quantiles (p50/p95/p99 within reservoir
    sampling error — ample for step-time/latency series of any length).

    Deterministic by construction (fixed-seed RNG per instance) so golden
    tests and re-runs of ``summarize`` agree bit-for-bit.
    """

    def __init__(self, reservoir_size: int = 4096, seed: int = 0):
        if reservoir_size < 1:
            raise ValueError("reservoir_size must be >= 1")
        self.reservoir_size = int(reservoir_size)
        self._rng = random.Random(seed)
        self._sample: List[float] = []
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value) -> None:
        v = float(value)
        if not math.isfinite(v):
            # NaN/inf would poison mean/max/quantiles forever — and the
            # dynamic scaler GUARANTEES an inf grad_norm roughly every
            # scale_window steps (the growth-probe overflow). Those events
            # are counted by the found_inf/overflow series; histograms
            # track the finite distribution only.
            return
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._sample) < self.reservoir_size:
            self._sample.append(v)
        else:  # Vitter's algorithm R
            j = self._rng.randrange(self.count)
            if j < self.reservoir_size:
                self._sample[j] = v

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Linear-interpolated quantile of the reservoir, q in [0, 1]."""
        if not self._sample:
            return 0.0
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        xs = sorted(self._sample)
        pos = q * (len(xs) - 1)
        lo = int(math.floor(pos))
        hi = int(math.ceil(pos))
        if lo == hi:
            return xs[lo]
        frac = pos - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "min": self.min,
            "max": self.max,
        }


# ---------------------------------------------------------- prometheus
# Fixed histogram bucket ladder (seconds-flavored, matching the
# prometheus_client defaults extended one decade down) — a FIXED ladder
# keeps the exposition stable across runs, which the golden test pins.
_PROM_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.075,
                 0.1, 0.25, 0.5, 0.75, 1.0, 2.5, 5.0, 7.5, 10.0, 25.0,
                 50.0, 100.0)

#: ``serving.router.replica<i>.<gauge>`` → labeled series
_PROM_REPLICA_RE = re.compile(r"^serving\.router\.replica(\d+)\.(.+)$")

_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """A valid Prometheus metric name: dots and every other character
    outside ``[a-zA-Z0-9_:]`` become ``_``; a leading digit gets a
    ``_`` prefix (the text exposition format rejects it outright)."""
    out = _PROM_BAD_CHARS.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    """Prometheus sample value: integral floats render without the
    trailing ``.0`` noise (counters read as counts), non-finite values
    as the spec's ``+Inf``/``-Inf``/``NaN`` tokens."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _prom_split(name: str):
    """``(prom_name, labels_dict)`` for a metric name: the per-replica
    router gauge namespace collapses into one labeled series family
    (``serving.router.replica3.queue_depth`` →
    ``serving_router_replica_queue_depth{replica="3"}``); everything
    else is label-less."""
    m = _PROM_REPLICA_RE.match(name)
    if m:
        return (_prom_name(f"serving.router.replica.{m.group(2)}"),
                {"replica": m.group(1)})
    return _prom_name(name), {}


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _jsonable_scalar(v):
    """Host scalar for a metric value: numpy/jax 0-d arrays collapse via
    .item(); bools become 0/1 so every series is numeric in the JSONL.
    Multi-element arrays fall back to a plain list (JSON-able, kept in
    the record but not histogrammed) — a raise here would kill the whole
    step record inside the runtime's callback thread."""
    if hasattr(v, "item"):
        try:
            v = v.item()
        except (TypeError, ValueError):
            tolist = getattr(v, "tolist", None)
            v = tolist() if tolist is not None else float(v)
    if isinstance(v, bool):
        return int(v)
    return v


class MetricsRegistry:
    """Process-local metrics owner: counters, gauges, histograms, a ring
    of the last ``ring_size`` step records, and the sink fan-out.

    Thread-safe: device callbacks (``jax.debug.callback``) may land on
    runtime threads while the training loop reads counters from the main
    thread.
    """

    def __init__(self, ring_size: int = 1024,
                 sinks: Optional[List[Sink]] = None,
                 reservoir_size: int = 4096):
        from collections import deque

        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, StreamingHistogram] = {}
        self.records = deque(maxlen=int(ring_size))
        self.sinks: List[Sink] = list(sinks or [])
        self._reservoir_size = reservoir_size
        self._seq = 0
        self._last_time: Dict[str, float] = {}   # per-tag, for step_time_s

    # ---------------------------------------------------------- primitives
    def counter_inc(self, name: str, value: float = 1.0) -> float:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value
            return self.counters[name]

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the named histogram."""
        with self._lock:
            self._observe_locked(name, value)

    def _observe_locked(self, name: str, value) -> None:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = StreamingHistogram(
                self._reservoir_size)
        h.observe(value)

    # ---------------------------------------------------------- step records
    def record_step(self, metrics: Dict[str, Any],
                    tag: str = "train", observe: bool = True) -> StepRecord:
        """Absorb one step's metric dict: stamp host time + sequence,
        derive ``step_time_s`` (host delta since this tag's previous
        record — the wall-time-per-step series), feed every numeric value
        into its histogram, count overflow events, append to the ring,
        and fan out to the sinks.

        ``observe=False`` keeps the record out of the histogram layer
        (no per-value reservoirs, no ``step_time_s`` series) — for
        EVENT-shaped records (e.g. the serving tier's per-request
        completion records) whose ids/latencies either are not series or
        already land in dedicated histograms; they still ride the ring
        and the sinks."""
        now = time.time()
        with self._lock:
            rec: StepRecord = {"tag": tag, "seq": self._seq, "time": now}
            self._seq += 1
            prev = self._last_time.get(tag)
            self._last_time[tag] = now
            if prev is not None and observe:
                rec["step_time_s"] = now - prev
                self._observe_locked(f"{tag}.step_time_s",
                                     rec["step_time_s"])
            for k, v in metrics.items():
                v = _jsonable_scalar(v)
                rec[k] = v
                if observe and isinstance(v, (int, float)):
                    self._observe_locked(f"{tag}.{k}", v)
            # the scaler's found_inf is the overflow-event signal
            # (SURVEY §6: scale trajectory + overflow events)
            if rec.get("found_inf"):
                self.counters["overflow_events"] = \
                    self.counters.get("overflow_events", 0.0) + 1.0
            self.records.append(rec)
            sinks = list(self.sinks)
        for s in sinks:
            s.emit(rec)
        return rec

    # ---------------------------------------------------------- summaries
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time view: counters, gauges, histogram summaries."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: h.summary()
                               for k, h in self.histograms.items()},
            }

    def emit_snapshot(self, tag: str = "summary") -> StepRecord:
        """Write the snapshot to the sinks as one final self-describing
        record (the run's comm-health / aggregate line)."""
        snap = self.snapshot()
        rec: StepRecord = {"tag": tag, "seq": self._seq,
                           "time": time.time(), **snap}
        with self._lock:
            self._seq += 1
            self.records.append(rec)
            sinks = list(self.sinks)
        for s in sinks:
            s.emit(rec)
        return rec

    def render_prometheus(self) -> str:
        """The registry as Prometheus text exposition format (version
        0.0.4), stdlib-only — the fleet snapshot a scrape endpoint or
        a node-exporter textfile collector can serve directly.

        - counters → ``# TYPE <name> counter`` samples, gauges →
          ``gauge`` samples; metric names are sanitized
          (``serving.ttft_s`` → ``serving_ttft_s``, anything outside
          ``[a-zA-Z0-9_:]`` becomes ``_``, leading digits get a ``_``
          prefix).
        - the per-replica router gauges
          (``serving.router.replica<i>.<gauge>``) collapse into ONE
          labeled family per gauge:
          ``serving_router_replica_<gauge>{replica="<i>"}`` — the
          namespacing contract, machine-readable.
        - histograms render as Prometheus histograms over a FIXED
          bucket ladder (``_bucket{le=...}`` cumulative counts +
          ``_sum`` / ``_count``). Bucket counts are exact while the
          reservoir holds every observation and reservoir-estimated
          (uniformly scaled) past that — ``_sum``/``_count`` stay
          exact always.

        Output is deterministically ordered (family name, then label
        set), so goldens and scrape diffs are stable."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = {k: (h.count, h.total, list(h._sample))
                     for k, h in self.histograms.items()}

        def _families(series: Dict[str, float]):
            fams: Dict[str, List] = {}
            for name, value in series.items():
                pname, labels = _prom_split(name)
                fams.setdefault(pname, []).append((labels, value))
            return fams

        lines: List[str] = []
        typed = [("counter", _families(counters)),
                 ("gauge", _families(gauges))]
        for kind, fams in typed:
            for pname in sorted(fams):
                lines.append(f"# TYPE {pname} {kind}")
                for labels, value in sorted(
                        fams[pname], key=lambda lv: sorted(
                            lv[0].items())):
                    lines.append(f"{pname}{_prom_labels(labels)} "
                                 f"{_prom_value(value)}")
        for name in sorted(hists):
            count, total, sample = hists[name]
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} histogram")
            scale = (count / len(sample)) if sample else 0.0
            for le in _PROM_BUCKETS:
                c = sum(1 for v in sample if v <= le)
                lines.append(
                    f'{pname}_bucket{{le="{_prom_value(le)}"}} '
                    f"{int(round(c * scale))}")
            lines.append(f'{pname}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{pname}_sum {_prom_value(total)}")
            lines.append(f"{pname}_count {count}")
        return "\n".join(lines) + "\n" if lines else ""

    def close(self) -> None:
        for s in self.sinks:
            s.close()
