"""apex_tpu.mlp — whole-MLP fused forward/backward (reference: apex/mlp).

The reference's ``apex/mlp/mlp.py — class MLP, class MlpFunction`` drives a
C++/CUDA extension (``csrc/mlp.cpp``, ``csrc/mlp_cuda.cu — mlp_forward,
mlp_backward``) that runs every layer's cuBLAS GEMM plus a fused bias+ReLU
epilogue out of one workspace, to beat eager-mode launch overhead.

On TPU the entire stack of ``dot_general + bias + activation`` layers is traced
into one XLA computation: the epilogue fusion the reference hand-writes is what
XLA does by default, and the MXU wants exactly these large dense GEMMs. What we
keep is the *API and numerics*: an ``mlp_sizes``-driven module, bias/activation
flags with the reference's names, fp32 params with half I/O under amp, and a
functional form mirroring ``mlp_cuda.forward``'s signature shape.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["MLP", "mlp_function"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(x, weights: Sequence[Any], biases: Optional[Sequence[Any]],
                 activation: str = "relu"):
    """Run the full MLP stack in one traced computation.

    Mirrors ``apex/mlp/mlp.py — class MlpFunction`` (forward through all
    layers, activation applied after every layer, as the reference kernel
    does). ``biases`` is None for the bias-free variant.
    """
    if activation not in _ACTIVATIONS:
        raise ValueError(
            f"activation must be one of {sorted(_ACTIVATIONS)}, got "
            f"{activation!r}")
    act = _ACTIVATIONS[activation]
    # O1 engine: 'linear' is an FP16_FUNCS entry — under an active autocast
    # policy the GEMMs run in the half dtype (weights follow via the
    # cast-to-y.dtype below, apex's cached weight cast); fp32 accumulation
    # is kept via preferred_element_type either way.
    from apex_tpu.amp.autocast import op_compute_dtype
    gemm_dtype = op_compute_dtype("linear")
    y = x if gemm_dtype is None else jnp.asarray(x, gemm_dtype)
    out_dtype = x.dtype if gemm_dtype is None else gemm_dtype
    for i, w in enumerate(weights):
        # apex stores weights as (out_features, in_features) (torch Linear
        # layout); keep that layout so state dicts line up, transpose in-trace
        # (free under XLA).
        y = jnp.dot(y, jnp.asarray(w, y.dtype).T,
                    preferred_element_type=jnp.float32)
        if biases is not None:
            y = y + jnp.asarray(biases[i], jnp.float32)
        y = act(y)
        y = jnp.asarray(y, out_dtype)
    return y


class MLP(nn.Module):
    """Fused multi-layer perceptron (reference: apex/mlp/mlp.py — class MLP).

    ``mlp_sizes`` includes the input feature size: ``[1024, 512, 256]`` is a
    two-layer MLP 1024→512→256. ``activation`` ∈ {'none', 'relu', 'sigmoid'}
    is applied after every layer, matching the reference kernel's epilogue.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs an input size and >=1 layer")
        if self.dtype is not None:
            x = jnp.asarray(x, self.dtype)
        weights = []
        biases = [] if self.bias else None
        for i in range(len(self.mlp_sizes) - 1):
            in_f, out_f = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            # apex/mlp/mlp.py — reset_parameters: weights ~ N(0,
            # sqrt(2/(fan_in+fan_out))) (Xavier-normal), biases ~ N(0,
            # sqrt(1/fan_out)).
            w_std = (2.0 / (in_f + out_f)) ** 0.5
            b_std = (1.0 / out_f) ** 0.5
            weights.append(self.param(
                f"weight_{i}", nn.initializers.normal(stddev=w_std),
                (out_f, in_f), self.param_dtype))
            if self.bias:
                biases.append(self.param(
                    f"bias_{i}", nn.initializers.normal(stddev=b_std),
                    (out_f,), self.param_dtype))
        return mlp_function(x, weights, biases, self.activation)
