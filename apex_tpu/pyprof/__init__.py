"""apex_tpu.pyprof — profiling subsystem (reference: apex/pyprof, P42).

The reference's pyprof has three stages: ``pyprof.nvtx.init()`` monkey-patches
torch ops to emit NVTX ranges; ``pyprof/parse`` ingests nvprof/Nsight sqlite
dumps; ``pyprof/prof`` turns them into per-kernel flop/byte reports.

TPU-native mapping (SURVEY §6 — tracing):

- NVTX ranges → :func:`annotate` (``jax.named_scope`` inside traced code, so
  the scope lands in the XLA HLO and shows up in the profiler UI, plus a host
  ``TraceAnnotation`` for eager sections).
- nvprof capture → :func:`trace` around ``jax.profiler`` (perfetto dump).
- the flop/byte report → :func:`cost_report`, straight from XLA's own cost
  analysis of the compiled executable — no dump parsing, the compiler knows.
- iteration timing (main_amp.py --prof N's role) → :class:`StepTimer`.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

__all__ = ["init", "annotate", "trace", "cost_report", "StepTimer"]

_enabled = True


def init(enabled: bool = True):
    """Reference: pyprof.nvtx.init() — global enable switch.

    Gates :func:`trace` and eager uses of :func:`annotate`. Inside jitted
    code the switch is read at TRACE time and baked into the cached
    executable — flip it before the first call of a jitted function (or
    ``jax.clear_caches()``), the same way the reference requires init()
    before the ops it patches are first invoked."""
    global _enabled
    _enabled = enabled


@contextlib.contextmanager
def annotate(name: str):
    """Named range visible in both the XLA profile (named_scope) and host
    timeline (TraceAnnotation). Usable inside and outside jit."""
    if not _enabled:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a profiler trace (perfetto) to ``log_dir`` — the nvprof
    capture stage. View with tensorboard or ui.perfetto.dev."""
    if not _enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_report(fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """Per-executable flop/byte report from XLA's cost analysis.

    The reference's pyprof/prof derives flops & bytes per kernel from
    captured traces; XLA computes the same quantities at compile time, so the
    report comes from ``jit(fn).lower(...).compile().cost_analysis()``.
    Returns {'flops', 'bytes_accessed', 'arithmetic_intensity', 'raw'}.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = compiled.cost_analysis()
    # cost_analysis: dict (newer jax) or list of per-device dicts (older)
    raw = analyses if isinstance(analyses, dict) else (analyses or [{}])[0]
    flops = float(raw.get("flops", 0.0))
    if "bytes accessed" in raw:
        # aggregate key already equals the sum of the per-operand
        # 'bytes accessedN{}' breakdown keys — don't double count
        in_bytes = float(raw["bytes accessed"])
    else:
        in_bytes = sum(float(v) for k, v in raw.items()
                       if k.startswith("bytes accessed"))
    report = {
        "flops": flops,
        "bytes_accessed": in_bytes,
        "arithmetic_intensity": flops / in_bytes if in_bytes else 0.0,
        "raw": dict(raw),
    }
    return report


class StepTimer:
    """Wall-clock iteration timing with warmup skip — the role of the
    imagenet recipe's --prof flag plus its img/s accounting, reusable.

    jax dispatch is async: synchronize inside the timed block (or pass
    ``sync=``) or you measure enqueue time, not execution time.

    >>> timer = StepTimer(warmup=3)
    >>> for batch in loader:
    ...     with timer.step(items=batch_size):
    ...         state, m = jit_step(state, batch)  # noqa
    ...         m["loss"].block_until_ready()      # sync point
    >>> print(timer.report())
    """

    def __init__(self, warmup: int = 3, sync: Optional[Callable] = None):
        self.warmup = warmup
        self.sync = sync
        self._times: List[float] = []
        self._items: List[int] = []
        self._count = 0

    @contextlib.contextmanager
    def step(self, items: int = 1):
        t0 = time.perf_counter()
        yield
        if self.sync is not None:
            self.sync()
        dt = time.perf_counter() - t0
        self._count += 1
        if self._count > self.warmup:
            self._times.append(dt)
            self._items.append(items)

    @property
    def times(self) -> np.ndarray:
        return np.asarray(self._times)

    def report(self) -> Dict[str, float]:
        if not self._times:
            return {"steps": 0}
        t = self.times
        items = float(np.sum(self._items))
        return {
            "steps": len(t),
            "mean_s": float(t.mean()),
            "p50_s": float(np.percentile(t, 50)),
            "p90_s": float(np.percentile(t, 90)),
            "items_per_s": items / float(t.sum()),
        }
