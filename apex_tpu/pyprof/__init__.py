"""apex_tpu.pyprof — profiling subsystem (reference: apex/pyprof, P42).

The reference's pyprof has three stages: ``pyprof.nvtx.init()`` monkey-patches
torch ops to emit NVTX ranges; ``pyprof/parse`` ingests nvprof/Nsight sqlite
dumps; ``pyprof/prof`` turns them into per-kernel flop/byte reports.

TPU-native mapping (SURVEY §6 — tracing):

- NVTX ranges → :func:`annotate` (``jax.named_scope`` inside traced code, so
  the scope lands in the XLA HLO and shows up in the profiler UI, plus a host
  ``TraceAnnotation`` for eager sections).
- nvprof capture → :func:`trace` around ``jax.profiler`` (perfetto dump).
- the flop/byte report → :func:`cost_report`, straight from XLA's own cost
  analysis of the compiled executable — no dump parsing, the compiler knows.
- pyprof/parse + pyprof/prof (sqlite dump → per-kernel table) →
  :func:`analyze`: parse the captured trace's device lane into per-op rows
  (occurrences, ms, flops, bytes) and :func:`report` to format them.
- iteration timing (main_amp.py --prof N's role) → :class:`StepTimer`.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

__all__ = ["init", "annotate", "trace", "cost_report", "analyze", "report",
           "device_busy", "step_device_throughput",
           "device_throughput_line", "StepTimer"]

_enabled = True


def init(enabled: bool = True):
    """Reference: pyprof.nvtx.init() — global enable switch.

    Gates :func:`trace` and eager uses of :func:`annotate`. Inside jitted
    code the switch is read at TRACE time and baked into the cached
    executable — flip it before the first call of a jitted function (or
    ``jax.clear_caches()``), the same way the reference requires init()
    before the ops it patches are first invoked."""
    global _enabled
    _enabled = enabled


@contextlib.contextmanager
def annotate(name: str):
    """Named range visible in both the XLA profile (named_scope) and host
    timeline (TraceAnnotation). Usable inside and outside jit."""
    if not _enabled:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a profiler trace (perfetto) to ``log_dir`` — the nvprof
    capture stage. View with tensorboard or ui.perfetto.dev."""
    if not _enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_report(fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """Per-executable flop/byte report from XLA's cost analysis.

    The reference's pyprof/prof derives flops & bytes per kernel from
    captured traces; XLA computes the same quantities at compile time, so the
    report comes from ``jit(fn).lower(...).compile().cost_analysis()``.
    Returns {'flops', 'bytes_accessed', 'arithmetic_intensity', 'raw'}.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analyses = compiled.cost_analysis()
    # cost_analysis: dict (newer jax) or list of per-device dicts (older)
    raw = analyses if isinstance(analyses, dict) else (analyses or [{}])[0]
    flops = float(raw.get("flops", 0.0))
    if "bytes accessed" in raw:
        # aggregate key already equals the sum of the per-operand
        # 'bytes accessedN{}' breakdown keys — don't double count
        in_bytes = float(raw["bytes accessed"])
    else:
        in_bytes = sum(float(v) for k, v in raw.items()
                       if k.startswith("bytes accessed"))
    report = {
        "flops": flops,
        "bytes_accessed": in_bytes,
        "arithmetic_intensity": flops / in_bytes if in_bytes else 0.0,
        "raw": dict(raw),
    }
    return report


def _trace_files(trace_dir: str) -> List[str]:
    """The newest profile run's chrome-trace dumps under ``trace_dir``
    (one per host), or ``trace_dir`` itself if it is already a dump."""
    if os.path.isfile(trace_dir):
        return [trace_dir]
    runs = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(
            f"no profile runs under {trace_dir!r} — capture one with "
            "pyprof.trace(log_dir) first")
    files = sorted(glob.glob(os.path.join(runs[-1], "*.trace.json.gz")))
    if not files:
        raise FileNotFoundError(f"no *.trace.json.gz in {runs[-1]!r}")
    return files


def _leaf_spans(evs: List[dict],
                lane_of: Optional[Callable[[dict], tuple]] = None
                ) -> List[dict]:
    """Drop spans that PROPERLY enclose another span on the same lane —
    parents double-count their children's time. One sorted sweep per lane
    with an open-interval stack. Identical intervals are siblings (two
    same-timestamp ops), not parent/child. ``lane_of`` defaults to
    (pid, tid); pass a richer key when events come from several files
    whose pid namespaces are independent."""
    if lane_of is None:
        lane_of = lambda e: (e.get("pid"), e.get("tid"))  # noqa: E731
    lanes: Dict[tuple, List[dict]] = {}
    for e in evs:
        lanes.setdefault(lane_of(e), []).append(e)
    out: List[dict] = []
    for lane in lanes.values():
        lane.sort(key=lambda e: (float(e.get("ts", 0.0)),
                                 -float(e.get("dur", 0.0))))
        parents: set = set()
        stack: List[tuple] = []          # (start_ts, end_ts, id(event))
        for e in lane:
            ts = float(e.get("ts", 0.0))
            end = ts + float(e.get("dur", 0.0))
            while stack and ts >= stack[-1][1]:
                stack.pop()
            if stack and (stack[-1][0], stack[-1][1]) != (ts, end):
                # e nests PROPERLY inside the top — and inside every twin
                # of the top (identical intervals sit adjacent on the
                # stack as siblings; each one encloses e equally)
                top = (stack[-1][0], stack[-1][1])
                for s_ts, s_end, s_id in reversed(stack):
                    if (s_ts, s_end) != top:
                        break
                    parents.add(s_id)
            stack.append((ts, end, id(e)))
        out += [e for e in lane if id(e) not in parents]
    return out


def _load_events(trace_dir: str) -> List[tuple]:
    """All complete ('X') events of the newest dump as (lane_name,
    file_idx, event) triples. pid namespaces are PER FILE (one dump per
    host), so each event is classified against its own file's
    process_name metadata and lanes never mix across files."""
    events: List[tuple] = []
    for fi, path in enumerate(_trace_files(trace_dir)):
        with gzip.open(path, "rt") as f:
            data = json.load(f)
        evs = data.get("traceEvents", [])
        pids = {e["pid"]: e.get("args", {}).get("name", "")
                for e in evs
                if e.get("ph") == "M" and e.get("name") == "process_name"}
        events += [(pids.get(e.get("pid"), ""), fi, e)
                   for e in evs if e.get("ph") == "X"]
    return events


def _device_ops(events: List[tuple]) -> tuple:
    """(ops, file_of) for the device lanes of :func:`_load_events` output:
    per-op HLO events when the backend cost-annotates them
    (``hlo_category``), else the proper-nesting leaf sweep so region
    wrappers (jit_fn(...)) don't double-count their children."""
    file_of = {id(e): fi for _, fi, e in events}
    dev = [e for lane, _, e in events if lane.startswith("/device:")]
    ops = [e for e in dev if "hlo_category" in e.get("args", {})]
    if not ops:
        ops = _leaf_spans(dev, lane_of=lambda e: (file_of[id(e)],
                                                  e.get("pid"),
                                                  e.get("tid")))
    return ops, file_of


def analyze(trace_dir: str, top: Optional[int] = None) -> List[Dict[str, Any]]:
    """Per-op table from a captured trace — the reference's pyprof/parse +
    pyprof/prof stages (nvprof sqlite → per-kernel name/occurrence/ns/
    flops/bytes report) applied to the ``jax.profiler`` dump that
    :func:`trace` writes.

    Reads the device lanes' HLO-op events (each carries its duration plus
    XLA's own ``model_flops`` / ``bytes_accessed``) and aggregates by op
    name. Returns rows sorted by total time, descending::

        {"name", "category", "occurrences", "total_ms", "mean_ms",
         "flops", "bytes", "intensity", "pct_time"}

    ``flops``/``bytes`` are totals across occurrences; ``intensity`` is
    flops/byte; ``pct_time`` is this op's share of all device-op time.
    When the dump has no cost-annotated device ops (host-only capture,
    or a backend without per-op HLO args), leaf spans are tabulated
    instead — parents that enclose other spans are dropped so region
    wrappers don't double-count their children — with zero flops/bytes.
    """
    events = _load_events(trace_dir)
    ops, file_of = _device_ops(events)
    if not ops:
        # host-only capture: tabulate the host lanes' leaf spans instead
        ops = _leaf_spans(
            [e for _, _, e in events],
            lane_of=lambda e: (file_of[id(e)], e.get("pid"),
                               e.get("tid")))

    rows: Dict[str, Dict[str, Any]] = {}
    for e in ops:
        args = e.get("args", {})
        r = rows.setdefault(e["name"], {
            "name": e["name"],
            "category": args.get("hlo_category", ""),
            "occurrences": 0, "total_ms": 0.0,
            "flops": 0.0, "bytes": 0.0,
        })
        r["occurrences"] += 1
        r["total_ms"] += float(e.get("dur", 0.0)) / 1e3   # dur is µs
        r["flops"] += float(args.get("model_flops", 0.0))
        r["bytes"] += float(args.get("raw_bytes_accessed",
                                     args.get("bytes_accessed", 0.0)))
    total_ms = sum(r["total_ms"] for r in rows.values()) or 1.0
    out = sorted(rows.values(), key=lambda r: -r["total_ms"])
    for r in out:
        r["mean_ms"] = r["total_ms"] / r["occurrences"]
        r["intensity"] = r["flops"] / r["bytes"] if r["bytes"] else 0.0
        r["pct_time"] = 100.0 * r["total_ms"] / total_ms
    return out[:top] if top else out


def device_busy(trace_dir: str) -> Dict[str, float]:
    """Device-time summary of a captured trace — the timing anchor that
    wall-clock measurement can't provide when dispatch is remote (the
    reference's equivalent is nvprof's kernel-time column, which times the
    GPU itself rather than the host loop; SURVEY §6 tracing / §7's
    "time the device, not the python loop" rule).

    Reads the ``/device:`` lanes' complete events and returns::

        {"busy_ms":  sum of leaf device-op durations (idle gaps excluded),
         "span_ms":  max over lanes of (last op end − first op start),
         "n_events": leaf device ops counted,
         "n_lanes":  device lanes seen}

    All readings come from the single BUSIEST device lane (most leaf-op
    time): chrome dumps split one device into sub-lanes ("XLA Ops",
    "Steps", copy streams, …) that mirror the same execution, so summing
    across lanes would double-count occupancy. ``span_ms`` is that lane's
    elapsed time, first op start to last op end (inter-op bubbles
    included); ``busy_ms`` its pure occupancy — ``busy_ms/span_ms`` is
    the duty cycle (ops overlapping *within* the lane can push it
    marginally over 1). ``n_lanes`` counts all device lanes seen. All
    zeros when the dump has no device lanes (host-only backends) —
    callers must fall back to wall clock.
    """
    events = _load_events(trace_dir)
    ops, file_of = _device_ops(events)
    if not ops:
        return {"busy_ms": 0.0, "span_ms": 0.0, "n_events": 0, "n_lanes": 0}
    n_lanes = len({(file_of[id(e)], e.get("pid"), e.get("tid"))
                   for lane, _, e in events
                   if lane.startswith("/device:")})
    per_lane: Dict[tuple, List[dict]] = {}
    for e in ops:
        key = (file_of[id(e)], e.get("pid"), e.get("tid"))
        per_lane.setdefault(key, []).append(e)
    lane_ops = max(per_lane.values(),
                   key=lambda es: sum(float(e.get("dur", 0.0)) for e in es))
    busy_us = sum(float(e.get("dur", 0.0)) for e in lane_ops)
    starts = [float(e.get("ts", 0.0)) for e in lane_ops]
    ends = [float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
            for e in lane_ops]
    span_us = max(ends) - min(starts)
    return {"busy_ms": busy_us / 1e3, "span_ms": span_us / 1e3,
            "n_events": len(lane_ops), "n_lanes": n_lanes}


def report(rows: List[Dict[str, Any]]) -> str:
    """Format :func:`analyze` rows as the aligned text table the
    reference's ``python -m pyprof.prof`` prints."""
    hdr = f"{'op':<40} {'n':>5} {'ms':>10} {'%':>6} {'GFLOP':>10} " \
          f"{'MB':>10} {'F/B':>8}"
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        lines.append(
            f"{r['name'][:40]:<40} {r['occurrences']:>5} "
            f"{r['total_ms']:>10.3f} {r['pct_time']:>6.1f} "
            f"{r['flops'] / 1e9:>10.3f} {r['bytes'] / 1e6:>10.3f} "
            f"{r['intensity']:>8.2f}")
    return "\n".join(lines)


def step_device_throughput(step_fn, state, batch, n, items_per_step):
    """Time ``n`` steps of a ``(state, batch) -> (state, metrics)`` train
    step on the profiler's DEVICE lanes and return a reading, or ``None``
    when no reading is possible — the recipes' ``--prof-device`` flag
    (the apex recipes' --prof role on device time).

    Observation-only by contract: the steps run on a deep COPY of
    ``state`` (donated input buffers would otherwise be invalidated under
    the caller's feet and the real state silently advanced past its step
    count), and EVERY failure — profiler already active, corrupt dump,
    a crash inside the profiled step — degrades to ``None`` rather than
    raising, so a timing nicety can never cost the caller its checkpoint.

    Returns ``{"items_per_s", "ms_per_step", "duty"}``.
    """
    if n <= 0:
        return None
    import tempfile

    import jax
    import jax.numpy as jnp

    try:
        prof_state = jax.tree_util.tree_map(jnp.copy, state)
        with tempfile.TemporaryDirectory() as td:
            with trace(td):
                metrics = None
                for _ in range(n):
                    prof_state, metrics = step_fn(prof_state, batch)
                jax.block_until_ready(metrics)
            d = device_busy(td)
    except Exception:  # noqa: BLE001 — observation-only, see docstring
        return None
    if d["span_ms"] <= 0:
        return None
    return {"items_per_s": n * items_per_step / (d["span_ms"] / 1e3),
            "ms_per_step": d["span_ms"] / n,
            "duty": d["busy_ms"] / d["span_ms"]}


def device_throughput_line(step_fn, state, batch, n, items_per_step,
                           unit):
    """The recipes' shared ``--prof-device`` rendering: one formatted
    line for the reading of :func:`step_device_throughput`, ``None``
    when the flag is off (``n == 0`` — print nothing). Negative ``n``
    gets its own diagnostic so a typo isn't misread as a backend
    problem. Never raises (same contract as the underlying helper)."""
    if n == 0:
        return None
    if n < 0:
        return f"device throughput: n/a (--prof-device {n} ignored)"
    r = step_device_throughput(step_fn, state, batch, n, items_per_step)
    if r is None:
        return ("device throughput: n/a (no device lanes, or profiling "
                "unavailable)")
    return (f"device throughput: {r['items_per_s']:,.1f} {unit} "
            f"({r['ms_per_step']:.2f} ms/step, duty {r['duty']:.2f})")


class StepTimer:
    """Wall-clock iteration timing with warmup skip — the role of the
    imagenet recipe's --prof flag plus its img/s accounting, reusable.

    jax dispatch is async: synchronize inside the timed block (or pass
    ``sync=``) or you measure enqueue time, not execution time.

    >>> timer = StepTimer(warmup=3)
    >>> for batch in loader:
    ...     with timer.step(items=batch_size):
    ...         state, m = jit_step(state, batch)  # noqa
    ...         m["loss"].block_until_ready()      # sync point
    >>> print(timer.report())
    """

    def __init__(self, warmup: int = 3, sync: Optional[Callable] = None):
        self.warmup = warmup
        self.sync = sync
        self._times: List[float] = []
        self._items: List[int] = []
        self._count = 0

    @contextlib.contextmanager
    def step(self, items: int = 1):
        t0 = time.perf_counter()
        yield
        if self.sync is not None:
            self.sync()
        dt = time.perf_counter() - t0
        self._count += 1
        if self._count > self.warmup:
            self._times.append(dt)
            self._items.append(items)

    @property
    def times(self) -> np.ndarray:
        return np.asarray(self._times)

    def report(self) -> Dict[str, float]:
        if not self._times:
            return {"steps": 0}
        t = self.times
        items = float(np.sum(self._items))
        return {
            "steps": len(t),
            "mean_s": float(t.mean()),
            "p50_s": float(np.percentile(t, 50)),
            "p90_s": float(np.percentile(t, 90)),
            "items_per_s": items / float(t.sum()),
        }
