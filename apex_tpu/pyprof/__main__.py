"""CLI for the analyze stage — the reference's ``python -m pyprof.prof``
usage (apex/pyprof/prof/__main__.py drives parse→prof over an nvprof
dump; here the dump is the ``jax.profiler`` capture pyprof.trace wrote):

    python -m apex_tpu.pyprof /tmp/trace_dir [--top N] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys

from . import analyze, report


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.pyprof",
        description="Per-op table from a captured jax.profiler trace")
    p.add_argument("trace_dir",
                   help="log dir passed to pyprof.trace (or a "
                        "*.trace.json.gz directly)")
    p.add_argument("--top", type=int, default=None,
                   help="only the N most time-consuming ops")
    p.add_argument("--json", action="store_true",
                   help="one JSON row per op instead of the table")
    args = p.parse_args(argv)
    try:
        rows = analyze(args.trace_dir, top=args.top)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        print(report(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
