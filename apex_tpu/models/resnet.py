"""Flax ResNet family — the benchmark workload of the reference recipes.

The reference's canonical example (examples/imagenet/main_amp.py — main) pulls
``torchvision.models.resnet{18,50}`` and wraps them with amp + apex DDP. A
TPU-native framework needs its own model zoo for those recipes, so this module
provides ResNet v1.5 (stride-2 in the 3x3 conv of the bottleneck, matching
torchvision and the NVIDIA ResNet50 v1.5 benchmark definition) in flax.linen.

TPU-first design decisions:
- NHWC layout throughout (flax default) — channels-last is the native TPU conv
  layout; the reference's NCHW is a CUDA convention we deliberately do not copy.
- ``dtype`` (compute) and ``param_dtype`` (storage) are plumbed separately so
  the amp Policy can run bf16 compute with fp32 params (O1) or bf16 params with
  fp32 batchnorm statistics (O2, keep_batchnorm_fp32 — norms get
  ``norm_dtype``; the fp32 part of the contract is the stats/param storage,
  which flax pins to fp32 regardless of the bf16 apply — see the norm_dtype
  resolution comment in ResNet.__call__).
- The norm layer is injectable (``norm_cls``) so
  apex_tpu.parallel.SyncBatchNorm (stat-psum over a mesh axis) slots in the
  same way apex's ``convert_syncbn_model`` rewrites nn.BatchNorm2d modules
  (reference: apex/parallel/__init__.py — convert_syncbn_model).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """ResNet basic block (two 3x3 convs) — resnet18/34."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, padding=[(1, 1), (1, 1)],
                      name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                      name="conv2")(y)
        y = self.norm(scale_init=nn.initializers.zeros, name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(residual + y)


class Bottleneck(nn.Module):
    """ResNet v1.5 bottleneck (stride on the 3x3) — resnet50/101/152."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        # zero-init the last BN gamma: standard "bag of tricks" residual
        # zero-gamma, same as NVIDIA's recipe default
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5, NHWC, flax.linen.

    ``norm_cls(use_running_average=..., dtype=..., param_dtype=...)`` —
    anything BatchNorm-shaped works, including
    apex_tpu.parallel.SyncBatchNorm bound to a mesh axis.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    norm_dtype: Optional[Any] = None
    norm_cls: Optional[ModuleDef] = None
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        # dtype=None consults the O1 engine per op class: convs/fc run in
        # the policy half dtype (FP16_FUNCS 'conv2d'/'linear'), batch norm
        # stays fp32 (FP32_FUNCS 'batch_norm'); no active policy → fp32.
        from apex_tpu.amp.autocast import resolve_dtype
        conv_dtype = resolve_dtype(self.dtype, "conv2d", jnp.float32)
        fc_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        conv = functools.partial(nn.Conv, use_bias=False, dtype=conv_dtype,
                                 param_dtype=self.param_dtype)
        # norm_dtype=None: the O1 engine's opinion if a policy is active
        # (batch_norm is FP32_FUNCS → fp32 apply, apex O1 semantics), else
        # FOLLOW THE CONV DTYPE. The keep_batchnorm_fp32 contract of apex
        # O2 is about where statistics and parameters live: flax always
        # promotes the mean/var reduction to fp32
        # (normalization._compute_stats, force_float32_reductions) and
        # param_dtype below pins scale/bias/running stats to fp32. A bf16
        # APPLY on bf16 activations preserves that contract while halving
        # the HBM traffic of every bn->relu->conv edge — on the
        # bandwidth-bound ResNet-50 O2 step this is +28% measured
        # throughput (2005 -> 2573 img/s/chip on v5e, device-trace basis,
        # identical loss to 4 decimals; BASELINE.md round-5 perf note).
        norm_dtype = self.norm_dtype if self.norm_dtype is not None \
            else resolve_dtype(None, "batch_norm", conv_dtype)
        base_norm = self.norm_cls if self.norm_cls is not None else nn.BatchNorm
        norm = functools.partial(
            base_norm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=norm_dtype, param_dtype=jnp.float32)

        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides=strides,
                                   conv=conv, norm=norm, act=self.act,
                                   name=f"stage{i + 1}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=fc_dtype,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=Bottleneck)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=Bottleneck)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=Bottleneck)

_ZOO = {"resnet18": ResNet18, "resnet34": ResNet34, "resnet50": ResNet50,
        "resnet101": ResNet101, "resnet152": ResNet152}


def create_model(name: str, **kwargs) -> ResNet:
    """By-name constructor mirroring the reference recipe's
    ``models.__dict__[args.arch]()`` (examples/imagenet/main_amp.py — main)."""
    try:
        return _ZOO[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown arch {name!r}; available: {sorted(_ZOO)}") from None
