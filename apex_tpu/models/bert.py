"""BERT encoder + pretraining heads — the framework's config-4 workload.

The reference drives BERT-large pretraining from NVIDIA DeepLearningExamples
(BASELINE.json config 4: "BERT-large pretraining with FusedLAMB + amp O2");
apex supplies FusedLAMB, FusedLayerNorm, and the fmha/xentropy kernels. This
is the standalone TPU equivalent built from the same framework tiers:

- post-LN encoder blocks (original BERT topology) with
  :class:`apex_tpu.normalization.FusedLayerNorm`
- attention via the Pallas flash kernel with ``segment_ids`` carrying the
  padding mask — the varlen trick fmhalib (apex/contrib/fmha) uses for
  MLPerf BERT, expressed as segment-blocked tiles instead of cu_seqlens
- MLM + NSP pretraining heads; MLM loss masked by ``masked_lm_positions``
  gather, the DeepLearningExamples formulation.

bf16 compute / fp32 params is the expected amp-O2 configuration.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention
from apex_tpu.normalization import FusedLayerNorm

__all__ = ["BertConfig", "BertModel", "BertForPreTraining", "create_bert"]


class BertConfig:
    """Mirror of the HuggingFace/DeepLearningExamples bert_config.json keys."""

    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.layer_norm_eps = layer_norm_eps


class BertLayer(nn.Module):
    """Post-LN block: LN(x + attn(x)); LN(x + mlp(x))."""

    config: BertConfig
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, attention_mask, train: bool):
        cfg = self.config
        # dtype=None consults the O1 engine: GEMMs are FP16_FUNCS 'linear'
        # (half under an active policy, fp32 otherwise); FusedLayerNorm below
        # receives the raw self.dtype and does its own 'layer_norm' (FP32)
        # resolution
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        B, S, H = x.shape
        heads = cfg.num_attention_heads
        d = H // heads
        qkv = nn.Dense(3 * H, dtype=dense_dtype,
                       param_dtype=self.param_dtype, name="qkv")(x)
        qkv = qkv.reshape(B, S, 3, heads, d)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        # padding mask as segment ids: real tokens (1) attend only among
        # themselves; pad tokens (0) form their own segment and are dropped
        # from the loss. This is the Pallas-native form of fmhalib's varlen
        # packing (apex/contrib/fmha — cu_seqlens).
        seg = attention_mask.astype(jnp.int32)
        out = flash_attention(q, k, v, segment_ids=seg)
        out = jnp.moveaxis(out, 1, 2).reshape(B, S, H)
        out = nn.Dense(H, dtype=dense_dtype, param_dtype=self.param_dtype,
                       name="attn_out")(out)
        if cfg.hidden_dropout_prob > 0.0:
            out = nn.Dropout(rate=cfg.hidden_dropout_prob,
                             deterministic=not train)(out)
        x = FusedLayerNorm(normalized_shape=H, eps=cfg.layer_norm_eps,
                           dtype=self.dtype, name="ln_attn")(x + out)
        h = nn.Dense(cfg.intermediate_size, dtype=dense_dtype,
                     param_dtype=self.param_dtype, name="mlp_in")(x)
        # tanh GELU — google-research/bert's own gelu() is the tanh
        # formulation, and it fuses into the TPU GEMM epilogue where
        # exact erf costs VPU time (see models/transformer_lm.py)
        h = nn.gelu(jnp.asarray(h, jnp.float32), approximate=True)
        h = nn.Dense(H, dtype=dense_dtype, param_dtype=self.param_dtype,
                     name="mlp_out")(jnp.asarray(h, dense_dtype))
        if cfg.hidden_dropout_prob > 0.0:
            h = nn.Dropout(rate=cfg.hidden_dropout_prob,
                           deterministic=not train)(h)
        return FusedLayerNorm(normalized_shape=H, eps=cfg.layer_norm_eps,
                              dtype=self.dtype, name="ln_mlp")(x + h)


class BertModel(nn.Module):
    """Embeddings + encoder + pooler.

    ``__call__(input_ids, token_type_ids, attention_mask, train) ->
    (sequence_output[B,S,H], pooled_output[B,H])``.
    """

    config: BertConfig
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    # activation checkpointing per encoder layer (jax.checkpoint; the
    # DeepLearningExamples recipe's checkpoint_activations flag)
    remat: bool = False
    # optional externally-owned word embedding (weight tying with the MLM
    # decoder: BertForPreTraining constructs it and shares the instance)
    embed: Optional[nn.Module] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 *, train: bool = True):
        cfg = self.config
        B, S = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        wte = self.embed if self.embed is not None else nn.Embed(
            cfg.vocab_size, cfg.hidden_size, param_dtype=self.param_dtype,
            name="word_embeddings")
        tte = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                       param_dtype=self.param_dtype,
                       name="token_type_embeddings")
        wpe = self.param("position_embeddings",
                         nn.initializers.normal(stddev=0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         self.param_dtype)
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        x = wte(input_ids) + tte(token_type_ids) + wpe[:S][None]
        x = FusedLayerNorm(normalized_shape=cfg.hidden_size,
                           eps=cfg.layer_norm_eps, name="embed_ln")(x)
        x = jnp.asarray(x, dense_dtype)
        if cfg.hidden_dropout_prob > 0.0:
            x = nn.Dropout(rate=cfg.hidden_dropout_prob,
                           deterministic=not train)(x)
        layer_cls = BertLayer
        if self.remat:
            layer_cls = nn.remat(BertLayer, static_argnums=(3,))
        for i in range(cfg.num_hidden_layers):
            x = layer_cls(cfg, self.dtype, self.param_dtype,
                          name=f"layer_{i}")(x, attention_mask, train)
        pooled = nn.Dense(cfg.hidden_size, dtype=dense_dtype,
                          param_dtype=self.param_dtype, name="pooler")(
                              x[:, 0])
        pooled = jnp.tanh(pooled)
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM + NSP heads over BertModel (DeepLearningExamples formulation).

    ``__call__`` returns ``(mlm_logits[B, P, vocab], nsp_logits[B, 2])`` where
    P = ``masked_lm_positions.shape[1]`` — MLM logits are computed only at the
    masked positions (gather before the vocab GEMM: the standard trick that
    turns a [B,S,vocab] matmul into [B,P,vocab], ~15x smaller for BERT's 15%
    masking — essential on HBM).
    """

    config: BertConfig
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, input_ids, token_type_ids, attention_mask,
                 masked_lm_positions, *, train: bool = True):
        cfg = self.config
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        # word embedding owned here so the MLM decoder can tie to it (flax
        # module sharing: the instance is a child of this module; BertModel
        # calls it by reference)
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       param_dtype=self.param_dtype, name="word_embeddings")
        bert = BertModel(cfg, self.dtype, self.param_dtype, embed=wte,
                         name="bert")
        seq, pooled = bert(input_ids, token_type_ids, attention_mask,
                           train=train)
        B, S, H = seq.shape
        # gather masked positions before the vocab GEMM: [B, P, H]
        gathered = jnp.take_along_axis(
            seq, masked_lm_positions[..., None].astype(jnp.int32), axis=1)
        h = nn.Dense(H, dtype=dense_dtype, param_dtype=self.param_dtype,
                     name="mlm_transform")(gathered)
        h = nn.gelu(jnp.asarray(h, jnp.float32), approximate=True)
        h = FusedLayerNorm(normalized_shape=H, eps=cfg.layer_norm_eps,
                           name="mlm_ln")(h)
        # tied decoder: h @ embedding.T + bias, logits fp32 (Embed.attend is
        # flax's shared-weight tied-decoder path)
        mlm_logits = wte.attend(jnp.asarray(h, jnp.float32))
        mlm_logits = jnp.asarray(mlm_logits, jnp.float32)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
        mlm_logits = mlm_logits + mlm_bias
        nsp_logits = nn.Dense(2, dtype=jnp.float32,
                              param_dtype=self.param_dtype,
                              name="nsp")(jnp.asarray(pooled, jnp.float32))
        return mlm_logits, nsp_logits


def create_bert(size: str = "base", **overrides) -> BertConfig:
    sizes = {
        "tiny": dict(hidden_size=128, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=512),
        "base": dict(hidden_size=768, num_hidden_layers=12,
                     num_attention_heads=12, intermediate_size=3072),
        "large": dict(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096),
    }
    if size not in sizes:
        raise ValueError(f"unknown bert size {size!r}")
    kw = dict(sizes[size])
    kw.update(overrides)
    return BertConfig(**kw)
