"""Model zoo for the framework's recipes and benchmarks.

The reference has no model zoo of its own (it borrows torchvision resnets in
examples/imagenet/main_amp.py and BERT from NVIDIA DeepLearningExamples); a
standalone TPU framework must ship the models its recipes run, so they live
here.
"""

from .resnet import (  # noqa: F401
    BasicBlock, Bottleneck, ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
    ResNet152, create_model)

__all__ = [
    "BasicBlock", "Bottleneck", "ResNet", "ResNet18", "ResNet34", "ResNet50",
    "ResNet101", "ResNet152", "create_model",
]
