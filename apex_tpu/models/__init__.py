"""Model zoo for the framework's recipes and benchmarks.

The reference has no model zoo of its own (it borrows torchvision resnets in
examples/imagenet/main_amp.py and BERT from NVIDIA DeepLearningExamples); a
standalone TPU framework must ship the models its recipes run, so they live
here.
"""

from .bert import (  # noqa: F401
    BertConfig, BertForPreTraining, BertModel, create_bert)
from .resnet import (  # noqa: F401
    BasicBlock, Bottleneck, ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
    ResNet152, create_model)
from .transformer_lm import (  # noqa: F401
    TransformerBlock, TransformerLM, create_lm)

__all__ = [
    "BasicBlock", "Bottleneck", "ResNet", "ResNet18", "ResNet34", "ResNet50",
    "ResNet101", "ResNet152", "create_model",
    "TransformerLM", "TransformerBlock", "create_lm",
    "BertConfig", "BertModel", "BertForPreTraining", "create_bert",
]
