"""GPT-style causal transformer LM — the framework's config-3 workload.

The reference has no LM of its own; its transformer pieces (FusedLayerNorm,
fused softmax/xentropy kernels, FusedAdam) are exercised by external Megatron
recipes (BASELINE.json config 3: "FusedLayerNorm + FusedAdam transformer LM
(WikiText-2)"). This model is the standalone equivalent, assembled entirely
from the framework's own fused tiers:

- pre-LN blocks with :class:`apex_tpu.normalization.FusedLayerNorm`
- attention via :func:`apex_tpu.kernels.flash_attention.flash_attention`
  (Pallas, causal tile-skip — replaces N8/N11's fused softmax+MHA kernels)
- MLP via :func:`apex_tpu.fused_dense.fused_dense_gelu_dense_function`'s
  fp32-epilogue GELU semantics
- LM loss via :mod:`apex_tpu.kernels.xentropy` in the recipes.

TPU-first choices: bf16 compute with fp32 params (amp O2 shape), weights kept
as flax Dense kernels (MXU-layout friendly), embedding output scaled and tied
to the LM head (standard GPT weight tying — one less HBM-resident vocab
matrix).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention
from apex_tpu.normalization import FusedLayerNorm

__all__ = ["TransformerLM", "TransformerBlock", "create_lm"]


class SelfAttention(nn.Module):
    hidden: int
    num_heads: int
    dropout: float = 0.0
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        # dtype=None → O1 engine: GEMMs are FP16_FUNCS 'linear'
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        B, S, H = x.shape
        d = self.hidden // self.num_heads
        qkv = nn.Dense(3 * self.hidden, dtype=dense_dtype,
                       param_dtype=self.param_dtype, name="qkv")(x)
        qkv = qkv.reshape(B, S, 3, self.num_heads, d)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        out = flash_attention(q, k, v, causal=True)  # [B, h, S, d]
        out = jnp.moveaxis(out, 1, 2).reshape(B, S, self.hidden)
        out = nn.Dense(self.hidden, dtype=dense_dtype,
                       param_dtype=self.param_dtype, name="proj")(out)
        if self.dropout > 0.0:
            out = nn.Dropout(rate=self.dropout, deterministic=not train)(out)
        return out


class TransformerBlock(nn.Module):
    """Pre-LN block: x + attn(LN(x)); x + mlp(LN(x))."""

    hidden: int
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        # FusedLayerNorm resolves 'layer_norm' (FP32) itself from the raw
        # self.dtype; the Dense sites resolve 'linear' (FP16) here
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        h = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_attn")(x)
        x = x + SelfAttention(self.hidden, self.num_heads, self.dropout,
                              self.dtype, self.param_dtype,
                              name="attn")(h, train=train)
        h = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_mlp")(x)
        inner = self.mlp_ratio * self.hidden
        h = nn.Dense(inner, dtype=dense_dtype, param_dtype=self.param_dtype,
                     name="mlp_in")(h)
        # tanh-approximation GELU (GPT-2's own formulation) on the fp32
        # accumulator. tanh fuses into the GEMM epilogue on TPU; exact
        # erf priced at +250 us per MLP f+b at the gpt2 shape on v5e
        # (the VPU erf is NOT epilogue-fusable). The apex-parity
        # fused_dense API keeps exact erf; the models use the variant
        # their original papers trained with.
        h = nn.gelu(jnp.asarray(h, jnp.float32), approximate=True)
        h = nn.Dense(self.hidden, dtype=dense_dtype,
                     param_dtype=self.param_dtype,
                     name="mlp_out")(jnp.asarray(h, dense_dtype))
        if self.dropout > 0.0:
            h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM: tied-embedding GPT with pre-LN blocks + final FusedLayerNorm.

    ``__call__(tokens[B, S], train) -> logits[B, S, vocab]`` (logits fp32 —
    loss math never runs in half, matching amp's FP32_FUNCS policy for
    softmax/loss: apex/amp/lists/functional_overrides.py).
    """

    vocab_size: int
    hidden: int = 512
    num_layers: int = 6
    num_heads: int = 8
    max_seq_len: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.0
    # activation checkpointing per block (the reference gets this from
    # apex/transformer/tensor_parallel/random.py — checkpoint; on TPU it is
    # jax.checkpoint trading recompute for HBM, the standard long-context
    # memory lever)
    remat: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, *, train: bool = True,
                 features_only: bool = False):
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        B, S = tokens.shape
        embed = nn.Embed(self.vocab_size, self.hidden,
                         param_dtype=self.param_dtype, name="wte")
        pos = self.param("wpe", nn.initializers.normal(stddev=0.02),
                         (self.max_seq_len, self.hidden), self.param_dtype)
        x = jnp.asarray(embed(tokens) + pos[:S][None], dense_dtype)
        if self.dropout > 0.0:
            x = nn.Dropout(rate=self.dropout, deterministic=not train)(x)
        block_cls = TransformerBlock
        if self.remat:
            block_cls = nn.remat(TransformerBlock, static_argnums=(2,))
        for i in range(self.num_layers):
            x = block_cls(self.hidden, self.num_heads, self.mlp_ratio,
                          self.dropout, self.dtype, self.param_dtype,
                          name=f"block_{i}")(x, train)
        x = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_f")(x)
        if features_only:
            # pre-head hidden states [B, S, H] for callers fusing the
            # tied head into the loss (kernels/lm_head_loss.py — the
            # head weight is params["wte"]["embedding"], vocab-major)
            return x
        # tied LM head; logits in fp32
        logits = jnp.dot(jnp.asarray(x, jnp.float32),
                         jnp.asarray(embed.embedding, jnp.float32).T)
        return logits


_LM_SIZES = {
    # (hidden, layers, heads) — "small" is the WikiText-2 recipe default
    "tiny": (128, 2, 4),
    "small": (512, 6, 8),
    "medium": (1024, 12, 16),
    "gpt2": (768, 12, 12),
}


def create_lm(size: str = "small", vocab_size: int = 32768,
              max_seq_len: int = 1024, dropout: float = 0.0,
              remat: bool = False, dtype: Optional[Any] = None,
              param_dtype: Any = jnp.float32) -> TransformerLM:
    if size not in _LM_SIZES:
        raise ValueError(f"unknown LM size {size!r}; one of {sorted(_LM_SIZES)}")
    hidden, layers, heads = _LM_SIZES[size]
    return TransformerLM(vocab_size=vocab_size, hidden=hidden,
                         num_layers=layers, num_heads=heads,
                         max_seq_len=max_seq_len, dropout=dropout,
                         remat=remat, dtype=dtype, param_dtype=param_dtype)
