"""GPT-style causal transformer LM — the framework's config-3 workload.

The reference has no LM of its own; its transformer pieces (FusedLayerNorm,
fused softmax/xentropy kernels, FusedAdam) are exercised by external Megatron
recipes (BASELINE.json config 3: "FusedLayerNorm + FusedAdam transformer LM
(WikiText-2)"). This model is the standalone equivalent, assembled entirely
from the framework's own fused tiers:

- pre-LN blocks with :class:`apex_tpu.normalization.FusedLayerNorm`
- attention via :func:`apex_tpu.kernels.flash_attention.flash_attention`
  (Pallas, causal tile-skip — replaces N8/N11's fused softmax+MHA kernels)
- MLP via :func:`apex_tpu.fused_dense.fused_dense_gelu_dense_function`'s
  fp32-epilogue GELU semantics
- LM loss via :mod:`apex_tpu.kernels.xentropy` in the recipes.

TPU-first choices: bf16 compute with fp32 params (amp O2 shape), weights kept
as flax Dense kernels (MXU-layout friendly), embedding output scaled and tied
to the LM head (standard GPT weight tying — one less HBM-resident vocab
matrix).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.kernels.flash_attention import flash_attention
from apex_tpu.normalization import FusedLayerNorm

__all__ = ["TransformerLM", "TransformerBlock", "create_lm"]


def _lora_term(x, pair, alpha, adapter_ids, out_dtype):
    """The gathered multi-tenant LoRA epilogue term for one GEMM site:
    ``(x @ A[ids]) @ B[ids] * alpha[ids]`` — the serving engine's
    stacked-adapter residual (:mod:`apex_tpu.serving.lora`).

    ``pair`` is the site's arena slice ``(A [rows, in, rank],
    B [rows, rank, out])`` and ``adapter_ids [B]`` names each batch
    row's arena row — a TRACED operand, so heterogeneous adapters ride
    one compiled program and the adapter id is data, never a trace
    key. Math runs in fp32 (the epilogue-accumulator convention every
    fused tier here shares) and the result is cast to the base GEMM's
    output dtype. Arena row 0 is all-zero with ``alpha[0] == 0``: a
    base (adapter-free) row's term is exactly ``+0.0`` per element,
    which fp32/bf16 addition leaves value-identical — the
    ``fault_bias`` pin, reapplied."""
    a, b = pair
    ids = jnp.asarray(adapter_ids, jnp.int32)
    h = jnp.einsum("bsh,bhr->bsr", jnp.asarray(x, jnp.float32),
                   jnp.asarray(a, jnp.float32)[ids])
    t = jnp.einsum("bsr,bro->bso", h,
                   jnp.asarray(b, jnp.float32)[ids])
    t = t * jnp.asarray(alpha, jnp.float32)[ids][:, None, None]
    return jnp.asarray(t, out_dtype)


def _dense_factory(weight_quant: bool, dense_dtype, param_dtype):
    """The one Dense-site constructor both block modules share: plain
    ``nn.Dense`` on the default path (kept verbatim — the bitwise
    baseline), ``QuantDense`` (int8 kernel, per-output-channel scale
    in the epilogue) when the engine enabled weight quantization —
    same param paths either way."""
    if weight_quant:
        from apex_tpu.serving.weight_quant import QuantDense

        def _dense(features, name):
            return QuantDense(features, dtype=dense_dtype,
                              param_dtype=param_dtype, name=name)
    else:
        def _dense(features, name):
            return nn.Dense(features, dtype=dense_dtype,
                            param_dtype=param_dtype, name=name)
    return _dense


class SelfAttention(nn.Module):
    """Causal MHA with four modes sharing one set of weights:

    - **train/eval** (default): full-sequence flash attention.
    - **prefill** (``return_kv=True``): same forward, additionally
      returning this layer's ``(k, v)`` ``[B, h, S, d]`` for the serving
      engine to write into its KV cache.
    - **decode** (``cache=(k_cache, v_cache)`` + ``positions``, S == 1):
      the token's K/V is scattered into the cache at ``positions[b]``
      and attention runs against the cached prefix via
      :func:`apex_tpu.kernels.decode_attention.decode_attention`
      (length-masked, fp32 accumulation), returning
      ``(out, (k_cache', v_cache'))``.
    - **chunked prefill** (``cache`` + ``positions``, S > 1): S
      consecutive prompt tokens starting at cache position
      ``positions[b]`` — their K/V is written at ``[positions[b],
      positions[b] + S)`` and each attends the cached prefix up to and
      including itself (write-then-attend, shifted-causal) via
      :func:`apex_tpu.kernels.prefill_attention.prefill_attention`.
    - **paged decode / chunked prefill** (``cache=(k_pool, v_pool,
      page_table)``): same two modes over the serving engine's paged
      pool — K/V scatter by page id (``page_table[b, pos // page_len]``
      at in-page offset ``pos % page_len``) and attention gathers
      through the table via the ``paged_*`` kernel variants. The
      returned aux is the UPDATED POOL pair (pages are shared across
      rows), not per-row caches; chunk writes must be page-aligned and
      whole-page (the engine enforces ``chunk_len % page_len == 0``).
    - **unaligned append** (``unaligned_append=True``, paged ``S > 1``):
      the speculative-verify write shape — a SMALL block of S draft
      tokens landing at an arbitrary (non-page-aligned) cache offset
      mid-generation, where the whole-page chunk write cannot apply.
      Each of the S positions scatters individually by page id (the
      decode write, unrolled over the static S), then the same
      shifted-causal paged prefill attention runs. The pages written
      are always the slot's own: generation positions sit past any
      copy-on-write share, so unaligned writes can never touch a
      shared page. Contiguous caches ignore the flag (their
      ``dynamic_update_slice`` chunk write already takes any offset).

    ``inference_dtype`` is the decode path's storage/compute dtype: when
    set, Q/K/V leave the qkv GEMM in that dtype (normally the amp half —
    pure-bf16 decode needs no fp32 master weights anywhere); when None
    the training-policy ``dense_dtype`` governs, as before.

    - **quantized cache** (``kv_scales=(k_scale, v_scale)``, each
      ``[heads]`` fp32 for this layer — the serving engine's
      ``kv_quant`` int8 storage tier): every cache WRITE above
      quantizes the fresh K/V symmetrically per head
      (:mod:`apex_tpu.serving.kv_quant`) before storing, and every
      attention READ passes the scales into the kernels, which
      dequantize in-kernel (int8 block load → scale multiply → the
      unchanged online-softmax fp32 math). ``kv_scales=None`` (the
      default) leaves every mode byte-identical to the bf16 tier.

    **Tensor parallelism** (``tp_axis``/``tp_size``, set by
    ``serving.Engine(mesh=...)`` and meaningful only inside a
    ``shard_map`` over that axis): the module becomes ONE SHARD of a
    Megatron-style split — the qkv projection is column-parallel over
    ``num_heads // tp_size`` local heads, attention (cached or not)
    runs entirely over the local heads (the KV cache/pool arrives
    heads-sharded, so nothing here crosses ICI), and the row-parallel
    output projection's partial sum is ``psum``-reduced over
    ``tp_axis``. The projection BIAS is added per shard inside the
    Dense and the param sharder value-scales it by ``1/tp_size``
    (:mod:`apex_tpu.serving.sharding`), so the psum restores it exactly
    once. ``tp_size=1`` (the default) leaves every shape and op
    untouched.

    **Quantized weights** (``weight_quant=True``, set by
    ``serving.Engine(weight_quant=...)``): the qkv and proj GEMMs run
    over int8 kernels through
    :class:`~apex_tpu.serving.weight_quant.QuantDense` — the
    per-output-channel fp32 scale multiplies the accumulator in the
    epilogue, so dequantized weights never materialise. The default
    (False) keeps ``nn.Dense`` on the trace path verbatim.
    """

    hidden: int
    num_heads: int
    dropout: float = 0.0
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    inference_dtype: Optional[Any] = None
    tp_axis: Optional[str] = None
    tp_size: int = 1
    weight_quant: bool = False

    @nn.compact
    def __call__(self, x, train: bool, cache=None, positions=None,
                 return_kv: bool = False, unaligned_append: bool = False,
                 kv_scales=None, lora=None, adapter_ids=None):
        # dtype=None → O1 engine: GEMMs are FP16_FUNCS 'linear'
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        if self.inference_dtype is not None and not train:
            dense_dtype = self.inference_dtype
        _dense = _dense_factory(self.weight_quant, dense_dtype,
                                self.param_dtype)
        B, S, H = x.shape
        d = self.hidden // self.num_heads
        # tensor-parallel shard: this module computes heads // tp local
        # heads over the full (replicated) residual stream; the param
        # sharder hands it the matching qkv/proj kernel slices
        heads = self.num_heads // self.tp_size
        qkv = _dense(3 * heads * d, "qkv")(x)
        if lora is not None:
            # column-parallel site: x and A replicated, B output-split
            # (the arena stores qkv's B head-group-permuted, so this
            # shard's slice lands on its own columns)
            qkv = qkv + _lora_term(x, lora["qkv"], lora["alpha"],
                                   adapter_ids, qkv.dtype)
        # one transpose to [3, B, h, S, d], then three views — no
        # throwaway generator re-indexing qkv[:, :, i] three times
        qkv = qkv.reshape(B, S, 3, heads, d).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]             # [B, h, S, d]
        # quantized-cache tier: per-head dequant scales for this layer
        # ([heads] fp32 each; None = the byte-identical bf16 tier).
        # _store is the ONE write-site cast every cache mode below
        # shares: a plain dtype cast on the bf16 tier, symmetric int8
        # quantization on the quant tier (heads at `axis`).
        ks = vs = None
        if kv_scales is not None:
            ks, vs = kv_scales

        def _store(new, ref_dtype, scale, axis):
            if scale is None:
                return jnp.asarray(new, ref_dtype)
            from apex_tpu.serving.kv_quant import quantize
            return quantize(new, scale, axis=axis)

        if cache is not None:
            paged = len(cache) == 3
            if paged:
                # paged layout: (k_pool, v_pool, page_table) — pool
                # [num_pages, h, page_len, d] shared across rows, table
                # [B, max_pages] int32 mapping logical blocks to pages.
                # Writes scatter by page id; attention gathers through
                # the table (the serving engine's block-table refactor).
                k_cache, v_cache, page_table = cache
                page_len = k_cache.shape[2]
                L = page_table.shape[1] * page_len
            else:
                k_cache, v_cache = cache             # [B, h, L, d]
                L = k_cache.shape[2]
            # clip is a traced-value safety net only: an out-of-range
            # offset would RELOCATE the S-wide write over earlier cache
            # rows, so callers must bound positions host-side (the
            # serving engine validates offset + chunk_len <= max_len)
            pos = jnp.clip(jnp.asarray(positions, jnp.int32), 0, L - S)
            if S == 1:
                from apex_tpu.kernels.decode_attention import (
                    decode_attention, paged_decode_attention)
                if paged:
                    # the write page: logical block pos // page_len of
                    # each row. Inactive slots' tables point at the
                    # sentinel page, so their (discarded) write can
                    # never corrupt a live row; a live slot's write
                    # page is uniquely owned (shared pages are always
                    # full — copy-on-write by construction).
                    page_ids = jnp.take_along_axis(
                        page_table, (pos // page_len)[:, None],
                        axis=1)[:, 0]
                    off = pos % page_len
                    k_cache = k_cache.at[page_ids, :, off].set(
                        _store(k[:, :, 0], k_cache.dtype, ks, 1))
                    v_cache = v_cache.at[page_ids, :, off].set(
                        _store(v[:, :, 0], v_cache.dtype, vs, 1))
                    ctx = paged_decode_attention(
                        q[:, :, 0], k_cache, v_cache, page_table,
                        pos + 1, k_scale=ks, v_scale=vs)
                else:
                    bidx = jnp.arange(B)
                    k_cache = k_cache.at[bidx, :, pos].set(
                        _store(k[:, :, 0], k_cache.dtype, ks, 1))
                    v_cache = v_cache.at[bidx, :, pos].set(
                        _store(v[:, :, 0], v_cache.dtype, vs, 1))
                    # write-then-attend: the token sees its own K/V
                    ctx = decode_attention(q[:, :, 0], k_cache, v_cache,
                                           pos + 1, k_scale=ks,
                                           v_scale=vs)
            else:
                from apex_tpu.kernels.prefill_attention import (
                    prefill_attention, paged_prefill_attention)
                if paged and unaligned_append:
                    # speculative verify: S is small (draft_len + 1)
                    # and the offset is an arbitrary mid-generation
                    # position — scatter each position individually
                    # (the decode write, unrolled over the static S)
                    for s in range(S):
                        p = pos + s                             # [B]
                        page_ids = jnp.take_along_axis(
                            page_table, (p // page_len)[:, None],
                            axis=1)[:, 0]
                        off = p % page_len
                        k_cache = k_cache.at[page_ids, :, off].set(
                            _store(k[:, :, s], k_cache.dtype, ks, 1))
                        v_cache = v_cache.at[page_ids, :, off].set(
                            _store(v[:, :, s], v_cache.dtype, vs, 1))
                    ctx = paged_prefill_attention(q, k_cache, v_cache,
                                                  page_table, pos,
                                                  k_scale=ks,
                                                  v_scale=vs)
                elif paged:
                    # chunk writes must cover whole pages: the serving
                    # engine pins chunk_len % page_len == 0 and page-
                    # aligned offsets, so the chunk's S positions are
                    # exactly S // page_len freshly-allocated pages
                    if S % page_len:
                        raise ValueError(
                            f"paged chunk prefill needs S ({S}) to be "
                            f"a multiple of page_len ({page_len})")
                    npg = S // page_len
                    idx = (pos // page_len)[:, None] + jnp.arange(
                        npg, dtype=jnp.int32)[None, :]
                    chunk_pages = jnp.take_along_axis(page_table, idx,
                                                      axis=1)  # [B, npg]
                    def _pages(x, dtype, scale):
                        return _store(x, dtype, scale, 1).reshape(
                            B, heads, npg, page_len, d
                        ).transpose(0, 2, 1, 3, 4)   # [B, npg, h, pl, d]
                    k_cache = k_cache.at[chunk_pages].set(
                        _pages(k, k_cache.dtype, ks))
                    v_cache = v_cache.at[chunk_pages].set(
                        _pages(v, v_cache.dtype, vs))
                    ctx = paged_prefill_attention(q, k_cache, v_cache,
                                                  page_table, pos,
                                                  k_scale=ks,
                                                  v_scale=vs)
                else:
                    # chunked prefill: S tokens land at [pos, pos + S)
                    # of each row's cache (vmapped per-row offsets)
                    def _write(row, new, p):
                        return jax.lax.dynamic_update_slice(row, new,
                                                            (0, p, 0))
                    k_cache = jax.vmap(_write)(
                        k_cache, _store(k, k_cache.dtype, ks, 1), pos)
                    v_cache = jax.vmap(_write)(
                        v_cache, _store(v, v_cache.dtype, vs, 1), pos)
                    ctx = prefill_attention(q, k_cache, v_cache, pos,
                                            k_scale=ks, v_scale=vs)
            out = jnp.moveaxis(ctx.reshape(B, heads, S, d),
                               1, 2).reshape(B, S, heads * d)
        else:
            if return_kv and ks is not None:
                # monolithic prefill on the quantized tier: attend (and
                # return) K/V through the storage grid — quantize then
                # dequantize with the per-head scales so this forward
                # sees exactly the values every later attend reads back
                # out of the int8 cache (chunked prefill writes codes
                # and attends them in-kernel; without this round-trip
                # the two ingest paths would attend different K/V and
                # store divergent codes for every layer past the
                # first). fp32 keeps the engine's storage quantize an
                # exact code recovery: round((c*s)/s) == c.
                from apex_tpu.serving.kv_quant import dequantize, quantize
                k = dequantize(quantize(k, ks, axis=1), ks, axis=1)
                v = dequantize(quantize(v, vs, axis=1), vs, axis=1)
                q = jnp.asarray(q, jnp.float32)
            out = flash_attention(q, k, v, causal=True)  # [B, h, S, d]
            out = jnp.moveaxis(out, 1, 2).reshape(B, S, heads * d)
        ctx_in = out
        out = _dense(self.hidden, "proj")(ctx_in)
        if lora is not None:
            # row-parallel site: A input-split to match the local
            # heads' context, B replicated — the term is a partial sum
            # the psum below restores, zero new collectives
            out = out + _lora_term(ctx_in, lora["proj"], lora["alpha"],
                                   adapter_ids, out.dtype)
        if self.tp_size > 1:
            # row-parallel reduce: each shard's proj saw only its heads'
            # context, so the outputs are partial sums; the Dense added
            # the 1/tp-scaled bias per shard (sharding.shard_params), so
            # this one psum yields x @ W + b exactly — the first of the
            # block's two canonical TP all-reduces
            out = jax.lax.psum(out, self.tp_axis)
        if self.dropout > 0.0:
            out = nn.Dropout(rate=self.dropout, deterministic=not train)(out)
        if cache is not None:
            return out, (k_cache, v_cache)
        if return_kv:
            return out, (k, v)
        return out


class TransformerBlock(nn.Module):
    """Pre-LN block: x + attn(LN(x)); x + mlp(LN(x)).

    ``cache``/``positions``/``return_kv`` thread straight through to
    :class:`SelfAttention` (see its docstring for the three modes); with
    either inference mode on, the block returns ``(x, aux)`` where aux is
    the updated layer cache (decode) or this layer's ``(k, v)``
    (prefill).
    """

    hidden: int
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    inference_dtype: Optional[Any] = None
    tp_axis: Optional[str] = None
    tp_size: int = 1
    weight_quant: bool = False

    @nn.compact
    def __call__(self, x, train: bool, cache=None, positions=None,
                 return_kv: bool = False, unaligned_append: bool = False,
                 kv_scales=None, lora=None, adapter_ids=None):
        # FusedLayerNorm resolves 'layer_norm' (FP32) itself from the raw
        # self.dtype; the Dense sites resolve 'linear' (FP16) here
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        if self.inference_dtype is not None and not train:
            dense_dtype = self.inference_dtype
        _dense = _dense_factory(self.weight_quant, dense_dtype,
                                self.param_dtype)
        h = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_attn")(x)
        aux = None
        attn_out = SelfAttention(self.hidden, self.num_heads, self.dropout,
                                 self.dtype, self.param_dtype,
                                 self.inference_dtype,
                                 self.tp_axis, self.tp_size,
                                 weight_quant=self.weight_quant,
                                 name="attn")(h, train=train, cache=cache,
                                              positions=positions,
                                              return_kv=return_kv,
                                              unaligned_append=
                                              unaligned_append,
                                              kv_scales=kv_scales,
                                              lora=lora,
                                              adapter_ids=adapter_ids)
        if cache is not None or return_kv:
            attn_out, aux = attn_out
        x = x + attn_out
        h = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_mlp")(x)
        # tensor-parallel shard: column-parallel up-projection (this
        # shard's inner/tp slice), row-parallel down-projection psummed
        # below — the MLP half of the Megatron split
        inner = self.mlp_ratio * self.hidden // self.tp_size
        mlp_in_x = h
        h = _dense(inner, "mlp_in")(mlp_in_x)
        if lora is not None:
            # column-parallel site: B output-split (contiguous — the
            # mlp_in kernel's own split), A replicated
            h = h + _lora_term(mlp_in_x, lora["mlp_in"], lora["alpha"],
                               adapter_ids, h.dtype)
        # tanh-approximation GELU (GPT-2's own formulation) on the fp32
        # accumulator. tanh fuses into the GEMM epilogue on TPU; exact
        # erf priced at +250 us per MLP f+b at the gpt2 shape on v5e
        # (the VPU erf is NOT epilogue-fusable). The apex-parity
        # fused_dense API keeps exact erf; the models use the variant
        # their original papers trained with.
        h = nn.gelu(jnp.asarray(h, jnp.float32), approximate=True)
        mlp_out_x = jnp.asarray(h, dense_dtype)
        h = _dense(self.hidden, "mlp_out")(mlp_out_x)
        if lora is not None:
            # row-parallel site: A input-split to match this shard's
            # inner slice, B replicated — psummed below
            h = h + _lora_term(mlp_out_x, lora["mlp_out"],
                               lora["alpha"], adapter_ids, h.dtype)
        if self.tp_size > 1:
            # row-parallel reduce (the block's second TP all-reduce);
            # mlp_out's bias is 1/tp-scaled per shard, restored here
            h = jax.lax.psum(h, self.tp_axis)
        if self.dropout > 0.0:
            h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        if aux is not None:
            return x + h, aux
        return x + h


class TransformerLM(nn.Module):
    """Causal LM: tied-embedding GPT with pre-LN blocks + final FusedLayerNorm.

    ``__call__(tokens[B, S], train) -> logits[B, S, vocab]`` (logits fp32 —
    loss math never runs in half, matching amp's FP32_FUNCS policy for
    softmax/loss: apex/amp/lists/functional_overrides.py).

    Inference modes (the ``apex_tpu.serving`` engine's compiled
    programs — see :class:`SelfAttention`):

    - **prefill**: ``__call__(tokens[B, S], train=False, return_kv=True)
      -> (logits, (k, v))`` with ``k``/``v`` stacked per layer
      ``[layers, B, h, S, d]`` — the engine writes them into its slot
      cache.
    - **decode**: ``__call__(tokens[B, 1], train=False,
      cache=(k, v), positions=lengths) -> (logits, (k', v'))`` — the
      single new token per batch row is embedded at ``positions[b]``,
      its K/V scattered into the cache, and attention runs length-masked
      against the cached prefix.
    - **chunked prefill**: same signature with ``tokens[B, C]`` (C > 1)
      — C consecutive prompt tokens per row, embedded at ``positions[b]
      + s``, K/V written to cache ``[positions[b], positions[b] + C)``,
      shifted-causal attention over the cached prefix (the engine's
      chunk-prefill program; one chunk per decode heartbeat).
    - **speculative verify**: chunked prefill with
      ``unaligned_append=True`` — a ``[B, K+1]`` draft block landing at
      an arbitrary mid-generation offset; paged caches switch to
      per-position scatters (see :class:`SelfAttention`), contiguous
      caches are offset-agnostic already.

    ``inference_dtype`` (normally the amp half dtype) pins the
    eval-mode GEMM/cache dtype independently of the training policy, so
    a pure-bf16 serving engine needs no fp32 master weights.
    """

    vocab_size: int
    hidden: int = 512
    num_layers: int = 6
    num_heads: int = 8
    max_seq_len: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.0
    # activation checkpointing per block (the reference gets this from
    # apex/transformer/tensor_parallel/random.py — checkpoint; on TPU it is
    # jax.checkpoint trading recompute for HBM, the standard long-context
    # memory lever)
    remat: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    inference_dtype: Optional[Any] = None
    # tensor parallelism (serving.Engine(mesh=...); meaningful only
    # inside a shard_map over tp_axis): every block becomes one
    # Megatron-style shard (local heads, split MLP, 2 psums/block) and
    # the tied LM head returns VOCAB-LOCAL logits — each shard matmuls
    # its vocab/tp slice of the replicated embedding; the caller (the
    # engine's compiled program) all-gathers only the sampled rows.
    tp_axis: Optional[str] = None
    tp_size: int = 1
    # quantized serving weights (serving.Engine(weight_quant=...); the
    # engine provides int8 kernels + per-output-channel fp32 scales in
    # the params tree): every block GEMM runs through QuantDense and
    # the tied embedding/head through QuantEmbed — dequant is the
    # epilogue scale multiply, never a materialised weight matrix.
    # Serving-only: int8 kernels cannot train.
    weight_quant: bool = False

    @nn.compact
    def __call__(self, tokens, *, train: bool = True,
                 features_only: bool = False, cache=None, positions=None,
                 return_kv: bool = False, unaligned_append: bool = False,
                 kv_scales=None, lora=None, adapter_ids=None):
        from apex_tpu.amp.autocast import resolve_dtype
        dense_dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        if self.inference_dtype is not None and not train:
            dense_dtype = self.inference_dtype
        if cache is not None and return_kv:
            raise ValueError("cache (decode) and return_kv (prefill) are "
                             "exclusive modes")
        if self.weight_quant and train:
            raise ValueError(
                "weight_quant is a serving-only mode: int8 kernels "
                "cannot train — keep the bf16/fp32 model for training "
                "and let serving.Engine(weight_quant=...) quantize")
        if self.tp_size > 1 and (self.num_heads % self.tp_size
                                 or self.vocab_size % self.tp_size):
            raise ValueError(
                f"tp_size={self.tp_size} must divide num_heads="
                f"{self.num_heads} and vocab_size={self.vocab_size}")
        B, S = tokens.shape
        if self.weight_quant:
            from apex_tpu.serving.weight_quant import QuantEmbed
            embed = QuantEmbed(self.vocab_size, self.hidden,
                               dtype=dense_dtype,
                               param_dtype=self.param_dtype, name="wte")
        else:
            embed = nn.Embed(self.vocab_size, self.hidden,
                             param_dtype=self.param_dtype, name="wte")
        pos = self.param("wpe", nn.initializers.normal(stddev=0.02),
                         (self.max_seq_len, self.hidden), self.param_dtype)
        if cache is not None:
            # decode/chunk: token s of row b lives at positions[b] + s
            ppos = jnp.clip(jnp.asarray(positions, jnp.int32)[:, None]
                            + jnp.arange(S, dtype=jnp.int32)[None, :],
                            0, self.max_seq_len - 1)          # [B, S]
            x = jnp.asarray(embed(tokens) + pos[ppos], dense_dtype)
        else:
            x = jnp.asarray(embed(tokens) + pos[:S][None], dense_dtype)
        if self.dropout > 0.0:
            x = nn.Dropout(rate=self.dropout, deterministic=not train)(x)
        block_cls = TransformerBlock
        if self.remat and cache is None and not return_kv:
            block_cls = nn.remat(TransformerBlock, static_argnums=(2,))
        kv_out = ([], [])
        for i in range(self.num_layers):
            block = block_cls(self.hidden, self.num_heads, self.mlp_ratio,
                              self.dropout, self.dtype, self.param_dtype,
                              self.inference_dtype, self.tp_axis,
                              self.tp_size,
                              weight_quant=self.weight_quant,
                              name=f"block_{i}")
            # quantized cache: this layer's per-head scale pair
            # ([layers, heads] engine arrays sliced at i) — threaded
            # into BOTH inference modes, so monolithic (return_kv)
            # prefill attends the same storage grid the cache modes
            # write and read
            layer_scales = None if kv_scales is None else \
                (kv_scales[0][i], kv_scales[1][i])
            # multi-tenant LoRA: this layer's slice of the stacked
            # adapter arena ([layers, rows, ...] engine arrays sliced
            # at i; alpha is layer-free) — serving modes only, like
            # kv_scales
            layer_lora = None if lora is None else {
                "qkv": (lora["qkv_a"][i], lora["qkv_b"][i]),
                "proj": (lora["proj_a"][i], lora["proj_b"][i]),
                "mlp_in": (lora["mlp_in_a"][i], lora["mlp_in_b"][i]),
                "mlp_out": (lora["mlp_out_a"][i],
                            lora["mlp_out_b"][i]),
                "alpha": lora["alpha"],
            }
            if cache is not None:
                # 2-tuple: per-slot rows [layers, B, h, L, d]; 3-tuple:
                # paged pools [layers, P, h, page_len, d] + one shared
                # [B, max_pages] page table (see SelfAttention)
                layer_cache = (cache[0][i], cache[1][i])
                if len(cache) == 3:
                    layer_cache = layer_cache + (cache[2],)
                x, (lk, lv) = block(x, train, cache=layer_cache,
                                    positions=positions,
                                    unaligned_append=unaligned_append,
                                    kv_scales=layer_scales,
                                    lora=layer_lora,
                                    adapter_ids=adapter_ids)
                kv_out[0].append(lk)
                kv_out[1].append(lv)
            elif return_kv:
                x, (lk, lv) = block(x, train, return_kv=True,
                                    kv_scales=layer_scales,
                                    lora=layer_lora,
                                    adapter_ids=adapter_ids)
                kv_out[0].append(lk)
                kv_out[1].append(lv)
            else:
                x = block(x, train)
        x = FusedLayerNorm(normalized_shape=self.hidden, dtype=self.dtype,
                           name="ln_f")(x)
        if features_only:
            # pre-head hidden states [B, S, H] for callers fusing the
            # tied head into the loss (kernels/lm_head_loss.py — the
            # head weight is params["wte"]["embedding"], vocab-major)
            return x
        # tied LM head; logits in fp32. Quantized weights: the head's
        # output channels ARE the vocab rows, so the per-row embedding
        # scales multiply the logits accumulator in the epilogue —
        # sliced by the SAME dynamic_slice as the vocab-parallel matrix
        if self.tp_size > 1:
            # vocab-parallel head: each shard matmuls its vocab/tp slice
            # of the replicated embedding (cutting the largest GEMM in a
            # decode step by tp) and returns VOCAB-LOCAL logits — the
            # engine all-gathers only the rows it actually samples
            vl = self.vocab_size // self.tp_size
            idx = jax.lax.axis_index(self.tp_axis)
            head = jax.lax.dynamic_slice_in_dim(
                jnp.asarray(embed.embedding, jnp.float32), idx * vl, vl,
                axis=0)                                     # [V/tp, H]
            logits = jnp.dot(jnp.asarray(x, jnp.float32), head.T)
            if self.weight_quant:
                logits = logits * jax.lax.dynamic_slice_in_dim(
                    embed.embedding_scale, idx * vl, vl, axis=0)
        else:
            logits = jnp.dot(jnp.asarray(x, jnp.float32),
                             jnp.asarray(embed.embedding, jnp.float32).T)
            if self.weight_quant:
                logits = logits * embed.embedding_scale
        if cache is not None or return_kv:
            return logits, (jnp.stack(kv_out[0]), jnp.stack(kv_out[1]))
        return logits


_LM_SIZES = {
    # (hidden, layers, heads) — "small" is the WikiText-2 recipe default
    "tiny": (128, 2, 4),
    "small": (512, 6, 8),
    "medium": (1024, 12, 16),
    "gpt2": (768, 12, 12),
}


def create_lm(size: str = "small", vocab_size: int = 32768,
              max_seq_len: int = 1024, dropout: float = 0.0,
              remat: bool = False, dtype: Optional[Any] = None,
              param_dtype: Any = jnp.float32,
              inference_dtype: Optional[Any] = None) -> TransformerLM:
    if size not in _LM_SIZES:
        raise ValueError(f"unknown LM size {size!r}; one of {sorted(_LM_SIZES)}")
    hidden, layers, heads = _LM_SIZES[size]
    return TransformerLM(vocab_size=vocab_size, hidden=hidden,
                         num_layers=layers, num_heads=heads,
                         max_seq_len=max_seq_len, dropout=dropout,
                         remat=remat, dtype=dtype, param_dtype=param_dtype,
                         inference_dtype=inference_dtype)
