"""Data-parallel gradient averaging — apex DDP's semantics on XLA collectives.

Reference: apex/parallel/distributed.py — class DistributedDataParallel and
class Reducer. Apex registers per-parameter grad hooks, coalesces grads into
flat dtype-segregated buckets (split_half_float_double, ``message_size``
elements each), and launches async NCCL allreduces on side streams overlapped
with the rest of backward; options: gradient averaging (÷world),
``gradient_predivide_factor``, ``delay_allreduce``, ``retain_allreduce_buffers``
(flat fp16 grads for amp O2), param broadcast from rank 0 at init.

Why the TPU version is this small: every mechanism above exists to overlap
communication with eager-mode autograd. Under jit, gradients are values in one
traced program — a single ``psum`` per pytree is bucketed and scheduled by
the compiler. That claim is certified, not assumed (bench_schedule.py +
tests/tpu/test_schedule_overlap.py read the scheduled HLO): XLA's combiner
merges every per-leaf psum into ONE all-reduce over the whole tuple — the
flat bucket apex builds by hand — placed after the last grad producer; on
the current toolchain the all-reduce op itself is synchronous in HLO (the
honest reading in BASELINE.md's overlap table). What survives here is the
*semantics*: mean-averaging, predivide factor, any-rank-overflow ⇒
all-rank skip (handled in amp.make_train_step), and replicated init.

Allreduce FREQUENCY is the other lever apex's recipes pull
(gradient_accumulation_steps + ``scale_loss(delay_unscale=True)``: N
backwards, one reduction): ``amp.make_train_step(accum_steps=N)`` scans
N microbatches inside the jitted step and runs this whole-tree reduction
ONCE per optimizer window — N× fewer comm bytes per optimizer step,
certified from scheduled HLO by bench_schedule.py's ddp_accum leg and at
trace time by the ``comm.ddp.allreduce.calls`` counter (docs/amp.md
§Microbatch gradient accumulation).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def average_gradients(grads, axis_name: str = "data",
                      gradient_predivide_factor: float = 1.0,
                      gradient_average: bool = True):
    """One-shot DDP gradient reduction, usable inside shard_map/pmap.

    Matches apex's arithmetic (distributed.py — allreduce_maybe_retain →
    allreduce_bucket): grads are divided by ``predivide`` before the sum and
    by ``world/predivide`` after, so the result is the mean; with
    ``gradient_average=False`` it is the raw sum (apex's
    gradient_average=False path).

    Comm health: the whole-pytree reduction is accounted to the
    ``comm.ddp.allreduce.*`` telemetry counters (bytes/calls/leaves, at
    trace time — apex's ``allreduce_bucket`` size accounting; the leaves
    counter is the bucketing input XLA's combiner coalesces into one op,
    bench_schedule.py ddp).
    """
    from apex_tpu import telemetry

    telemetry.account_collective("ddp.allreduce", grads)
    world = jax.lax.psum(1, axis_name)
    pre = gradient_predivide_factor

    def reduce_one(g):
        g = jax.lax.psum(g / pre if pre != 1.0 else g, axis_name)
        if gradient_average:
            post = world / pre
            g = g / post
        return g

    return jax.tree_util.tree_map(reduce_one, grads)


class Reducer:
    """apex/parallel/distributed.py — class Reducer: the manual variant.

    Apex's Reducer just allreduce-averages whatever you hand it when you call
    ``.reduce()``. Identical here, bound to a mesh axis.
    """

    def __init__(self, axis_name: str = "data"):
        self.axis_name = axis_name

    def reduce(self, grads):
        return average_gradients(grads, self.axis_name)


class DistributedDataParallel:
    """API-parity wrapper for apex.parallel.DistributedDataParallel.

    Wraps a functional model ``apply_fn`` (or any callable); the forward is
    untouched, and :meth:`reduce_gradients` performs the bucketed-allreduce
    equivalent. The constructor accepts apex's knobs; the ones that are
    overlap-mechanics under eager autograd (``message_size``,
    ``delay_allreduce``, ``allreduce_communicators``, ...) are accepted and
    ignored because XLA owns scheduling — documented here rather than
    silently dropped.

    Preferred integration: ``amp.make_train_step(grad_average_axis="data",
    gradient_predivide_factor=...)``, which inlines this reduction in the
    jitted step. This class exists for recipe parity
    (examples/imagenet/main_amp.py wraps the model then trains manually).
    """

    def __init__(self, module: Optional[Callable] = None,
                 message_size: int = 10000000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params: Optional[Any] = None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators: Optional[Any] = None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = "data"):
        self.module = module
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32

    def __call__(self, *args, **kwargs):
        if self.module is None:
            raise TypeError("DistributedDataParallel wraps no module")
        return self.module(*args, **kwargs)

    def reduce_gradients(self, grads):
        if self.allreduce_always_fp32:
            # apex option: cast half grads to fp32 for the reduction, back
            # after (allreduce_bucket's allreduce_always_fp32 branch)
            dtypes = jax.tree_util.tree_map(lambda g: jnp.asarray(g).dtype,
                                            grads)
            grads32 = jax.tree_util.tree_map(
                lambda g: jnp.asarray(g, jnp.float32), grads)
            red = average_gradients(grads32, self.axis_name,
                                    self.gradient_predivide_factor,
                                    self.gradient_average)
            return jax.tree_util.tree_map(
                lambda g, d: jnp.asarray(g, d), red, dtypes)
        return average_gradients(grads, self.axis_name,
                                 self.gradient_predivide_factor,
                                 self.gradient_average)
