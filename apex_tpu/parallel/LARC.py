"""LARC — layer-wise adaptive rate control wrapping any optimizer.

Reference: apex/parallel/LARC.py — class LARC.step. Per parameter tensor,
apex computes

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)

and, in ``clip=True`` mode, scales the *gradient* by
``min(adaptive_lr / lr, 1)`` (so the effective LR is min(lr, adaptive_lr));
in ``clip=False`` mode scales by ``adaptive_lr`` directly (LARS-style).
Weight decay is folded into the scaled gradient before the wrapped
optimizer's step, and params with zero norm are left untouched.

TPU design: a ``optax.GradientTransformation`` applied upstream of the inner
optimizer — identical math, per-leaf, in one fused jaxpr. Wrap as
``larc(optax.sgd(lr), lr, ...)`` or use the :class:`LARC` class facade which
mirrors apex's "wrap an existing optimizer instance" shape.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable]


class LARCState(NamedTuple):
    count: jnp.ndarray


def larc_transform(learning_rate: ScalarOrSchedule,
                   trust_coefficient: float = 0.02,
                   clip: bool = True, eps: float = 1e-8,
                   weight_decay: float = 0.0) -> optax.GradientTransformation:
    """The gradient-rescaling stage of LARC as an optax transformation.

    Chain it before the inner optimizer:
    ``optax.chain(larc_transform(lr), optax.sgd(lr, momentum))``.
    """

    def init_fn(params):
        del params
        return LARCState(count=jnp.zeros([], jnp.int32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("larc requires params")
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate

        def one(g, p):
            p32 = jnp.asarray(p, jnp.float32)
            g32 = jnp.asarray(g, jnp.float32)
            pn = jnp.linalg.norm(p32.ravel())
            gn = jnp.linalg.norm(g32.ravel())
            adaptive = trust_coefficient * pn / (gn + weight_decay * pn + eps)
            if clip:
                scale = jnp.minimum(adaptive / lr, 1.0)
            else:
                scale = adaptive
            # apex skips params/grads with zero norm (LARC.py — the
            # `if param_norm != 0 and grad_norm != 0` guard)
            scale = jnp.where((pn != 0) & (gn != 0), scale, 1.0)
            out = (g32 + weight_decay * p32) * scale
            return out.astype(jnp.asarray(g).dtype)

        new = jax.tree_util.tree_map(one, updates, params)
        return new, LARCState(count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)


def larc(inner: optax.GradientTransformation,
         learning_rate: ScalarOrSchedule,
         trust_coefficient: float = 0.02, clip: bool = True,
         eps: float = 1e-8,
         weight_decay: float = 0.0) -> optax.GradientTransformation:
    """LARC-wrapped optimizer (grad rescale → inner update)."""
    return optax.chain(
        larc_transform(learning_rate, trust_coefficient, clip, eps,
                       weight_decay),
        inner)


class LARC:
    """Class facade matching apex's ``LARC(optimizer, trust_coefficient=...)``
    wrap-an-instance usage, for the framework's FusedSGD-style classes.

    The wrapped object must expose ``.step(grads, params)`` and hold
    ``lr``/``weight_decay`` attributes (all apex_tpu fused optimizer classes
    do)."""

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def __getattr__(self, name):
        return getattr(self.optim, name)

    def step(self, grads, params):
        lr = getattr(self.optim, "lr", None)
        wd = getattr(self.optim, "weight_decay", 0.0)
        tx = larc_transform(lr if lr is not None else 1.0,
                            self.trust_coefficient, self.clip, self.eps, wd)
        scaled, _ = tx.update(grads, tx.init(params), params)
        # Apex idiom (LARC.py — step): weight decay is folded into the
        # trust-scaled gradient above, so the INNER step must run with the
        # group's weight_decay zeroed (else decay applies twice, unscaled),
        # restored afterwards. param_groups is live — the fused classes
        # rebuild their transform from it (optimizers/_surface.py).
        groups = getattr(self.optim, "param_groups", None)
        saved = None
        if groups:
            saved = [g.get("weight_decay", 0.0) for g in groups]
            for g in groups:
                g["weight_decay"] = 0.0
        try:
            return self.optim.step(scaled, params)
        finally:
            if groups:
                for g, w in zip(groups, saved):
                    g["weight_decay"] = w
