"""SyncBatchNorm — cross-device batch norm via stat psum over a mesh axis.

Reference: apex/parallel/optimized_sync_batchnorm.py +
optimized_sync_batchnorm_kernel.py — SyncBatchnormFunction. The CUDA path
computes per-GPU Welford (mean, var, count) with the ``syncbn`` extension,
all_gathers the per-rank stats, combines them (welford_parallel), then
normalizes; backward all_reduces (sum_dy, sum_dy_xmu).

TPU mapping: the per-device moment computation is one fused XLA reduction, the
cross-rank Welford combine collapses to ``psum`` of (sum, sum-of-squares,
count) over the named axis — algebraically identical to the count-weighted
Welford combination (csrc/welford.cu — welford_parallel_CUDA weights each
rank's contribution by its element count) and numerically done in fp32. Under
SPMD every rank's *shape* is identical, so unequal counts enter through the
optional ``mask`` argument (ragged last batches padded to shape): masked
elements are excluded from the statistics but still normalized. Backward needs
no custom kernel at all: the psums sit inside the autodiff graph, so XLA
derives exactly apex's batchnorm_backward allreduce pattern (the transpose of
psum is psum).

Process groups (apex/parallel/__init__.py — create_syncbn_process_group's
``group_size``) map to ``axis_index_groups``: stats sync within fixed-size
subgroups of the axis.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


def create_syncbn_process_group(axis_size: int, group_size: int):
    """Partition an axis of ``axis_size`` devices into contiguous groups of
    ``group_size`` — returns axis_index_groups for :class:`SyncBatchNorm`.

    Mirrors apex/parallel/__init__.py — create_syncbn_process_group (which
    builds torch.distributed new_group()s of group_size ranks each).
    """
    if group_size <= 0 or axis_size % group_size != 0:
        raise ValueError(
            f"group_size {group_size} must evenly divide axis size {axis_size}")
    return [list(range(i, i + group_size))
            for i in range(0, axis_size, group_size)]


class SyncBatchNorm(nn.Module):
    """Drop-in for flax ``nn.BatchNorm`` that reduces batch statistics over
    ``axis_name`` (reference: apex/parallel/SyncBatchNorm).

    With ``axis_name=None`` (or when called outside shard_map/pmap traces via
    ``use_running_average=True``) it behaves as plain BatchNorm, matching
    apex's fallback when torch.distributed isn't initialized.
    """

    use_running_average: Optional[bool] = None
    axis: int = -1
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Any = nn.initializers.zeros
    scale_init: Any = nn.initializers.ones
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None,
                 mask=None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feature_axis = self.axis % x.ndim
        reduction_axes = tuple(i for i in range(x.ndim) if i != feature_axis)
        feature_shape = (x.shape[feature_axis],)

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(feature_shape, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(feature_shape, jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            x32 = x.astype(jnp.float32)
            # Local partial sums in fp32 (csrc/welford.cu — welford_mean_var
            # accumulates in accscalar_t=float). We carry (sum, sumsq, count)
            # rather than moments so the cross-rank combine is exact for
            # unequal per-rank element counts (welford_parallel_CUDA weights
            # by count); counts differ only when a validity mask marks padded
            # elements of a ragged batch.
            if mask is not None:
                m32 = jnp.broadcast_to(mask, x.shape).astype(jnp.float32)
                s = jnp.sum(x32 * m32, axis=reduction_axes)
                ss = jnp.sum(jnp.square(x32) * m32, axis=reduction_axes)
                cnt = jnp.sum(m32, axis=reduction_axes)
            else:
                s = jnp.sum(x32, axis=reduction_axes)
                ss = jnp.sum(jnp.square(x32), axis=reduction_axes)
                cnt = jnp.full(feature_shape,
                               float(x32.size // x32.shape[feature_axis]),
                               jnp.float32)
            # During module init there is no bound mesh axis to reduce over
            # (apex likewise skips comm when torch.distributed isn't up).
            if self.axis_name is not None and not self.is_initializing():
                s, ss, cnt = jax.lax.psum(
                    (s, ss, cnt), self.axis_name,
                    axis_index_groups=self.axis_index_groups)
            safe_cnt = jnp.maximum(cnt, 1.0)
            mean = s / safe_cnt
            var = ss / safe_cnt - jnp.square(mean)

            if not self.is_initializing():
                # biased var for normalization, unbiased for running stats —
                # apex matches torch.nn.BatchNorm semantics here. A batch with
                # zero valid elements (all-padding drain step) must leave the
                # running stats untouched rather than decay them toward 0.
                unbiased = var * (safe_cnt / jnp.maximum(safe_cnt - 1.0, 1.0))
                m = self.momentum
                has_data = cnt > 0
                ra_mean.value = jnp.where(
                    has_data, m * ra_mean.value + (1 - m) * mean,
                    ra_mean.value)
                ra_var.value = jnp.where(
                    has_data, m * ra_var.value + (1 - m) * unbiased,
                    ra_var.value)

        y = (x.astype(jnp.float32)
             - mean.reshape([-1 if i == feature_axis else 1
                             for i in range(x.ndim)]))
        y = y * jax.lax.rsqrt(
            var + self.epsilon).reshape([-1 if i == feature_axis else 1
                                         for i in range(x.ndim)])
        if self.use_scale:
            scale = self.param("scale", self.scale_init, feature_shape,
                               self.param_dtype)
            y = y * scale.astype(jnp.float32).reshape(
                [-1 if i == feature_axis else 1 for i in range(x.ndim)])
        if self.use_bias:
            bias = self.param("bias", self.bias_init, feature_shape,
                              self.param_dtype)
            y = y + bias.astype(jnp.float32).reshape(
                [-1 if i == feature_axis else 1 for i in range(x.ndim)])
        # O1 engine: 'batch_norm' is FP32_FUNCS — with no explicit dtype an
        # active autocast policy keeps the (already-fp32) result in fp32
        from apex_tpu.amp.autocast import resolve_dtype
        out_dtype = resolve_dtype(self.dtype, "batch_norm", x.dtype)
        return y.astype(out_dtype)


def convert_syncbn_model(module, axis_name: str = "data",
                         process_group: Optional[Sequence[Sequence[int]]] = None):
    """apex/parallel/__init__.py — convert_syncbn_model: swap BatchNorm for
    SyncBatchNorm throughout a model.

    Apex walks module children and replaces ``nn.BatchNorm2d`` instances; flax
    modules are frozen dataclasses configured up-front, so conversion means
    rebinding the model's injectable ``norm_cls`` field (the pattern our model
    zoo uses — apex_tpu/models/resnet.py). Models without a ``norm_cls`` field
    must be constructed with SyncBatchNorm directly.
    """
    import functools

    bound = functools.partial(SyncBatchNorm, axis_name=axis_name,
                              axis_index_groups=process_group)
    if hasattr(module, "norm_cls"):
        return module.replace(norm_cls=bound)
    raise TypeError(
        f"{type(module).__name__} has no injectable 'norm_cls' field; "
        "construct it with norm_cls=apex_tpu.parallel.SyncBatchNorm instead")
