"""SyncBatchNorm — cross-device batch norm via stat psum over a mesh axis.

Reference: apex/parallel/optimized_sync_batchnorm.py +
optimized_sync_batchnorm_kernel.py — SyncBatchnormFunction. The CUDA path
computes per-GPU Welford (mean, var, count) with the ``syncbn`` extension,
all_gathers the per-rank stats, combines them (welford_parallel), then
normalizes; backward all_reduces (sum_dy, sum_dy_xmu).

TPU mapping: the per-device moment computation is one fused XLA reduction
producing the Welford triple (mean, M2, count); the cross-rank combine
all_gathers the per-rank triples and folds them with Chan's count-weighted
formula — the ACTUAL welford_parallel algorithm (csrc/welford.cu —
welford_parallel_CUDA), which is exact for unequal counts AND numerically
stable where a psum of (sum, sumsq) cancels catastrophically for
large-mean activations. Under SPMD every rank's *shape* is identical, so
unequal counts enter through the optional ``mask`` argument (ragged last
batches padded to shape): masked elements are excluded from the statistics
but still normalized. Backward needs no custom kernel at all: the gathers
sit inside the autodiff graph, so XLA derives exactly apex's
batchnorm_backward allreduce pattern.

Process groups (apex/parallel/__init__.py — create_syncbn_process_group's
``group_size``) map to ``axis_index_groups``: stats sync within fixed-size
subgroups of the axis.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


def create_syncbn_process_group(axis_size: int, group_size: int):
    """Partition an axis of ``axis_size`` devices into contiguous groups of
    ``group_size`` — returns axis_index_groups for :class:`SyncBatchNorm`.

    Mirrors apex/parallel/__init__.py — create_syncbn_process_group (which
    builds torch.distributed new_group()s of group_size ranks each).
    """
    if group_size <= 0 or axis_size % group_size != 0:
        raise ValueError(
            f"group_size {group_size} must evenly divide axis size {axis_size}")
    return [list(range(i, i + group_size))
            for i in range(0, axis_size, group_size)]


def _welford_fold(means, m2s, cnts):
    """Fold stacked per-rank Welford triples [W, C] with Chan's
    count-weighted combine (csrc/welford.cu — welford_parallel_CUDA).
    The combine is associative, so pairs fold in log2(W) rounds — O(W)
    serial chains would stretch the critical path on wide axes. Odd
    remainders carry a zero-count pad, which the combine ignores exactly
    (nb=0 leaves (mean, m2) untouched)."""
    while means.shape[0] > 1:
        w = means.shape[0]
        if w % 2:
            pad = lambda a: jnp.concatenate(
                [a, jnp.zeros_like(a[:1])], axis=0)
            means, m2s, cnts = pad(means), pad(m2s), pad(cnts)
            w += 1
        ma, mb = means[0::2], means[1::2]
        sa, sb = m2s[0::2], m2s[1::2]
        na, nb = cnts[0::2], cnts[1::2]
        total = jnp.maximum(na + nb, 1.0)
        delta = mb - ma
        means = ma + delta * (nb / total)
        m2s = sa + sb + jnp.square(delta) * (na * nb / total)
        cnts = na + nb
    return means[0], m2s[0], cnts[0]


class SyncBatchNorm(nn.Module):
    """Drop-in for flax ``nn.BatchNorm`` that reduces batch statistics over
    ``axis_name`` (reference: apex/parallel/SyncBatchNorm).

    With ``axis_name=None`` (or when called outside shard_map/pmap traces via
    ``use_running_average=True``) it behaves as plain BatchNorm, matching
    apex's fallback when torch.distributed isn't initialized.
    """

    use_running_average: Optional[bool] = None
    axis: int = -1
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Dtype] = None
    param_dtype: Dtype = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Any = nn.initializers.zeros
    scale_init: Any = nn.initializers.ones
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None,
                 mask=None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feature_axis = self.axis % x.ndim
        reduction_axes = tuple(i for i in range(x.ndim) if i != feature_axis)
        feature_shape = (x.shape[feature_axis],)

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(feature_shape, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(feature_shape, jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            x32 = x.astype(jnp.float32)
            expand = [-1 if i == feature_axis else 1 for i in range(x.ndim)]
            # Local Welford triple in fp32 (csrc/welford.cu —
            # welford_mean_var accumulates in accscalar_t=float): mean,
            # CENTERED M2, count. Centering before squaring keeps
            # large-mean activations finite where sum/sumsq cancels;
            # counts differ across ranks only through the validity mask
            # (ragged padded batches).
            if mask is not None:
                m32 = jnp.broadcast_to(mask, x.shape).astype(jnp.float32)
                cnt = jnp.sum(m32, axis=reduction_axes)
                safe = jnp.maximum(cnt, 1.0)
                mean = jnp.sum(x32 * m32, axis=reduction_axes) / safe
                centered = (x32 - mean.reshape(expand)) * m32
                m2 = jnp.sum(jnp.square(centered), axis=reduction_axes)
            else:
                cnt = jnp.full(feature_shape,
                               float(x32.size // x32.shape[feature_axis]),
                               jnp.float32)
                mean = jnp.mean(x32, axis=reduction_axes)
                m2 = jnp.sum(jnp.square(x32 - mean.reshape(expand)),
                             axis=reduction_axes)
            # During module init there is no bound mesh axis to reduce over
            # (apex likewise skips comm when torch.distributed isn't up).
            if self.axis_name is not None and not self.is_initializing():
                # welford_parallel: all_gather the per-rank triples and
                # fold with Chan's count-weighted combine — apex gathers
                # mean_l/var_l/count and combines exactly the same way
                mean_g, m2_g, cnt_g = jax.lax.all_gather(
                    (mean, m2, cnt), self.axis_name,
                    axis_index_groups=self.axis_index_groups)
                mean, m2, cnt = _welford_fold(mean_g, m2_g, cnt_g)
            safe_cnt = jnp.maximum(cnt, 1.0)
            var = m2 / safe_cnt

            if not self.is_initializing():
                # biased var for normalization, unbiased for running stats —
                # apex matches torch.nn.BatchNorm semantics here. A batch with
                # zero valid elements (all-padding drain step) must leave the
                # running stats untouched rather than decay them toward 0.
                unbiased = var * (safe_cnt / jnp.maximum(safe_cnt - 1.0, 1.0))
                m = self.momentum
                has_data = cnt > 0
                ra_mean.value = jnp.where(
                    has_data, m * ra_mean.value + (1 - m) * mean,
                    ra_mean.value)
                ra_var.value = jnp.where(
                    has_data, m * ra_var.value + (1 - m) * unbiased,
                    ra_var.value)

        y = (x.astype(jnp.float32)
             - mean.reshape([-1 if i == feature_axis else 1
                             for i in range(x.ndim)]))
        y = y * jax.lax.rsqrt(
            var + self.epsilon).reshape([-1 if i == feature_axis else 1
                                         for i in range(x.ndim)])
        if self.use_scale:
            scale = self.param("scale", self.scale_init, feature_shape,
                               self.param_dtype)
            y = y * scale.astype(jnp.float32).reshape(
                [-1 if i == feature_axis else 1 for i in range(x.ndim)])
        if self.use_bias:
            bias = self.param("bias", self.bias_init, feature_shape,
                              self.param_dtype)
            y = y + bias.astype(jnp.float32).reshape(
                [-1 if i == feature_axis else 1 for i in range(x.ndim)])
        # O1 engine: 'batch_norm' is FP32_FUNCS — with no explicit dtype an
        # active autocast policy keeps the (already-fp32) result in fp32
        from apex_tpu.amp.autocast import resolve_dtype
        out_dtype = resolve_dtype(self.dtype, "batch_norm", x.dtype)
        return y.astype(out_dtype)


def convert_syncbn_model(module, axis_name: str = "data",
                         process_group: Optional[Sequence[Sequence[int]]] = None):
    """apex/parallel/__init__.py — convert_syncbn_model: swap BatchNorm for
    SyncBatchNorm throughout a model.

    Apex walks module children and replaces ``nn.BatchNorm2d`` instances; flax
    modules are frozen dataclasses configured up-front, so conversion means
    rebinding the model's injectable ``norm_cls`` field (the pattern our model
    zoo uses — apex_tpu/models/resnet.py). Models without a ``norm_cls`` field
    must be constructed with SyncBatchNorm directly.
    """
    import functools

    bound = functools.partial(SyncBatchNorm, axis_name=axis_name,
                              axis_index_groups=process_group)
    if hasattr(module, "norm_cls"):
        return module.replace(norm_cls=bound)
    raise TypeError(
        f"{type(module).__name__} has no injectable 'norm_cls' field; "
        "construct it with norm_cls=apex_tpu.parallel.SyncBatchNorm instead")
