"""apex_tpu.parallel — data parallelism utilities (reference: apex/parallel/).

- :class:`DistributedDataParallel` / :func:`average_gradients` /
  :class:`Reducer` — gradient averaging over a mesh axis (flat-bucket NCCL
  allreduce in the reference, one psum under XLA here).
- :class:`SyncBatchNorm` + :func:`convert_syncbn_model` +
  :func:`create_syncbn_process_group` — cross-device batch norm statistics.
- :func:`larc` / :class:`LARC` — layer-wise adaptive rate control.
- ``multiproc`` — launcher parity shim (single process drives all chips).
"""

from .distributed import (  # noqa: F401
    DistributedDataParallel, Reducer, average_gradients)
from .LARC import LARC, larc, larc_transform  # noqa: F401
from .sync_batchnorm import (  # noqa: F401
    SyncBatchNorm, convert_syncbn_model, create_syncbn_process_group)

__all__ = [
    "DistributedDataParallel", "Reducer", "average_gradients",
    "LARC", "larc", "larc_transform",
    "SyncBatchNorm", "convert_syncbn_model", "create_syncbn_process_group",
]
