"""Multi-process launcher parity shim.

Reference: apex/parallel/multiproc.py — main: a pre-torchrun launcher that
spawned one training process per GPU with WORLD_SIZE/RANK env vars.

On TPU there is nothing to launch: a single Python process drives every local
chip through the runtime, and multi-host jobs get one process per host started
by the cluster scheduler, bootstrapped with ``jax.distributed.initialize()``
(see apex_tpu.comm.initialize_distributed). This module exists so
``python -m apex_tpu.parallel.multiproc script.py`` keeps working: it execs
the script once, which is the correct process topology for a TPU host.
"""

from __future__ import annotations

import runpy
import sys


def main():
    if len(sys.argv) < 2:
        print("usage: python -m apex_tpu.parallel.multiproc <script> [args...]",
              file=sys.stderr)
        return 1
    sys.argv = sys.argv[1:]
    print("apex_tpu.parallel.multiproc: TPU hosts run one process for all "
          "local chips; executing the script directly.", file=sys.stderr)
    runpy.run_path(sys.argv[0], run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
