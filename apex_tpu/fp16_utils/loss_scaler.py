"""Legacy loss scalers — TPU equivalent of apex/fp16_utils/loss_scaler.py.

Reference symbols (loss_scaler.py — class LossScaler, class DynamicLossScaler):
the pre-amp manual API. ``LossScaler`` is a fixed scale with no overflow
tracking; ``DynamicLossScaler`` starts high (2**32 in apex's legacy default),
halves on overflow, doubles after ``scale_window`` clean iterations.

These are thin shims over the shared scaler math in apex_tpu.amp.scaler (the
modern path); kept as distinct classes because apex's two APIs differ:
legacy exposes ``scale`` (attr) / ``has_overflow(params)`` / ``update_scale
(overflow)``, amp's exposes ``loss_scale()`` / implicit overflow tracking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _has_inf_or_nan(x) -> bool:
    """loss_scaler.py — DynamicLossScaler._has_inf_or_nan (per-tensor check)."""
    arr = jnp.asarray(x)
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return False
    return bool(jnp.logical_not(jnp.all(jnp.isfinite(arr))))


class LossScaler:
    """Static scale. loss_scaler.py — class LossScaler."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    # apex's legacy API takes the param/grad list; static scaler never overflows
    def has_overflow(self, params) -> bool:
        return False

    def update_scale(self, overflow: bool) -> None:
        pass

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(
            lambda g: g * jnp.asarray(self.cur_scale, jnp.asarray(g).dtype),
            grads)

    def backward(self, loss):
        """Return the scaled loss (caller differentiates it)."""
        return loss * jnp.asarray(self.cur_scale, jnp.asarray(loss).dtype)


class DynamicLossScaler(LossScaler):
    """loss_scaler.py — class DynamicLossScaler.

    Legacy schedule: ``scale_factor`` 2.0, ``scale_window`` 1000 (the legacy
    default; amp's LossScaler uses 2000), init 2**32.
    """

    def __init__(self, init_scale: float = 2.0 ** 32,
                 scale_factor: float = 2.0, scale_window: int = 1000):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.last_overflow_iter = -1
        self.cur_iter = 0

    def has_overflow(self, grads) -> bool:
        for leaf in jax.tree_util.tree_leaves(grads):
            if _has_inf_or_nan(leaf):
                return True
        return False

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
