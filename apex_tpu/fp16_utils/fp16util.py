"""fp16/bf16 conversion helpers — TPU equivalent of apex/fp16_utils/fp16util.py.

Reference symbols mirrored (apex/fp16_utils/fp16util.py — network_to_half,
BN_convert_float, prep_param_lists, model_grads_to_master_grads,
master_params_to_model_params, to_python_float, clip_grad_norm):

- apex converts ``nn.Module`` trees in place, keeping BatchNorm modules fp32
  for numeric safety. Here the model is a param pytree, so conversion is a
  ``tree_map`` with a path predicate standing in for the module-type check.
- ``prep_param_lists`` pairs the (half) model params with fp32 master copies;
  ``model_grads_to_master_grads`` / ``master_params_to_model_params`` are the
  two copies in apex's manual mixed-precision loop (csrc-free pure ops here —
  XLA fuses the casts into adjacent work).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

# Module-path fragments treated as "BatchNorm" for keep-fp32 purposes —
# the pytree analogue of apex's ``isinstance(module, _BatchNorm)`` check.
_BN_PATH_FRAGMENTS = ("batchnorm", "batch_norm", "bn", "syncbatchnorm")


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts).lower()


def is_batchnorm_path(path) -> bool:
    """True when a pytree path addresses a batch-norm parameter."""
    s = _path_str(path)
    return any(frag in s for frag in _BN_PATH_FRAGMENTS)


def network_to_half(
    params: Any,
    dtype: jnp.dtype = jnp.bfloat16,
    keep_fp32: Optional[Callable[[Any], bool]] = is_batchnorm_path,
) -> Any:
    """Cast a param pytree to half precision, keeping BN params fp32.

    Mirrors fp16util.py — network_to_half + BN_convert_float: apex wraps the
    model in ``nn.Sequential(tofp16(), convert_module'd model)``; functionally
    that is exactly "cast every non-BN floating leaf". ``dtype`` defaults to
    bf16, the TPU-native half type (fp16 accepted for scaler tests).
    """

    def cast(path, leaf):
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        if keep_fp32 is not None and keep_fp32(path):
            return leaf.astype(jnp.float32)
        return leaf.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def convert_network(params: Any, dtype: jnp.dtype = jnp.bfloat16) -> Any:
    """Alias with apex's name (fp16util.py — convert_network)."""
    return network_to_half(params, dtype=dtype)


def BN_convert_float(params: Any) -> Any:
    """Force batch-norm params back to fp32 (fp16util.py — BN_convert_float).

    Apex applies it to a module tree after ``.half()``; the pytree analogue
    re-casts every BN-path leaf of an already-halved tree.
    """

    def cast(path, leaf):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating) and is_batchnorm_path(path):
            return leaf.astype(jnp.float32)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, params)


def prep_param_lists(params: Any,
                     flat_master: bool = False) -> Tuple[Any, Any]:
    """(model_params, fp32 master copies).

    fp16util.py — prep_param_lists: with ``flat_master=True`` apex flattens all
    masters into one contiguous fp32 buffer (_flatten_dense_tensors). Here the
    flat variant returns (params, (flat_fp32_vector, unravel_fn)) via pytree
    ravel — same memory layout win, jax-native mechanism.
    """
    if flat_master:
        from apex_tpu.utils.pytree import flatten_tree  # apex_C.flatten parity

        flat, spec = flatten_tree(
            jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32),
                                   params))
        return params, (flat, spec)
    master = jax.tree_util.tree_map(
        lambda p: jnp.asarray(p, jnp.float32), params)
    return params, master


def model_grads_to_master_grads(model_grads: Any, flat: bool = False) -> Any:
    """Cast (half) model grads to fp32 master grads.

    fp16util.py — model_grads_to_master_grads.
    """
    master = jax.tree_util.tree_map(
        lambda g: jnp.asarray(g, jnp.float32), model_grads)
    if flat:
        from apex_tpu.utils.pytree import flatten_tree

        return flatten_tree(master)[0]
    return master


def master_params_to_model_params(master_params: Any,
                                  model_params: Any) -> Any:
    """Copy fp32 masters back into the model's dtypes (shape-preserving).

    fp16util.py — master_params_to_model_params.
    """
    return jax.tree_util.tree_map(
        lambda m, p: jnp.asarray(m, jnp.asarray(p).dtype),
        master_params, model_params)


def to_python_float(t) -> float:
    """fp16util.py — to_python_float (``t.item()`` with list fallback)."""
    arr = jnp.asarray(t)
    return float(arr.reshape(()))


def clip_grad_norm(grads: Any, max_norm: float,
                   norm_type: float = 2.0) -> Tuple[Any, jnp.ndarray]:
    """Global-norm clip over a grad pytree; returns (clipped, total_norm).

    fp16util.py — clip_grad_norm (re-export of torch's): computes the global
    norm in fp32 and scales every grad by ``max_norm / (norm + 1e-6)`` when
    over. The fp32 accumulation is the part that matters for parity.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(jnp.asarray(l, jnp.float32))) for l in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(jnp.asarray(l, jnp.float32)) ** norm_type)
             for l in leaves])) ** (1.0 / norm_type)
    clip = jnp.minimum(1.0, max_norm / (total + 1e-6))

    def scale(g):
        return (jnp.asarray(g, jnp.float32) * clip).astype(
            jnp.asarray(g).dtype)

    return jax.tree_util.tree_map(scale, grads), total
