"""FP16_Optimizer — TPU equivalent of apex/fp16_utils/fp16_optimizer.py.

Reference (fp16_optimizer.py — class FP16_Optimizer): the pre-amp manual
mixed-precision wrapper. It owns fp32 master copies of the (half) model
params, scales the loss, and on ``step``:

  1. check grads for inf/nan (DynamicLossScaler.has_overflow)
  2. overflow → update_scale, SKIP (optimizer state must not advance)
  3. else: model grads → fp32 master grads, ÷ scale, optional global-norm clip
  4. inner optimizer steps the masters
  5. masters copied back into the model's half params

TPU design: wraps an optax ``GradientTransformation`` instead of a torch
optimizer; params/grads are pytrees. The overflow-gated step runs under jit
with ``lax.cond``-free ``tree_map(where)`` select so the whole thing is one
compiled program; the Python-level scaler bookkeeping (scale schedule) stays
host-side exactly like apex's.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.fp16_utils.fp16util import (
    clip_grad_norm,
    master_params_to_model_params,
    model_grads_to_master_grads,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler


class FP16_Optimizer:
    """Manual master-weight mixed precision (fp16_optimizer.py — FP16_Optimizer)."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        params: Any,
        static_loss_scale: float = 1.0,
        dynamic_loss_scale: bool = False,
        dynamic_loss_args: Optional[dict] = None,
        verbose: bool = False,
    ):
        self.optimizer = optimizer
        self.verbose = verbose
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)

        # fp32 masters + inner optimizer state live here (apex: param_groups
        # rewritten to point at masters; optimizer state keyed on them).
        self.fp32_params = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.float32), params)
        self.opt_state = optimizer.init(self.fp32_params)
        self.overflow = False

    # -- loss scaling ------------------------------------------------------
    def scale_loss(self, loss):
        """Scaled loss for the caller to differentiate.

        apex's ``backward(loss)`` calls ``loss*scale .backward()``; in jax the
        caller owns autodiff, so the analogue is
        ``grads = grad(lambda p: opt.scale_loss(loss_fn(p)))(params)``.
        """
        return loss * jnp.asarray(self.loss_scaler.loss_scale,
                                  jnp.asarray(loss).dtype)

    @property
    def loss_scale(self) -> float:
        return self.loss_scaler.loss_scale

    # -- step --------------------------------------------------------------
    def step(self, model_grads: Any, model_params: Any,
             max_grad_norm: Optional[float] = None) -> Any:
        """Returns updated model params (same dtypes as ``model_params``).

        Mirrors fp16_optimizer.py — step: overflow check happens on the raw
        model grads (pre-unscale), matching apex's has_overflow placement.
        """
        self.overflow = self.loss_scaler.has_overflow(model_grads)
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            if self.verbose:
                from apex_tpu.log_util import get_logger

                get_logger("fp16_utils").warning(
                    "OVERFLOW! Skipping step. Reducing loss scale to %s",
                    self.loss_scaler.loss_scale)
            return model_params

        master_grads = model_grads_to_master_grads(model_grads)
        inv = 1.0 / self.loss_scaler.loss_scale
        master_grads = jax.tree_util.tree_map(
            lambda g: g * jnp.float32(inv), master_grads)
        if max_grad_norm is not None:
            master_grads, _ = clip_grad_norm(master_grads, max_grad_norm)

        updates, self.opt_state = self.optimizer.update(
            master_grads, self.opt_state, self.fp32_params)
        self.fp32_params = optax.apply_updates(self.fp32_params, updates)
        return master_params_to_model_params(self.fp32_params, model_params)

    def clip_master_grads(self, grads: Any, max_norm: float):
        """fp16_optimizer.py — clip_master_grads (exposed for manual loops)."""
        return clip_grad_norm(grads, max_norm)

    # -- checkpointing -----------------------------------------------------
    def state_dict(self) -> dict:
        sd = {
            "loss_scale": self.loss_scaler.loss_scale,
            "overflow": self.overflow,
            "fp32_params": self.fp32_params,
            "opt_state": self.opt_state,
        }
        if isinstance(self.loss_scaler, DynamicLossScaler):
            sd["cur_iter"] = self.loss_scaler.cur_iter
            sd["last_overflow_iter"] = self.loss_scaler.last_overflow_iter
        return sd

    def load_state_dict(self, sd: dict) -> None:
        self.loss_scaler.cur_scale = float(sd["loss_scale"])
        self.overflow = bool(sd["overflow"])
        self.fp32_params = sd["fp32_params"]
        self.opt_state = sd["opt_state"]
        if isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler.cur_iter = int(sd.get("cur_iter", 0))
            self.loss_scaler.last_overflow_iter = int(
                sd.get("last_overflow_iter", -1))
