"""apex_tpu.fp16_utils — legacy manual mixed-precision API.

TPU equivalent of apex/fp16_utils/ (reference: fp16util.py, loss_scaler.py,
fp16_optimizer.py — the pre-amp API kept for backward compatibility). New code
should use apex_tpu.amp; this tier exists for apex API parity.
"""

from apex_tpu.fp16_utils.fp16util import (
    BN_convert_float,
    clip_grad_norm,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer

__all__ = [
    "BN_convert_float",
    "DynamicLossScaler",
    "FP16_Optimizer",
    "LossScaler",
    "clip_grad_norm",
    "convert_network",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "network_to_half",
    "prep_param_lists",
    "to_python_float",
]
