"""apex_tpu.RNN — fp16/bf16-friendly recurrent layers (reference: apex/RNN).

The reference's ``apex/RNN/models.py — LSTM, GRU, ReLU, Tanh, mLSTM`` (built
from ``RNNBackend.py — RNNCell, stackedRNN, bidirectionalRNN`` and
``cells.py — mLSTMRNNCell``) exists because cuDNN's fused RNNs didn't support
fp16 master-weight training; apex rebuilt them from cells so amp could manage
dtypes.

TPU-first redesign, not a translation:

- The input projection ``x @ W_ih^T`` for ALL timesteps of a layer is hoisted
  out of the recurrence into one large MXU GEMM (time×batch collapsed); the
  ``lax.scan`` body carries only the unavoidable serial dependence
  ``h @ W_hh^T`` plus elementwise gating. This is the structure cuDNN's
  persistent RNNs hand-schedule; here XLA gets it from the trace shape.
- Gate math runs in fp32 (``preferred_element_type``) with half I/O, the
  property the reference's cells exist to guarantee.
- Weight layout and parameter names are torch's (``weight_ih_l{k}``,
  ``(4H, in)``, gate order i,f,g,o / r,z,n), so state dicts port and
  torch-CPU is the test oracle.

mLSTM follows ``cells.py — mLSTMCell``: ``m = (x W_mih) * (h W_mhh)`` feeds
the recurrent half of otherwise-standard LSTM gates (Krause et al. 2016).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.fused_dense import _linear_fp32 as _linear32
from apex_tpu.fused_dense import torch_linear_init

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "RNNBase"]


def _lstm_step(carry, gates32):
    h, c = carry
    i, f, g, o = jnp.split(gates32, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


class RNNBase(nn.Module):
    """Shared stacked/bidirectional scan machinery.

    Reference: apex/RNN/RNNBackend.py — stackedRNN + bidirectionalRNN; the
    constructor surface matches apex's model factories (which mirror
    torch.nn.LSTM/GRU): ``(input_size, hidden_size, num_layers, bias,
    batch_first, dropout, bidirectional)``.
    """

    input_size: int
    hidden_size: int
    num_layers: int = 1
    bias: bool = True
    batch_first: bool = False
    dropout: float = 0.0
    bidirectional: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    # subclass contract
    mode: str = "LSTM"  # LSTM | GRU | RNN_TANH | RNN_RELU | MLSTM

    @property
    def _gate_mult(self):
        return {"LSTM": 4, "MLSTM": 4, "GRU": 3,
                "RNN_TANH": 1, "RNN_RELU": 1}[self.mode]

    @property
    def _has_cell_state(self):
        return self.mode in ("LSTM", "MLSTM")

    def _layer_params(self, layer: int, suffix: str, in_size: int):
        gm = self._gate_mult
        # torch RNN reset_parameters: uniform(±1/sqrt(hidden_size)) for every
        # weight and bias, which torch_linear_init(hidden_size) produces.
        shifted = torch_linear_init(self.hidden_size)
        H = self.hidden_size
        p = {
            "w_ih": self.param(f"weight_ih_l{layer}{suffix}", shifted,
                               (gm * H, in_size), self.param_dtype),
            "w_hh": self.param(f"weight_hh_l{layer}{suffix}", shifted,
                               (gm * H, H), self.param_dtype),
        }
        if self.bias:
            p["b_ih"] = self.param(f"bias_ih_l{layer}{suffix}", shifted,
                                   (gm * H,), self.param_dtype)
            p["b_hh"] = self.param(f"bias_hh_l{layer}{suffix}", shifted,
                                   (gm * H,), self.param_dtype)
        if self.mode == "MLSTM":
            p["w_mih"] = self.param(f"weight_mih_l{layer}{suffix}", shifted,
                                    (H, in_size), self.param_dtype)
            p["w_mhh"] = self.param(f"weight_mhh_l{layer}{suffix}", shifted,
                                    (H, H), self.param_dtype)
        return p

    def _scan_layer(self, x_tbf, h0, c0, p, reverse: bool):
        """One direction of one layer. x_tbf: (T, B, in)."""
        dtype = x_tbf.dtype
        b_ih = p.get("b_ih")
        b_hh = p.get("b_hh")
        if self.mode == "MLSTM":
            # input half of m precomputed for all t in one GEMM
            mx = _linear32(x_tbf, p["w_mih"])  # (T, B, H) fp32
            gx = _linear32(x_tbf, p["w_ih"], b_ih)

            def step(carry, inp):
                h, c = carry
                mx_t, gx_t = inp
                m = mx_t * _linear32(h, p["w_mhh"])
                gates = gx_t + _linear32(jnp.asarray(m, dtype), p["w_hh"],
                                         b_hh)
                h32, c32 = _lstm_step((jnp.asarray(h, jnp.float32),
                                       jnp.asarray(c, jnp.float32)), gates)
                h_new = jnp.asarray(h32, dtype)
                return (h_new, jnp.asarray(c32, dtype)), h_new

            (h_n, c_n), ys = lax.scan(step, (h0, c0), (mx, gx),
                                      reverse=reverse)
            return ys, h_n, c_n

        gx = _linear32(x_tbf, p["w_ih"], b_ih)  # (T, B, gm*H) fp32

        if self.mode in ("LSTM",):
            def step(carry, gx_t):
                h, c = carry
                gates = gx_t + _linear32(h, p["w_hh"], b_hh)
                h32, c32 = _lstm_step((jnp.asarray(h, jnp.float32),
                                       jnp.asarray(c, jnp.float32)), gates)
                h_new = jnp.asarray(h32, dtype)
                return (h_new, jnp.asarray(c32, dtype)), h_new

            (h_n, c_n), ys = lax.scan(step, (h0, c0), gx, reverse=reverse)
            return ys, h_n, c_n

        if self.mode == "GRU":
            def step(h, gx_t):
                gh = _linear32(h, p["w_hh"], b_hh)
                rx, zx, nx = jnp.split(gx_t, 3, axis=-1)
                rh, zh, nh = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(rx + rh)
                z = jax.nn.sigmoid(zx + zh)
                n = jnp.tanh(nx + r * nh)
                h32 = (1.0 - z) * n + z * jnp.asarray(h, jnp.float32)
                h_new = jnp.asarray(h32, dtype)
                return h_new, h_new

            h_n, ys = lax.scan(step, h0, gx, reverse=reverse)
            return ys, h_n, None

        act = jnp.tanh if self.mode == "RNN_TANH" else jax.nn.relu

        def step(h, gx_t):
            h32 = act(gx_t + _linear32(h, p["w_hh"], b_hh))
            h_new = jnp.asarray(h32, dtype)
            return h_new, h_new

        h_n, ys = lax.scan(step, h0, gx, reverse=reverse)
        return ys, h_n, None

    @nn.compact
    def __call__(self, x, hidden=None, deterministic: bool = True):
        """Returns (output, h_n) or (output, (h_n, c_n)) following torch/apex.

        ``x``: (T, B, F), or (B, T, F) when ``batch_first``. ``hidden``:
        optional (h_0[, c_0]) of shape (num_layers*num_directions, B, H).
        """
        if self.dtype is not None:
            x = jnp.asarray(x, self.dtype)
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        T, B = x.shape[0], x.shape[1]
        H = self.hidden_size
        ndir = 2 if self.bidirectional else 1

        if hidden is None:
            h0_all = jnp.zeros((self.num_layers * ndir, B, H), x.dtype)
            c0_all = jnp.zeros_like(h0_all) if self._has_cell_state else None
        elif self._has_cell_state:
            h0_all, c0_all = hidden
            # carry dtype must match the step output dtype or lax.scan rejects
            # the carry; follow the compute dtype like torch's cast of hx.
            h0_all = jnp.asarray(h0_all, x.dtype)
            c0_all = jnp.asarray(c0_all, x.dtype)
        else:
            h0_all, c0_all = jnp.asarray(hidden, x.dtype), None

        drop = nn.Dropout(rate=self.dropout) if self.dropout > 0 else None

        y = x
        h_ns, c_ns = [], []
        for layer in range(self.num_layers):
            in_size = self.input_size if layer == 0 else H * ndir
            outs = []
            for d in range(ndir):
                suffix = "_reverse" if d == 1 else ""
                p = self._layer_params(layer, suffix, in_size)
                idx = layer * ndir + d
                c0 = c0_all[idx] if c0_all is not None else None
                ys, h_n, c_n = self._scan_layer(y, h0_all[idx], c0, p,
                                                reverse=(d == 1))
                outs.append(ys)
                h_ns.append(h_n)
                if c_n is not None:
                    c_ns.append(c_n)
            y = outs[0] if ndir == 1 else jnp.concatenate(outs, axis=-1)
            if drop is not None and layer < self.num_layers - 1:
                y = drop(y, deterministic=deterministic)

        out = jnp.swapaxes(y, 0, 1) if self.batch_first else y
        h_n = jnp.stack(h_ns)
        if self._has_cell_state:
            return out, (h_n, jnp.stack(c_ns))
        return out, h_n


class LSTM(RNNBase):
    """apex/RNN/models.py — LSTM."""
    mode: str = "LSTM"


class GRU(RNNBase):
    """apex/RNN/models.py — GRU."""
    mode: str = "GRU"


class Tanh(RNNBase):
    """Vanilla tanh RNN (apex/RNN/models.py — Tanh)."""
    mode: str = "RNN_TANH"


class ReLU(RNNBase):
    """Vanilla relu RNN (apex/RNN/models.py — ReLU)."""
    mode: str = "RNN_RELU"


class mLSTM(RNNBase):
    """Multiplicative LSTM (apex/RNN/cells.py — mLSTMCell)."""
    mode: str = "MLSTM"
