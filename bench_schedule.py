"""Compile-time overlap evidence at the scheduled-HLO level.

The counterpart of bench_memory.py for the LATENCY-HIDING claims
(VERDICT round-4 missing #3): each row AOT-compiles one of the REAL
library programs for an 8-chip TPU topology (nothing executes — compile-
only devices) and reads the overlap evidence out of the scheduled HLO:

    python bench_schedule.py            # all rows
    python bench_schedule.py pipeline   # a subset

Rows:
- ``pipeline_1f1b``  — collective-permute-start/done pairs from the
  hand-scheduled 1F1B's microbatch transport, with the number of compute
  ops the scheduler placed INSIDE each in-flight window (> 0 = the
  ppermute rides under stage compute, apex's batch_isend_irecv overlap);
- ``ddp``            — the amp O2 DDP step: XLA's combiner coalesces
  every per-leaf grad psum into ONE all-reduce over the whole tuple
  (the reference's allreduce_bucket flat-bucket, compiler-built), plus
  the honest negative that this toolchain keeps all-reduce SYNC in the
  scheduled HLO (async_split=0 — recorded in BASELINE.md, not hidden);
- ``zero``           — the ZeRO skeleton's reduce-scatter/all-gather
  async pairs, if the toolchain splits them.

Run on the axon/TPU backend; the topology compiler is the TPU plugin's.
"""

from __future__ import annotations

import json
import sys

from apex_tpu.utils.schedule_report import (
    all_reduce_bucketing, collective_async_pairs, ddp_accum_step_program,
    ddp_step_program, pipeline_1f1b_program, ring_attention_program,
    scheduled_text, ulysses_attention_program, zero_update_program)


def emit(row):
    print(json.dumps(row), flush=True)


def bench_pipeline():
    fn, avals = pipeline_1f1b_program()
    txt = scheduled_text(fn, *avals)
    pairs = collective_async_pairs(txt, "collective-permute")
    overlapped = [p for p in pairs if p["compute_between"] > 0]
    emit({
        "program": "pipeline_1f1b",
        "mesh": "pipe=8", "microbatches": 16,
        "collective_permute_start_done_pairs": len(pairs),
        "pairs_with_compute_inside": len(overlapped),
        "max_compute_inside": max((p["compute_between"] for p in pairs),
                                  default=0),
        "evidence": "ppermute in flight while stage compute runs"
        if overlapped else "NO overlap found",
    })


_DDP_BASELINE = None


def _ddp_baseline():
    """The plain DDP step's bucketing, AOT-scheduled ONCE per process —
    bench_ddp and bench_ddp_accum share it (scheduling the 8-chip O2
    step twice per default run doubles the dominant compile cost for no
    extra information)."""
    global _DDP_BASELINE
    if _DDP_BASELINE is None:
        fn, avals, n_leaves = ddp_step_program()
        _DDP_BASELINE = (all_reduce_bucketing(scheduled_text(fn, *avals)),
                         n_leaves)
    return _DDP_BASELINE


def bench_ddp():
    b, n_leaves = _ddp_baseline()
    emit({
        "program": "ddp_o2_step",
        "mesh": "data=8", "grad_leaves": n_leaves,
        **b,
        "evidence": ("XLA combiner bucketed all grad leaves into "
                     f"{b['n_all_reduce_ops']} all-reduce op(s) "
                     "(apex allreduce_bucket analogue); async_split=0 is "
                     "an honest negative — this toolchain schedules "
                     "all-reduce synchronously in HLO"),
    })


def bench_ddp_accum():
    """The accumulation tentpole's acceptance leg: with accum_steps=N the
    window's grads must ride the SAME one bucketed all-reduce as the
    plain DDP step — the reduction sits after the microbatch scan, so
    allreduce count per optimizer step does NOT scale with N."""
    fn, avals, n_leaves, accum = ddp_accum_step_program(accum_steps=4)
    txt = scheduled_text(fn, *avals)
    b = all_reduce_bucketing(txt)
    base, _ = _ddp_baseline()
    per_window_ok = b["n_all_reduce_ops"] == base["n_all_reduce_ops"]
    emit({
        "program": "ddp_o2_accum_step",
        "mesh": "data=8", "accum_steps": accum, "grad_leaves": n_leaves,
        **b,
        "baseline_n_all_reduce_ops": base["n_all_reduce_ops"],
        "one_grad_psum_per_window": per_window_ok,
        "evidence": (f"accum_steps={accum} schedules "
                     f"{b['n_all_reduce_ops']} all-reduce op(s) per "
                     f"optimizer window — same as the plain DDP step "
                     f"({base['n_all_reduce_ops']}): comm bytes per "
                     f"optimizer step cut {accum}x")
        if per_window_ok else
        (f"REGRESSION: accumulation scheduled {b['n_all_reduce_ops']} "
         f"all-reduce ops vs baseline {base['n_all_reduce_ops']} — a "
         f"reduction leaked inside the microbatch scan"),
    })


def bench_zero():
    fn, avals = zero_update_program()
    txt = scheduled_text(fn, *avals)
    row = {"program": "zero_update", "mesh": "data=8"}
    for op in ("reduce-scatter", "all-gather", "collective-permute"):
        pairs = collective_async_pairs(txt, op)
        row[f"{op}_pairs"] = len(pairs)
        row[f"{op}_pairs_with_compute"] = sum(
            1 for p in pairs if p["compute_between"] > 0)
        row[f"{op}_sync_ops"] = txt.count(f" {op}(")
    emit(row)


def bench_ring():
    fn, avals = ring_attention_program()
    txt = scheduled_text(fn, *avals)
    pairs = collective_async_pairs(txt, "collective-permute")
    overlapped = [p for p in pairs if p["compute_between"] > 0]
    emit({
        "program": "ring_attention_fwd_bwd",
        "mesh": "context=8", "local_seq": 256,
        "collective_permute_start_done_pairs": len(pairs),
        "pairs_with_compute_inside": len(overlapped),
        "max_compute_inside": max((p["compute_between"] for p in pairs),
                                  default=0),
        "sync_permutes": txt.count(" collective-permute("),
        "evidence": "every KV rotation in flight under attention "
                    "compute" if pairs and len(overlapped) == len(pairs)
        else "NO async KV rotation found",
    })


def bench_ulysses():
    """Honest row: the all-to-all CP flavor. This toolchain does NOT
    async-split all-to-all in HLO — Ulysses' transport is a synchronous
    phase between attention computes (vs ring's fully-hidden
    rotations). That asymmetry is itself a scheduling argument for the
    ring layout at long sequence on this compiler generation."""
    fn, avals = ulysses_attention_program()
    txt = scheduled_text(fn, *avals)
    pairs = collective_async_pairs(txt, "all-to-all")
    emit({
        "program": "ulysses_attention_fwd_bwd",
        "mesh": "context=8", "local_seq": 256,
        "all_to_all_async_pairs": len(pairs),
        "all_to_all_sync_ops": txt.count(" all-to-all("),
        "evidence": "all-to-all stays SYNC in this toolchain's HLO — "
                    "honest negative; ring attention's ppermute "
                    "transport is the hidden one",
    })


SUITES = {"pipeline": bench_pipeline, "ddp": bench_ddp,
          "ddp_accum": bench_ddp_accum,
          "ring": bench_ring, "ulysses": bench_ulysses,
          "zero": bench_zero}


def main(argv):
    import jax

    emit({"device": str(jax.devices()[0]),
          "backend": jax.default_backend(),
          "note": "AOT topology v5e:2x4 compile-only; nothing executes"})
    bad = [n for n in argv if n not in SUITES]
    if bad:
        raise SystemExit(f"unknown suite(s) {', '.join(map(repr, bad))}; "
                         f"pick from {', '.join(sorted(SUITES))}")
    for name in (argv or list(SUITES)):
        SUITES[name]()


if __name__ == "__main__":
    # crash contract: any failure still ends in one parseable JSON
    # line ({"metric", "error", "rc": 1}) instead of a bare traceback
    from apex_tpu.telemetry import guard_bench_main
    guard_bench_main(lambda: main(sys.argv[1:]), "bench_schedule")
