"""Generate the per-symbol API reference (docs/api/*.md) from the
package's own docstrings — the docs cannot drift from the code because
they ARE the code's docstrings (VERDICT round-4: per-symbol reference at
the reference's sphinx depth; autogen sanctioned).

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python docs/gen_api.py

Checked-in output: regenerate after changing public docstrings;
tests/L0/test_docs.py asserts the pages exist and cover the public
surface.
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "api")

# page -> modules documented on it (order preserved)
PAGES = {
    "amp": ["apex_tpu.amp", "apex_tpu.amp.scaler", "apex_tpu.amp.autocast",
            "apex_tpu.fp16_utils"],
    "optimizers": ["apex_tpu.optimizers", "apex_tpu.multi_tensor_apply"],
    "normalization": ["apex_tpu.normalization"],
    "parallel": ["apex_tpu.parallel", "apex_tpu.comm"],
    "transformer": ["apex_tpu.transformer",
                    "apex_tpu.transformer.tensor_parallel",
                    "apex_tpu.transformer.pipeline_parallel",
                    "apex_tpu.transformer.functional",
                    "apex_tpu.transformer.context_parallel",
                    "apex_tpu.transformer.moe"],
    "kernels": ["apex_tpu.kernels", "apex_tpu.kernels.flash_attention",
                "apex_tpu.kernels.decode_attention",
                "apex_tpu.kernels.prefill_attention",
                "apex_tpu.kernels.layer_norm", "apex_tpu.kernels.xentropy",
                "apex_tpu.kernels.lm_head_loss",
                "apex_tpu.kernels.multi_tensor",
                "apex_tpu.kernels.group_norm", "apex_tpu.kernels.vmem"],
    "models": ["apex_tpu.models", "apex_tpu.models.bert",
               "apex_tpu.models.transformer_lm"],
    "layers": ["apex_tpu.mlp", "apex_tpu.fused_dense"],
    "utils": ["apex_tpu.utils", "apex_tpu.utils.checkpoint",
              "apex_tpu.utils.sharded_checkpoint", "apex_tpu.utils.pytree",
              "apex_tpu.utils.memory_report",
              "apex_tpu.utils.schedule_report", "apex_tpu.utils.compat",
              "apex_tpu.pyprof"],
    "telemetry": ["apex_tpu.telemetry", "apex_tpu.telemetry.sinks",
                  "apex_tpu.telemetry.summarize",
                  "apex_tpu.telemetry.tracing", "apex_tpu.log_util"],
    "serving": ["apex_tpu.serving", "apex_tpu.serving.kv_cache",
                "apex_tpu.serving.quant_common",
                "apex_tpu.serving.kv_quant",
                "apex_tpu.serving.weight_quant",
                "apex_tpu.serving.engine",
                "apex_tpu.serving.sharding",
                "apex_tpu.serving.prefix_cache",
                "apex_tpu.serving.host_tier",
                "apex_tpu.serving.speculative",
                "apex_tpu.serving.scheduler",
                "apex_tpu.serving.slo",
                "apex_tpu.serving.router",
                "apex_tpu.serving.routing_policy",
                "apex_tpu.serving.fleet",
                "apex_tpu.serving.fleet_worker",
                "apex_tpu.serving.faults",
                "apex_tpu.serving.lora"],
    "contrib": [
        "apex_tpu.contrib.bottleneck", "apex_tpu.contrib.clip_grad",
        "apex_tpu.contrib.conv_bias_relu", "apex_tpu.contrib.cudnn_gbn",
        "apex_tpu.contrib.fmha", "apex_tpu.contrib.focal_loss",
        "apex_tpu.contrib.gpu_direct_storage",
        "apex_tpu.contrib.group_norm", "apex_tpu.contrib.groupbn",
        "apex_tpu.contrib.index_mul_2d", "apex_tpu.contrib.layer_norm",
        "apex_tpu.contrib.multihead_attn",
        "apex_tpu.contrib.nccl_allocator", "apex_tpu.contrib.openfold_triton",
        "apex_tpu.contrib.optimizers", "apex_tpu.contrib.peer_memory",
        "apex_tpu.contrib.sparsity", "apex_tpu.contrib.transducer",
        "apex_tpu.contrib.xentropy",
    ],
}


def _public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    return [n for n, v in vars(mod).items()
            if not n.startswith("_")
            and getattr(v, "__module__", None) == mod.__name__]


_ADDR_RE = None


def _scrub(text: str) -> str:
    """Default-value reprs carry memory addresses (`<object object at
    0x...>`, `<function zeros at 0x...>`) — nondeterministic across
    runs, which would make the checked-in pages permanently stale."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re

        _ADDR_RE = re.compile(r" at 0x[0-9a-f]+")
    return _ADDR_RE.sub("", text)


def _sig(obj) -> str:
    try:
        return _scrub(str(inspect.signature(obj)))
    except (TypeError, ValueError):
        return "(...)"


def _doc(obj) -> str:
    d = inspect.getdoc(obj)
    return d.strip() if d else "*(no docstring)*"


def _emit_symbol(f, name, obj, level="###"):
    if inspect.isclass(obj):
        f.write(f"{level} class `{name}`\n\n")
        f.write(_doc(obj) + "\n\n")
        # flax modules: dataclass fields are the constructor surface
        fields = getattr(obj, "__dataclass_fields__", None)
        if fields:
            shown = [n for n in fields
                     if n not in ("parent", "name")
                     and not n.startswith("_")]
            if shown:
                f.write("Fields: " + ", ".join(f"`{n}`" for n in shown)
                        + "\n\n")
        for mname, m in sorted(vars(obj).items()):
            if mname.startswith("_"):
                continue
            if fields and mname in fields:
                continue   # callable dataclass-field DEFAULTS, not methods
            # unwrap descriptors so properties and class/staticmethods
            # document like plain methods (classmethod objects are not
            # callable; property docs live on fget)
            tag = ""
            if isinstance(m, property):
                m, tag = m.fget, " [property]"
            elif isinstance(m, classmethod):
                m, tag = m.__func__, " [classmethod]"
            elif isinstance(m, staticmethod):
                m, tag = m.__func__, " [staticmethod]"
            if m is None or not callable(m):
                continue
            if inspect.getdoc(m):
                sig = "" if tag == " [property]" else _sig(m)
                f.write(f"- **`.{mname}{sig}`**{tag} — "
                        + _doc(m).splitlines()[0] + "\n")
        f.write("\n")
    elif callable(obj):
        f.write(f"{level} `{name}{_sig(obj)}`\n\n")
        f.write(_doc(obj) + "\n\n")
    else:
        f.write(f"{level} `{name}` = `{_scrub(repr(obj))}`\n\n")


def gen_page(page, modules, out=None):
    path = os.path.join(out or OUT, f"{page}.md")
    with open(path, "w") as f:
        f.write(f"# API reference — {page}\n\n")
        f.write("*Generated from docstrings by `docs/gen_api.py`; "
                "do not edit by hand.*\n\n")
        for modname in modules:
            mod = importlib.import_module(modname)
            f.write(f"## `{modname}`\n\n")
            moddoc = inspect.getdoc(mod)
            if moddoc:
                f.write(moddoc.strip() + "\n\n")
            explicit = hasattr(mod, "__all__")
            for name in _public_names(mod):
                if explicit and not hasattr(mod, name):
                    # __all__ is an explicit contract: a stale/typo'd
                    # entry must fail the build, not silently ship
                    # docs with the symbol missing
                    raise SystemExit(
                        f"{modname}.__all__ lists {name!r} but the "
                        "module has no such attribute")
                obj = getattr(mod, name, None)
                if obj is None or inspect.ismodule(obj):
                    continue
                _emit_symbol(f, name, obj)
    with open(path) as f:
        n = sum(1 for _ in f)
    print(f"  {path}: {n} lines")
    return n


def main(out=None):
    out = out or OUT
    os.makedirs(out, exist_ok=True)
    total = 0
    for page, modules in PAGES.items():
        total += gen_page(page, modules, out)
    idx = os.path.join(out, "index.md")
    with open(idx, "w") as f:
        f.write("# API reference\n\nGenerated per-symbol pages "
                "(`python docs/gen_api.py`):\n\n")
        for page in PAGES:
            f.write(f"- [{page}]({page}.md)\n")
    print(f"total: {total} lines")


if __name__ == "__main__":
    main()
