"""HARDWARE-ONLY test: in-kernel dropout mask replay on a real TPU.

The CI suite (tests/conftest.py) forces the CPU backend, where the Pallas
PRNG has no lowering and flash_attention's dropout dispatches to the
jax.random fallback — so the kernel path's replay property (backward
regenerates the forward's exact hardware mask per (bh, q-block, k-block))
can only be checked on silicon. Run on a TPU-attached machine with
apex_tpu importable (installed, or repo root on sys.path):

    python -c "import sys; sys.path.insert(0, '.'); \
               exec(open('tests/tpu/test_flash_dropout_hw.py').read())"

or via pytest with a TPU backend (it self-skips on CPU; note the repo's
tests/conftest.py forces CPU, so invoke pytest from outside tests/'s
conftest scope to run it on hardware). A regression in
the replay indexing (e.g. swapping _keep_mask's qi/ki in the transposed
dkdv grid) fails this immediately while leaving the CPU suite green.
"""

import numpy as np


def _mix_seed_np(seed, b, qi, ki):
    """numpy replica of kernels.flash_attention._mix_seed."""
    x = np.uint32(seed)
    with np.errstate(over="ignore"):
        for v, c in ((b, 0x9E3779B1), (qi, 0x85EBCA77), (ki, 0xC2B2AE3D)):
            x = np.uint32((int(x) ^ int(np.uint32(v))) * c & 0xFFFFFFFF)
            x = np.uint32(int(x) ^ (int(x) >> 16))
    return np.int32(x)


def test_dropout_replay_on_hardware():
    import jax
    import jax.numpy as jnp
    import pytest

    if jax.default_backend() == "cpu":
        pytest.skip("hardware-PRNG path needs a real TPU backend")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from apex_tpu.kernels.flash_attention import flash_attention

    B, H, S, D = 1, 2, 256, 64
    BQ = BK = 128
    R, SEED = 0.3, 21
    k0 = jax.random.PRNGKey(0)
    q = jax.random.normal(k0, (B, H, S, D), jnp.float32) * 0.5
    k = jax.random.normal(jax.random.fold_in(k0, 1), (B, H, S, D),
                          jnp.float32) * 0.5
    v = jax.random.normal(jax.random.fold_in(k0, 2), (B, H, S, D),
                          jnp.float32) * 0.5

    # pin the block geometry explicitly: the mask extraction below
    # reconstructs per-(bh, qi, ki) blocks, so it must not drift when the
    # packaged tuned defaults (kernels/tuned/<kind>.json) change
    f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, dropout_rate=R, dropout_seed=jnp.int32(SEED),
        block_q=BQ, block_k=BK))

    # extract the kernel's per-block masks with the same seed derivation
    def mask_kern(seed_ref, o_ref):
        pltpu.prng_seed(seed_ref[0])
        bits = pltpu.bitcast(pltpu.prng_random_bits((BQ, BK)), jnp.uint32)
        thresh = min(int(R * 4294967296.0), 4294967295)
        o_ref[...] = (bits >= jnp.uint32(thresh)).astype(jnp.int32)

    def block_mask(mixed_seed):
        return np.asarray(pl.pallas_call(
            mask_kern,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((BQ, BK), jnp.int32),
        )(jnp.array([mixed_seed], jnp.int32))).astype(np.float64)

    nq, nk, bh = S // BQ, S // BK, B * H
    M = np.zeros((bh, S, S))
    for b in range(bh):
        for qi in range(nq):
            for ki in range(nk):
                M[b, qi * BQ:(qi + 1) * BQ, ki * BK:(ki + 1) * BK] = \
                    block_mask(_mix_seed_np(SEED, b, qi, ki))

    # analytic oracle with the extracted masks (fp64, loss = sum(o^2))
    sc = 1 / np.sqrt(D)
    qn = np.asarray(q, np.float64).reshape(bh, S, D)
    kn = np.asarray(k, np.float64).reshape(bh, S, D)
    vn = np.asarray(v, np.float64).reshape(bh, S, D)
    tri = np.tril(np.ones((S, S)))
    o_ref = np.zeros((bh, S, D))
    dq_ref = np.zeros((bh, S, D))
    dk_ref = np.zeros((bh, S, D))
    dv_ref = np.zeros((bh, S, D))
    for b in range(bh):
        s = np.where(tri > 0, qn[b] @ kn[b].T * sc, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        pd = p * M[b] / (1 - R)
        o_ref[b] = pd @ vn[b]
        do = 2 * o_ref[b]
        dv_ref[b] = pd.T @ do
        dphat = (do @ vn[b].T) * M[b] / (1 - R)
        delta = (dphat * p).sum(-1, keepdims=True)
        ds = p * (dphat - delta) * sc
        dq_ref[b] = ds @ kn[b]
        dk_ref[b] = ds.T @ qn[b]

    out = np.asarray(f(q, k, v)).reshape(bh, S, D)
    np.testing.assert_allclose(out, o_ref, atol=7e-3)

    def loss(q, k, v):
        return (f(q, k, v).astype(jnp.float32) ** 2).sum()

    gq, gk, gv = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for name, g, ref in [("dq", gq, dq_ref), ("dk", gk, dk_ref),
                         ("dv", gv, dv_ref)]:
        rel = np.abs(np.asarray(g).reshape(bh, S, D) - ref).max() \
            / (np.abs(ref).max() + 1e-9)
        assert rel < 2e-2, (name, rel)


if __name__ == "__main__":
    test_dropout_replay_on_hardware()
    print("HARDWARE DROPOUT REPLAY TEST PASSED")
