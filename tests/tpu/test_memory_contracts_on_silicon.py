"""Compiler-priced kernel memory contracts on the real backend.

VERDICT round-3 item 1: the emulator cannot price the fused kernels' wins
in time (its clock is dispatch-dominated — BASELINE.md "Honest reading"),
so the perf bar is met in the one currency this environment can certify:
**bytes, priced by XLA buffer assignment on the TPU backend**. Each test
lowers the SAME computation twice — with the Pallas kernel and with the
jnp/XLA composition — compiles both (nothing executes; abstract avals
only), and asserts the contract's analytic saving shows up in
``memory_analysis().peak_memory_in_bytes``.

The contracts are the reference's own headline claims:
- xentropy bprop-in-fprop (apex/contrib/csrc/xentropy/xentropy_kernel.cu):
  backward consumes (logits, mlse) only; no [N, V] fp32 softmax residual.
- flash attention (apex/contrib/fmha, fast_multihead_attn — fmhalib):
  no O(s^2) probability materialization, forward or residual.
- rematerialisation (checkpoint-activations recipes): trade FLOPs for
  activation memory.

The canonical contract setups live in apex_tpu/utils/memory_report.py
(shared with bench_memory.py, so the asserted and the reported contract
cannot drift). The CPU backend's ``memory_analysis`` does NOT price these
(its peak counter excludes the temp arena), which is why this tier lives
in tests/tpu; the hermetic structural halves are in
tests/L1/test_memory_contracts.py. Production-shape numbers for
BASELINE.md come from ``python bench_memory.py``.
"""

from apex_tpu.utils.memory_report import (compiled_memory, flash_contract,
                                          lm_head_contract,
                                          price_contract,
                                          remat_mlp_contract,
                                          xentropy_contract)


def test_xentropy_saves_nv_softmax_residual(tpu_backend):
    """Fused CE's backward never holds an [N, V] fp32 residual; the
    composed log_softmax path does (theory: N*V*4 bytes)."""
    n, v = 1024, 8192
    fused, composed, avals, theory = xentropy_contract(n, v)
    row = price_contract("xentropy_fwd_bwd", fused, composed, avals,
                         theory_bytes=theory)
    # measured on this backend: saved ≈ 1.45x theory (the composed path
    # also keeps masked-logit intermediates); assert the full contract
    assert row["saved_peak_bytes"] >= 0.9 * theory, row
    # and the fused overhead really is "losses + mlse"-scale, not [N, V]
    assert row["fused_overhead_bytes"] < n * v, row


def test_lm_head_fused_saves_nv_logits(tpu_backend):
    """The fused LM head+CE (kernels/lm_head_loss.py) drops the [N, V]
    fp32 logits residual the composed tail saves for backward. Priced
    at the GPT-2 tail shape the recipe actually runs (the unrolled
    chunks' scheduler liveness is a few chunk buffers, so the win only
    dominates when V >> chunk — at toy shapes with nc*chunk ~ V the
    overlap eats the saving, measured 14% at n2048/v8192/chunk1024).
    Compile-only pricing: the 2.3 GB composed peak never executes."""
    n, h, v = 8184, 768, 32768
    fused, composed, avals, theory = lm_head_contract(n, h, v)
    row = price_contract("lm_head_xentropy_fwd_bwd", fused, composed,
                         avals, theory_bytes=theory)
    assert row["saved_peak_bytes"] >= 0.9 * theory, row


def test_flash_fwd_never_materializes_s2_probabilities(tpu_backend):
    """Flash forward peak stays O(s*d); the composed softmax(qk)v peak
    carries a live [b, h, s, s] fp32 buffer.

    The fused overhead is block-working-set scale (with the round-5
    v5e tuned blocks — block_q=block_k=1024 — that is ~5 MB of fp32
    score scratch + pipeline buffers, bigger than the pre-tuning
    working set but still O(1) in s). The contract is therefore
    asserted in flash's actual regime — sequences where the s^2 buffer
    dominates the block working set (at bh=2, s=1024 the two are the
    same ~8 MB order and the ratio says nothing) — plus the
    scale-honest doubling assert: 2x the sequence must NOT grow the
    overhead the ~4x an s^2 residual would."""
    fused, composed, avals, theory = flash_contract(1, 2, 4096, 128,
                                                    with_bwd=False)
    row = price_contract("flash_fwd", fused, composed, avals,
                         theory_bytes=theory)
    assert row["saved_peak_bytes"] >= 0.9 * theory, row
    # fused live overhead well under the composed path's s^2 buffer
    assert row["fused_overhead_bytes"] < theory / 2, row

    fused2, composed2, avals2, theory2 = flash_contract(1, 2, 8192, 128,
                                                        with_bwd=False)
    row2 = price_contract("flash_fwd_s2x", fused2, composed2, avals2,
                          theory_bytes=theory2)
    assert row2["saved_peak_bytes"] >= 0.9 * theory2, row2
    # O(1)-in-s: 2x the sequence leaves the block-scale overhead roughly
    # flat (lse/segment rows grow O(s)); an s^2 residual would 4x it
    assert row2["fused_overhead_bytes"] < \
        1.5 * row["fused_overhead_bytes"] + 2 * 8192 * 8, (row, row2)


def test_flash_bwd_saves_no_s2_residual(tpu_backend):
    """Flash residuals are (q, k, v, o, lse) — O(s*d); the composed path
    saves the [b, h, s, s] fp32 probability matrix for backward."""
    fused, composed, avals, theory = flash_contract(1, 2, 1024, 128,
                                                    with_bwd=True)
    row = price_contract("flash_fwd_bwd", fused, composed, avals,
                         theory_bytes=theory)
    # measured ≈ 2.05x theory (composed also keeps masked logits)
    assert row["saved_peak_bytes"] >= 0.9 * theory, row


def test_remat_trades_flops_for_activation_memory(tpu_backend):
    """jax.checkpoint on a residual-MLP stack drops compiled peak by at
    least one [N, 4H] fp32 hidden activation per layer."""
    plain_fn, remat_fn, avals, theory = remat_mlp_contract(6, 512, 512)
    plain = compiled_memory(plain_fn, *avals)
    remat = compiled_memory(remat_fn, *avals)
    # the 0.9x bound is shape-dependent: measured 1.17x theory HERE, but
    # only 0.54x at the production shape (L12 n2048 h1024 — BASELINE.md
    # round-4 table) because XLA trims more plain-path residuals on its
    # own as shapes grow. Keep this test at (6, 512, 512) or re-derive.
    assert plain.peak_bytes - remat.peak_bytes >= 0.9 * theory, \
        (plain, remat)


def test_lm_recipe_remat_flag_saves_real_step_memory(tpu_backend):
    """The integrated row: --remat on the LM recipe's COMPLETE amp-O2
    train step (create_lm + flash + fused LN/CE + fused_adam + dynamic
    scaler) drops compiled peak by at least the per-block MLP hidden
    bound — the recipe's memory lever certified end to end, not on a
    toy stack."""
    from apex_tpu.utils.memory_report import lm_step_remat_contract

    remat_step, plain_step, avals, theory = lm_step_remat_contract(
        size="tiny", vocab=8192, seq=256, batch=8)
    remat = compiled_memory(remat_step, *avals)
    plain = compiled_memory(plain_step, *avals)
    assert plain.peak_bytes - remat.peak_bytes >= 0.9 * theory, \
        (plain, remat, theory)


def test_layer_norm_memory_efficient_drops_input_residuals(tpu_backend):
    """apex memory_efficient parity (round 5, VERDICT r4 weak #4): over
    a pre-LN stack the me variant's backward keeps the matmul-shared
    OUTPUT instead of the input, so the inter-layer x residuals die at
    the forward — peak must drop by most of the droppable theory."""
    from apex_tpu.utils.memory_report import ln_memory_efficient_contract

    me, default, avals, theory = ln_memory_efficient_contract(
        2048, 1024, n_layers=4)
    row = price_contract("ln_memory_efficient", me, default, avals,
                         theory_bytes=theory)
    assert row["saved_peak_bytes"] >= 0.5 * theory, row


def test_north_star_configs_price_and_fit_the_chip(tpu_backend):
    """Driver configs 2 and 4 at production shape (round 5): the COMPLETE
    ResNet-50 O2 DDP step (b256/chip over an 8-chip AOT topology) and the
    BERT-large seq-512 LAMB step must compile, carry their full O2 state
    (floor sanity: BERT-large LAMB state alone is >4 GB), and peak within
    the 16 GB v5e chip. Smaller shapes than bench_memory's headline rows
    to keep the gate fast; `python bench_memory.py configs` prints the
    production numbers for BASELINE.md."""
    from apex_tpu.utils.memory_report import (bert_large_lamb_step,
                                              resnet50_o2_ddp_step)

    fn, avals, floor = resnet50_o2_ddp_step(batch_per_chip=64)
    m = compiled_memory(fn, *avals)
    assert m.peak_bytes > floor > 200 * 2**20, (m.peak_bytes, floor)
    assert m.peak_bytes < 16 * 2**30

    fn, avals, floor = bert_large_lamb_step(batch=2)
    m = compiled_memory(fn, *avals)
    assert m.peak_bytes > floor > 4 * 2**30, (m.peak_bytes, floor)
    assert m.peak_bytes < 16 * 2**30
