"""On-silicon tier conftest: REQUIRES a real TPU backend.

This is the hardware gate (VERDICT round-1 item 4): `pytest tests/tpu`
runs every Pallas kernel through its actual Mosaic lowering on the chip —
the hermetic suite (tests/conftest.py forces CPU) only ever exercises
interpret mode, so a lowering regression would otherwise ship green.
Run it before every BENCH:

    pytest tests/tpu -q          # from the repo root, no env overrides

Under `pytest tests/` (CPU forced by the parent conftest) every test here
self-skips, keeping the hermetic suite hermetic.
"""

import os

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() == "cpu":
        if os.environ.get("APEX_TPU_SILICON"):
            # the explicit opt-in means "this MUST run on silicon" — a CPU
            # backend here is a broken invocation/host, never a quiet skip
            raise RuntimeError(
                "APEX_TPU_SILICON is set but the jax backend is cpu — the "
                "on-silicon tier would silently not test the hardware")
        skip = pytest.mark.skip(
            reason="on-silicon tier: needs a real TPU backend (run as "
                   "`pytest tests/tpu`, or APEX_TPU_SILICON=1 for "
                   "xdist/option-heavy invocations)")
        for item in items:
            if "tests/tpu" in str(item.fspath).replace("\\", "/"):
                item.add_marker(skip)


@pytest.fixture(scope="session")
def tpu_backend():
    assert jax.default_backend() != "cpu"
    return jax.devices()[0]
