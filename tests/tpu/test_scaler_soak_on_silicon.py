"""The scaler-dynamics soak through the REAL Mosaic lowerings (VERDICT
round-4 weak #7, silicon half): same driver as
tests/L1/test_scaler_soak.py — fp16 LM train step, small scale_window,
overflow→hysteresis-backoff→regrow cycle checked step-for-step against
the independent automaton, plus one mid-dynamics bitwise resume — but on
the chip, where the fused kernels run their actual TPU lowerings rather
than interpret mode and fp16 overflow behavior is the hardware's own.
Shorter horizon than the hermetic run (the emulator clock is slow); the
cycle still completes several times at window 5.
"""

import importlib.util
import os

import jax
import numpy as np

_SOAK = os.path.join(os.path.dirname(__file__), os.pardir, "L1",
                     "test_scaler_soak.py")
_spec = importlib.util.spec_from_file_location("_scaler_soak", _SOAK)
_soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_soak)


def test_scaler_cycle_on_silicon(tpu_backend, tmp_path):
    window, hysteresis, n = 5, 2, 120
    trace, state, resumed = _soak.run_soak(n, window, hysteresis,
                                           ckpt_at=60, tmp_path=tmp_path)
    _soak.assert_soak_dynamics(trace, window, hysteresis,
                               min_overflows=2, min_growths=6)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
