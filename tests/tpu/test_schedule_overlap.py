"""Compiler-certified overlap evidence on the TPU toolchain (VERDICT
round-4 missing #3).

These tests AOT-compile the REAL library programs for an 8-chip v5e
topology (compile-only devices; nothing executes, so they run fine on
the single attached chip) and assert the latency-hiding claims straight
off the scheduled HLO — the same move that made the memory contracts
compiler-certified instead of docstring-asserted:

- the 1F1B schedule's ppermute transport is split into
  collective-permute-start/done pairs with stage COMPUTE scheduled
  inside the in-flight window (apex's batch_isend_irecv overlap,
  schedules.py's claim);
- the DDP step's per-leaf grad psums are COMBINED into one all-reduce
  over the whole tuple (apex allreduce_bucket, distributed.py's claim) —
  plus the honest negative, pinned so it can't silently rot: this
  toolchain does NOT async-split all-reduce in HLO.

bench_schedule.py prints the same readings as JSON for BASELINE.md.
"""

import jax
import numpy as np
import pytest

from apex_tpu.utils.schedule_report import (
    all_reduce_bucketing, collective_async_pairs, ddp_accum_step_program,
    ddp_step_program, pipeline_1f1b_program, ring_attention_program,
    scheduled_text, ulysses_attention_program, zero_update_program)


@pytest.fixture(scope="module")
def pipeline_txt():
    fn, avals = pipeline_1f1b_program()
    return scheduled_text(fn, *avals)


def test_1f1b_ppermute_is_async_with_compute_inside(pipeline_txt):
    pairs = collective_async_pairs(pipeline_txt, "collective-permute")
    # the scan body rotates activations forward and counter-rotates
    # cotangents backward: two transports per tick, both must be split
    assert len(pairs) >= 2, pairs
    overlapped = [p for p in pairs if p["compute_between"] > 0]
    assert len(overlapped) == len(pairs), \
        f"ppermute NOT hidden under compute: {pairs}"
    # and no synchronous (unsplit) permute remains
    assert " collective-permute(" not in pipeline_txt


@pytest.fixture(scope="module")
def ddp_baseline():
    """(bucketing, n_leaves) of the plain DDP step — scheduled once,
    shared by the bucketing test and the accumulation-window test."""
    fn, avals, n_leaves = ddp_step_program()
    return all_reduce_bucketing(scheduled_text(fn, *avals)), n_leaves


def test_ddp_grad_psums_bucketed_into_one_allreduce(ddp_baseline):
    b, n_leaves = ddp_baseline
    # every grad leaf rides ONE combined all-reduce (the other ops are
    # scalar reductions: loss pmean / found_inf)
    assert max(b["tensors_per_op"]) == n_leaves, b
    assert b["n_all_reduce_ops"] <= 2, b
    # honest negative, pinned: this toolchain keeps all-reduce sync in
    # HLO. If a toolchain bump starts splitting it, this assert flips and
    # BASELINE.md's overlap table must be re-run (a good problem).
    assert b["async_split"] == 0, \
        "toolchain now async-splits all-reduce — update BASELINE.md"


def test_accum_window_schedules_one_grad_allreduce(ddp_baseline):
    """The accumulation tentpole's scheduled-HLO certificate: with
    accum_steps=N the whole-tree grad psum sits AFTER the microbatch
    scan — the compiled window schedules exactly as many all-reduce ops
    as the plain DDP step (one bucketed grad op + scalar reductions),
    never N of them."""
    fn, avals, n_leaves, accum = ddp_accum_step_program(accum_steps=4)
    txt = scheduled_text(fn, *avals)
    b = all_reduce_bucketing(txt)
    base, _ = ddp_baseline
    assert b["n_all_reduce_ops"] == base["n_all_reduce_ops"], (b, base)
    # the grad tuple still rides one combined op, full leaf count
    assert max(b["tensors_per_op"]) == n_leaves, b


def test_ring_attention_rotations_hidden_under_compute():
    """The long-context tier's core claim: ring attention's KV-block
    rotations (fwd ring + bwd counter-ring) are ALL async-split with
    attention compute scheduled inside every in-flight window — the
    transport is free when compute per chunk dominates."""
    fn, avals = ring_attention_program()
    txt = scheduled_text(fn, *avals)
    pairs = collective_async_pairs(txt, "collective-permute")
    assert len(pairs) >= 4, pairs          # fwd + bwd rotations
    not_hidden = [p for p in pairs if p["compute_between"] == 0]
    assert not not_hidden, f"rotations NOT hidden: {not_hidden}"
    assert " collective-permute(" not in txt   # zero sync permutes


def test_ulysses_all_to_all_sync_pinned():
    """Honest negative, pinned: this toolchain keeps all-to-all
    synchronous in scheduled HLO (8 sync ops in the Ulysses fwd+bwd,
    zero async pairs). If a toolchain bump starts splitting it, this
    flips and BASELINE.md's overlap table gets a better row."""
    fn, avals = ulysses_attention_program()
    txt = scheduled_text(fn, *avals)
    assert txt.count(" all-to-all(") >= 4
    assert not collective_async_pairs(txt, "all-to-all"),         "toolchain now async-splits all-to-all — update BASELINE.md"


def test_zero_collectives_compile_at_schedule_level():
    fn, avals = zero_update_program()
    txt = scheduled_text(fn, *avals)
    # the ZeRO skeleton lowers to real reduce-scatter/all-gather ops
    # (sync on this toolchain — recorded, same caveat as the all-reduce)
    assert txt.count("reduce-scatter(") >= 4
    assert txt.count("all-gather(") >= 4


def test_pair_parser_on_canned_schedule():
    """The pair matcher itself: tuple-typed results, dotted var names,
    compute counted only strictly inside the window, computation
    boundaries closing unmatched starts."""
    txt = "\n".join([
        "HloModule m, is_scheduled=true",
        "%body (p: f32[8]) -> f32[8] {",
        "  %cps.1 = (f32[8], f32[8], u32[], u32[]) "
        "collective-permute-start(%p), source_target_pairs={{0,1}}",
        "  %fusion.9 = f32[8] fusion(%p), kind=kLoop, calls=%fc",
        "  %tuple.0 = (f32[8]) tuple(%fusion.9)",
        "  ROOT %done.1 = f32[8] collective-permute-done(%cps.1)",
        "}",
        "ENTRY %main () -> f32[8] {",
        "  %cps.2 = (f32[8], f32[8], u32[], u32[]) "
        "collective-permute-start(%x)",
        "}",  # unmatched start dies at the boundary
    ])
    pairs = collective_async_pairs(txt, "collective-permute")
    assert len(pairs) == 1
    assert pairs[0]["compute_between"] == 1      # the fusion, not the tuple
    assert pairs[0]["ops_between"] == 2
