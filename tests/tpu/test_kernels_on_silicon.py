"""Mosaic-lowering parity tier: every Pallas kernel vs its fp32 jnp oracle
ON THE REAL CHIP (VERDICT round-1 item 4 / SURVEY §5.4 inverse).

The hermetic suite runs these kernels in interpret mode only; this tier is
the proof the compiled Mosaic code computes the same numbers. Tolerances
follow the reference's L0 kernel tests (fp32 tight, bf16 ~1e-2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _close(a, b, tol, atol=None):
    # On silicon, fp32 matmuls run through the MXU at default precision
    # (bf16 passes), so near-zero outputs show large RELATIVE error while
    # absolute error stays at bf16-epsilon scale — compare atol-dominant.
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=tol, atol=tol if atol is None else atol)


# ------------------------------------------------------------ layer norm
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_layer_norm_fwd_bwd(tpu_backend, dtype, tol):
    from apex_tpu.kernels.layer_norm import layer_norm, layer_norm_reference

    n, h = 256, 512
    x = jax.random.normal(jax.random.PRNGKey(0), (n, h), dtype) * 2.0
    w = jax.random.normal(jax.random.PRNGKey(1), (h,), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (h,), jnp.float32)

    _close(jax.jit(layer_norm)(x, w, b),
           layer_norm_reference(x, w, b), tol)

    def loss_k(x, w, b):
        return jnp.sum(jnp.square(layer_norm(x, w, b)))

    def loss_r(x, w, b):
        return jnp.sum(jnp.square(layer_norm_reference(
            jnp.asarray(x, jnp.float32), w, b)))

    gk = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2)))(
        x.astype(jnp.float32), w, b)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x.astype(jnp.float32), w, b)
    for a, r in zip(gk, gr):
        _close(a, r, 1e-3)


def test_rms_norm(tpu_backend):
    from apex_tpu.kernels.layer_norm import rms_norm, rms_norm_reference

    x = jax.random.normal(jax.random.PRNGKey(3), (128, 384), jnp.float32)
    w = jnp.ones((384,)) * 1.5
    _close(jax.jit(rms_norm)(x, w), rms_norm_reference(x, w), 2e-5)


# ------------------------------------------------------------- xentropy
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_fwd_bwd(tpu_backend, smoothing):
    from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                           xent_reference)

    n, v = 128, 1024
    logits = jax.random.normal(jax.random.PRNGKey(4), (n, v),
                               jnp.float32) * 4.0
    labels = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, v)

    _close(jax.jit(lambda l: softmax_cross_entropy_loss(
        l, labels, smoothing=smoothing))(logits),
        xent_reference(logits, labels, smoothing), 1e-5)

    gk = jax.jit(jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(
        l, labels, smoothing=smoothing))))(logits)
    gr = jax.grad(lambda l: jnp.sum(xent_reference(
        l, labels, smoothing)))(logits)
    # compiled exp/sum reassociation differs from the composed oracle at
    # ~1e-4 relative on the smallest softmax entries
    _close(gk, gr, 5e-4, atol=1e-5)


# -------------------------------------------------------- multi-tensor
def test_multi_tensor_ops(tpu_backend):
    from apex_tpu.kernels.multi_tensor import (fused_adam_step, fused_axpby,
                                               fused_l2norm, fused_scale)

    n = 8192
    x = jax.random.normal(jax.random.PRNGKey(6), (n,), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(7), (n,), jnp.float32)

    out, inf = jax.jit(fused_scale)(x, 0.5)
    _close(out, x * 0.5, 1e-6)
    assert not bool(inf)

    ax, inf = jax.jit(fused_axpby)(x, y, 2.0, -1.0)
    _close(ax, 2.0 * x - y, 1e-6)

    _close(jax.jit(fused_l2norm)(x), jnp.sqrt(jnp.sum(x * x)), 1e-5)

    # inf detection must survive lowering
    bad = x.at[17].set(jnp.inf)
    _, inf = jax.jit(fused_scale)(bad, 1.0)
    assert bool(inf)

    # one adam step vs the composed update
    m = jnp.zeros((n,))
    v = jnp.zeros((n,))
    p2, m2, v2 = jax.jit(lambda p, m, v, g: fused_adam_step(
        p, m, v, g, lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8,
        weight_decay=0.0, step=1, adam_w_mode=True))(x, m, v, y)
    m_ref = 0.1 * y
    v_ref = 0.001 * y * y
    update = (m_ref / 0.1) / (jnp.sqrt(v_ref / 0.001) + 1e-8)
    _close(p2, x - 1e-2 * update, 1e-5)
    _close(m2, m_ref, 1e-4, atol=1e-6)
    _close(v2, v_ref, 1e-4, atol=1e-6)


# ------------------------------------------------------ flash attention
@pytest.mark.parametrize("case", ["plain", "causal", "segments", "bias"])
def test_flash_attention_fwd_bwd(tpu_backend, case):
    from apex_tpu.kernels.flash_attention import (flash_attention,
                                                  mha_reference)

    b, h, s, d = 2, 4, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(8), 4)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.float32)
    kw = {"scale": d ** -0.5}
    if case == "causal":
        kw["causal"] = True
    elif case == "segments":
        kw["segment_ids"] = jnp.concatenate(
            [jnp.zeros((b, s // 2), jnp.int32),
             jnp.ones((b, s - s // 2), jnp.int32)], axis=1)
    elif case == "bias":
        kw["bias"] = jax.random.normal(ks[3], (b, 1, s, s),
                                       jnp.float32) * 0.5

    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, **kw))(q, k, v)
    ref = mha_reference(q, k, v, **kw)
    _close(out, ref, 2e-2)  # MXU default-precision scale (see _close)

    def lk(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, **kw)))

    def lr(q, k, v):
        return jnp.sum(jnp.square(mha_reference(q, k, v, **kw)))

    gk = jax.jit(jax.grad(lk, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gk, gr):
        _close(a, r, 2e-2, atol=1e-1)  # grad magnitudes are O(seq)
    if case == "bias":
        gbk = jax.jit(jax.grad(
            lambda bb: jnp.sum(jnp.square(flash_attention(
                q, k, v, scale=d ** -0.5, bias=bb)))))(kw["bias"])
        gbr = jax.grad(
            lambda bb: jnp.sum(jnp.square(mha_reference(
                q, k, v, scale=d ** -0.5, bias=bb))))(kw["bias"])
        _close(gbk, gbr, 2e-2, atol=1e-1)


def test_flash_attention_bf16(tpu_backend):
    from apex_tpu.kernels.flash_attention import (flash_attention,
                                                  mha_reference)

    b, h, s, d = 1, 2, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
               for kk in ks)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
        q, k, v)
    assert out.dtype == jnp.bfloat16
    # flash_attention defaults scale to 1/sqrt(d); mha_reference to 1.0
    _close(out, mha_reference(q, k, v, causal=True, scale=d ** -0.5), 5e-2)


# ------------------------------------------------------ causal softmax
def test_causal_softmax(tpu_backend):
    from apex_tpu.kernels.causal_softmax import (causal_softmax,
                                                 causal_softmax_reference)

    x = jax.random.normal(jax.random.PRNGKey(10), (4, 256, 256),
                          jnp.float32) * 3.0
    _close(jax.jit(lambda x: causal_softmax(x, 0.5))(x),
           causal_softmax_reference(x, 0.5), 1e-5)
    gk = jax.jit(jax.grad(lambda x: jnp.sum(jnp.sin(
        causal_softmax(x) * 3))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(
        causal_softmax_reference(x) * 3)))(x)
    _close(gk, gr, 1e-4)


# ------------------------------------------------- tuned block overrides
def test_tuned_override_lowers_and_matches(tpu_backend):
    """A bench_kernels --sweep override (non-default block) must lower on
    silicon and keep oracle parity — the 'only ever slower, never broken'
    contract behind APEX_TPU_TUNED."""
    from apex_tpu.kernels import vmem
    from apex_tpu.kernels.layer_norm import layer_norm, layer_norm_reference

    prev = vmem.overrides().get("layer_norm.block_rows")
    try:
        vmem.set_override("layer_norm.block_rows", 32)
        x = jax.random.normal(jax.random.PRNGKey(20), (512, 1024))
        w, b = jnp.ones((1024,)) * 1.1, jnp.zeros((1024,)) + 0.1
        _close(jax.jit(layer_norm)(x, w, b),
               layer_norm_reference(x, w, b), 1e-5)
    finally:
        # restore only OUR key — an APEX_TPU_TUNED registry loaded for
        # the whole gate run must survive this test
        if prev is None:
            vmem.remove_override("layer_norm.block_rows")
        else:
            vmem.set_override("layer_norm.block_rows", prev)


# ------------------------------------------------------ masked softmax
def test_masked_softmax(tpu_backend):
    """N8's arbitrary-mask kernel (round 3): compiled Mosaic lowering vs
    the fp32 oracle, incl. the [b, 1, sq, sk] head-broadcast mask."""
    from apex_tpu.kernels.masked_softmax import (masked_softmax,
                                                 masked_softmax_reference)

    b, h, sq, sk = 2, 4, 128, 256
    x = jax.random.normal(jax.random.PRNGKey(11), (b, h, sq, sk),
                          jnp.float32) * 3.0
    m = jax.random.bernoulli(jax.random.PRNGKey(12), 0.3,
                             (b, 1, sq, sk)).at[..., 0].set(False)
    _close(jax.jit(lambda x: masked_softmax(x, m, 0.5))(x),
           masked_softmax_reference(x, m, 0.5), 1e-5)
    gk = jax.jit(jax.grad(lambda x: jnp.sum(jnp.sin(
        masked_softmax(x, m) * 3))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(
        masked_softmax_reference(x, m) * 3)))(x)
    _close(gk, gr, 1e-4)


# ---------------------------------------------------------- group norm
@pytest.mark.parametrize("act", [None, "silu"])
def test_group_norm_fwd_bwd(tpu_backend, act):
    from apex_tpu.kernels.group_norm import (group_norm_nhwc,
                                             group_norm_reference)

    x = jax.random.normal(jax.random.PRNGKey(11), (2, 16, 16, 256),
                          jnp.float32) * 2.0
    g = jax.random.normal(jax.random.PRNGKey(12), (256,)) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(13), (256,))

    out = jax.jit(lambda x: group_norm_nhwc(x, 16, g, b, act=act))(x)
    ref = group_norm_reference(x, 16, g, b, act=act)
    _close(out, ref, 1e-4, atol=1e-4)

    gk = jax.jit(jax.grad(lambda x, g, b: jnp.sum(jnp.sin(
        group_norm_nhwc(x, 16, g, b, act=act) * 2)), argnums=(0, 1, 2)))(
        x, g, b)
    gr = jax.grad(lambda x, g, b: jnp.sum(jnp.sin(
        group_norm_reference(x, 16, g, b, act=act) * 2)),
        argnums=(0, 1, 2))(x, g, b)
    for a, r in zip(gk, gr):
        _close(a, r, 1e-3, atol=1e-3)


def test_fp16_inputs_take_the_xla_fallback(tpu_backend):
    """TPU Mosaic has no fp16: every public fused op must detect float16
    operands and route to its jnp fallback (where XLA upconverts) instead
    of crashing the remote compile — found by the on-silicon scaler soak.
    bf16 stays on the Pallas path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.kernels import fused_scale, layer_norm, rms_norm
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    from apex_tpu.kernels.flash_attention import flash_attention
    from apex_tpu.kernels.group_norm import group_norm_nhwc

    x16 = jnp.ones((8, 256), jnp.float16)
    g = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    assert layer_norm(x16, g, b).dtype == jnp.float16
    assert rms_norm(x16, g).dtype == jnp.float16
    out, found = fused_scale(jnp.ones((300,), jnp.float16), 2.0)
    assert not bool(found) and float(out[0]) == 2.0
    lg = jnp.ones((8, 128), jnp.float16)
    assert np.isfinite(float(softmax_cross_entropy_loss(
        lg, jnp.zeros((8,), jnp.int32)).mean()))
    q = jnp.ones((1, 2, 128, 64), jnp.float16)
    assert jnp.all(jnp.isfinite(jnp.asarray(
        flash_attention(q, q, q, causal=True), jnp.float32)))
    xg = jnp.ones((2, 4, 4, 128), jnp.float16)
    y = group_norm_nhwc(xg, 4, jnp.ones((128,)), jnp.zeros((128,)))
    assert jnp.all(jnp.isfinite(jnp.asarray(y, jnp.float32)))
    # grads flow through the fallbacks too
    dx = jax.grad(lambda x: jnp.sum(jnp.asarray(
        layer_norm(x, g, b), jnp.float32)))(x16)
    assert dx.dtype == jnp.float16
