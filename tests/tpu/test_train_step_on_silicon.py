"""Full amp train step on the real chip: the kernel tier proves each
Pallas lowering; this proves the COMPOSED benchmark-shaped step (policy
casts + fused optimizer + scaler cond + BN state) compiles and executes
on silicon end-to-end — the single-chip slice of the bench.py workload."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_resnet_train_step(tpu_backend, opt_level):
    from apex_tpu import amp
    from apex_tpu.models import create_model

    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic",
                                verbose=False)
    model = create_model("resnet18", num_classes=10,
                         dtype=policy.model_dtype)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(p, ms, batch):
        images, labels = batch
        logits, updated = model.apply({"params": p, **ms}, images,
                                      train=True, mutable=list(ms.keys()))
        loss = optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), labels).mean()
        return loss, updated

    init_fn, step_fn = amp.make_train_step(
        loss_fn, optax.sgd(0.01, momentum=0.9), policy,
        with_model_state=True)
    state = init_fn(params, mstate)
    jit_step = jax.jit(step_fn)
    labels = jnp.zeros((4,), jnp.int32)
    losses = []
    for _ in range(3):
        state, metrics = jit_step(state, (x, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[2] < losses[0]      # it actually learns the fixed batch
    assert not bool(metrics["found_inf"])


def test_lm_train_step_with_fused_xentropy(tpu_backend):
    from apex_tpu import amp
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models import create_lm

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    model = create_lm("tiny", vocab_size=128, max_seq_len=32,
                      dtype=policy.model_dtype)
    tokens = jnp.ones((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch, train=False)
        return softmax_cross_entropy_loss(
            logits[:, :-1].reshape(-1, 128),
            batch[:, 1:].reshape(-1)).mean()

    from apex_tpu.optimizers.fused_adam import fused_adam
    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    l0 = None
    for _ in range(3):
        state, metrics = jit_step(state, tokens)
        l0 = l0 if l0 is not None else float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < l0   # flash + xentropy + fused adam
