"""Full amp train step on the real chip: the kernel tier proves each
Pallas lowering; this proves the COMPOSED benchmark-shaped step (policy
casts + fused optimizer + scaler cond + BN state) compiles and executes
on silicon end-to-end — the single-chip slice of the bench.py workload."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_resnet_train_step(tpu_backend, opt_level):
    from apex_tpu import amp
    from apex_tpu.models import create_model

    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic",
                                verbose=False)
    model = create_model("resnet18", num_classes=10,
                         dtype=policy.model_dtype)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(p, ms, batch):
        images, labels = batch
        logits, updated = model.apply({"params": p, **ms}, images,
                                      train=True, mutable=list(ms.keys()))
        loss = optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), labels).mean()
        return loss, updated

    init_fn, step_fn = amp.make_train_step(
        loss_fn, optax.sgd(0.01, momentum=0.9), policy,
        with_model_state=True)
    state = init_fn(params, mstate)
    jit_step = jax.jit(step_fn)
    labels = jnp.zeros((4,), jnp.int32)
    losses = []
    for _ in range(3):
        state, metrics = jit_step(state, (x, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[2] < losses[0]      # it actually learns the fixed batch
    assert not bool(metrics["found_inf"])


def test_lm_train_step_with_fused_xentropy(tpu_backend):
    from apex_tpu import amp
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models import create_lm

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    model = create_lm("tiny", vocab_size=128, max_seq_len=32,
                      dtype=policy.model_dtype)
    tokens = jnp.ones((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch, train=False)
        return softmax_cross_entropy_loss(
            logits[:, :-1].reshape(-1, 128),
            batch[:, 1:].reshape(-1)).mean()

    from apex_tpu.optimizers.fused_adam import fused_adam
    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    l0 = None
    for _ in range(3):
        state, metrics = jit_step(state, tokens)
        l0 = l0 if l0 is not None else float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < l0   # flash + xentropy + fused adam


def test_lm_train_step_with_fused_head(tpu_backend):
    """The --fused-head tail on silicon: features_only hidden states into
    kernels/lm_head_loss.py's chunked online-logsumexp against the tied
    embedding, composed with amp O2 masters + dynamic scaler + fused
    adam — the recipe's fused single-chip step end-to-end on hardware."""
    from apex_tpu import amp
    from apex_tpu.amp.autocast import resolve_dtype
    from apex_tpu.kernels.lm_head_loss import lm_head_xentropy
    from apex_tpu.models import create_lm
    from apex_tpu.optimizers.fused_adam import fused_adam

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    model = create_lm("tiny", vocab_size=128, max_seq_len=32,
                      dtype=policy.model_dtype)
    tokens = jnp.ones((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
    hd = resolve_dtype(policy.model_dtype, "linear", jnp.float32)

    def loss_fn(p, batch):
        hidden = model.apply({"params": p}, batch[:, :-1], train=False,
                             features_only=True)
        return lm_head_xentropy(hidden, p["wte"]["embedding"],
                                batch[:, 1:], compute_dtype=hd).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    l0 = None
    for _ in range(3):
        state, metrics = jit_step(state, tokens)
        l0 = l0 if l0 is not None else float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < l0


def test_bert_lamb_train_step(tpu_backend):
    """VERDICT round-2 weak #7: the BERT-LAMB step on chip — FusedLAMB's
    l2norm + trust-ratio multi_tensor path lowered and composed with amp
    O2 master weights + dynamic scaler (the config-4 workload's step)."""
    from apex_tpu import amp
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.bert import BertForPreTraining, create_bert
    from apex_tpu.optimizers import fused_lamb

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    cfg = create_bert("tiny", vocab_size=512, max_position_embeddings=64)
    model = BertForPreTraining(cfg, dtype=policy.model_dtype)

    b, s, npred = 2, 64, 8
    rng = jax.random.PRNGKey(0)
    ks = jax.random.split(rng, 6)
    input_ids = jax.random.randint(ks[0], (b, s), 0, cfg.vocab_size)
    token_type = jnp.zeros((b, s), jnp.int32)
    attn_mask = jnp.ones((b, s), jnp.int32)
    mlm_pos = jax.random.randint(ks[1], (b, npred), 0, s)
    mlm_ids = jax.random.randint(ks[2], (b, npred), 1, cfg.vocab_size)
    nsp_labels = jnp.zeros((b,), jnp.int32)
    params = model.init(rng, input_ids, token_type, attn_mask, mlm_pos,
                        train=False)["params"]

    def loss_fn(p, batch):
        ii, tt, am, mp, mi, nl = batch
        mlm_logits, nsp_logits = model.apply(
            {"params": p}, ii, tt, am, mp, train=False)
        mlm = softmax_cross_entropy_loss(mlm_logits, mi).mean()
        nsp = softmax_cross_entropy_loss(nsp_logits, nl).mean()
        return mlm + nsp

    init_fn, step_fn = amp.make_train_step(
        loss_fn, fused_lamb(1e-3, weight_decay=0.01), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    batch = (input_ids, token_type, attn_mask, mlm_pos, mlm_ids, nsp_labels)
    losses = []
    for _ in range(3):
        state, metrics = jit_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[2] < losses[0]
    assert not bool(metrics["found_inf"])


@pytest.mark.parametrize("include_norm_add", [False, True])
def test_contrib_fused_mha_fwd_bwd(tpu_backend, include_norm_add):
    """VERDICT round-2 weak #7: the contrib fused-MHA module path on chip —
    impl='fast' (flash kernel) forward AND backward vs the impl='default'
    explicit-probs composition on the same params."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    S, B, E, H = 128, 2, 256, 4          # d_head 64, flash-aligned
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E)) * 0.5
    m_fast = SelfMultiheadAttn(embed_dim=E, num_heads=H, impl="fast",
                               include_norm_add=include_norm_add)
    m_def = SelfMultiheadAttn(embed_dim=E, num_heads=H, impl="default",
                              include_norm_add=include_norm_add)
    variables = m_fast.init(jax.random.PRNGKey(1), x, is_training=False)

    out_fast = jax.jit(
        lambda v, x: m_fast.apply(v, x, is_training=False))(variables, x)
    out_def = m_def.apply(variables, x, is_training=False)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_def),
                               rtol=2e-2, atol=2e-2)

    def loss_fast(v):
        return jnp.sum(m_fast.apply(v, x, is_training=False) ** 2)

    def loss_def(v):
        return jnp.sum(m_def.apply(v, x, is_training=False) ** 2)

    g_fast = jax.jit(jax.grad(loss_fast))(variables)
    g_def = jax.grad(loss_def)(variables)
    for a, b_ in zip(jax.tree_util.tree_leaves(g_fast),
                     jax.tree_util.tree_leaves(g_def)):
        a, b_ = np.asarray(a), np.asarray(b_)
        # silicon MXU runs fp32 matmuls in bf16 passes: tolerances must be
        # atol-dominant and scale-aware (deviation ≤1% of the leaf's max
        # grad magnitude — measured 0.3% for the norm_add path)
        np.testing.assert_allclose(
            a, b_, rtol=5e-2, atol=1e-2 * max(1.0, np.abs(b_).max()))


def test_contrib_encdec_mha_on_chip(tpu_backend):
    """Encoder-decoder (cross) attention fused path on chip, fast vs
    default composition, fwd + bwd."""
    from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn

    Sq, Skv, B, E, H = 128, 256, 2, 256, 4
    q = jax.random.normal(jax.random.PRNGKey(0), (Sq, B, E)) * 0.5
    kv = jax.random.normal(jax.random.PRNGKey(1), (Skv, B, E)) * 0.5
    m_fast = EncdecMultiheadAttn(embed_dim=E, num_heads=H, impl="fast")
    m_def = EncdecMultiheadAttn(embed_dim=E, num_heads=H, impl="default")
    variables = m_fast.init(jax.random.PRNGKey(2), q, kv)

    out_fast = jax.jit(lambda v: m_fast.apply(v, q, kv,
                                              is_training=False))(variables)
    out_def = m_def.apply(variables, q, kv, is_training=False)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_def),
                               rtol=2e-2, atol=2e-2)

    g_fast = jax.jit(jax.grad(lambda v: jnp.sum(
        m_fast.apply(v, q, kv, is_training=False) ** 2)))(variables)
    g_def = jax.grad(lambda v: jnp.sum(
        m_def.apply(v, q, kv, is_training=False) ** 2))(variables)
    for a, b_ in zip(jax.tree_util.tree_leaves(g_fast),
                     jax.tree_util.tree_leaves(g_def)):
        a, b_ = np.asarray(a), np.asarray(b_)
        np.testing.assert_allclose(
            a, b_, rtol=5e-2, atol=1e-2 * max(1.0, np.abs(b_).max()))


def test_transducer_loss_on_chip(tpu_backend):
    """VERDICT round-2 weak #7: the transducer wavefront scan executes on
    chip and matches a brute-force numpy alpha-recursion oracle."""
    from apex_tpu.contrib.transducer import transducer_loss

    b, t, u, v = 2, 6, 4, 8
    rng = jax.random.PRNGKey(3)
    logits = jax.random.normal(rng, (b, t, u + 1, v))
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    labels = jax.random.randint(jax.random.PRNGKey(4), (b, u), 1, v)
    f_len = jnp.array([t, t - 1], jnp.int32)
    y_len = jnp.array([u, u - 2], jnp.int32)

    loss = jax.jit(transducer_loss)(log_probs, labels, f_len, y_len)

    # numpy brute-force alpha recursion per sample
    lp = np.asarray(log_probs, np.float64)
    lab = np.asarray(labels)
    expected = []
    for i in range(b):
        T, U = int(f_len[i]), int(y_len[i])
        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for ti in range(T):
            for ui in range(U + 1):
                if ti > 0:
                    alpha[ti, ui] = np.logaddexp(
                        alpha[ti, ui], alpha[ti - 1, ui]
                        + lp[i, ti - 1, ui, 0])
                if ui > 0:
                    alpha[ti, ui] = np.logaddexp(
                        alpha[ti, ui], alpha[ti, ui - 1]
                        + lp[i, ti, ui - 1, lab[i, ui - 1]])
        expected.append(-(alpha[T - 1, U] + lp[i, T - 1, U, 0]))
    np.testing.assert_allclose(np.asarray(loss), expected, rtol=1e-4)

    # gradients lower and are finite on chip
    g = jax.jit(jax.grad(
        lambda lpx: transducer_loss(lpx, labels, f_len, y_len).sum()))(
        log_probs)
    assert bool(jnp.all(jnp.isfinite(g)))


def test_save_resume_bitwise_on_chip(tpu_backend, tmp_path):
    """Checkpoint round-trip of REAL device arrays (bf16 masters-on-chip
    included: npz stores them as fp32 and the restore must cast back
    bit-faithfully on the TPU backend): an interrupted O2 LM run resumed
    from disk reproduces the uninterrupted trajectory bitwise."""
    import os

    from apex_tpu import amp
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.transformer_lm import create_lm
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.utils.checkpoint import (load_checkpoint,
                                           save_checkpoint)

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    model = create_lm("tiny", vocab_size=256, max_seq_len=64,
                      dtype=policy.model_dtype)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 64), jnp.int32),
                        train=False)["params"]

    def loss_fn(p, tokens):
        logits = model.apply({"params": p}, tokens[:, :-1], train=True)
        return softmax_cross_entropy_loss(logits, tokens[:, 1:]).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3),
                                           policy)
    jit_step = jax.jit(step_fn)

    def batch(i):
        return jax.random.randint(jax.random.PRNGKey(i), (4, 65), 0, 256)

    full = init_fn(params)
    for i in range(4):
        full, m_full = jit_step(full, batch(i))

    half = init_fn(params)
    for i in range(2):
        half, _ = jit_step(half, batch(i))
    path = os.path.join(tmp_path, "chip.npz")
    save_checkpoint(path, half, step=2)
    resumed, step, _ = load_checkpoint(path, init_fn(params))
    assert step == 2
    for i in range(2, 4):
        resumed, m_res = jit_step(resumed, batch(i))

    assert float(m_res["loss"]) == float(m_full["loss"])
    for a, b in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
