"""On-silicon coverage for the remaining hot paths (VERDICT r3 weak #5):
ring-attention chunk kernels (the long-context recipe's compute), the
Ulysses all-to-all path, fused_dense/MLP modules, the NovoGrad/Adagrad
fused functors, and the detection recipe's SyncBN train step.

Single-chip strategy: CP/collective paths run inside a 1-device mesh —
the collectives are degenerate but every Pallas kernel they wrap lowers
through real Mosaic (shapes kept block-aligned so the chunk kernels take
the Pallas path, not the jnp fallback), which is exactly what the
hermetic CPU suite cannot see.
"""

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                os.pardir))


def _close(a, b, tol, atol=None):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               rtol=tol, atol=tol if atol is None else atol)


# Pallas-aligned attention shapes: s % block == 0, d % 8 == 0 — the chunk
# kernels must take the compiled Mosaic path, not the jnp fallback.
B, H, S, D = 1, 2, 256, 64
AXIS = "context"


def _ctx_mesh():
    return Mesh(np.array(jax.devices()[:1]), (AXIS,))


def _qkv(seed):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, S, D), jnp.float32)
                 for k in ks)


def _sharded(fn, mesh):
    spec = P(None, None, AXIS, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)


# ------------------------------------------------- ring-attention chunks
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_chunk_kernels_on_chip(tpu_backend, causal):
    """attn_chunk_fwd AND attn_chunk_bwd (via the ring's custom vjp)
    lower on silicon and match the full-sequence oracle — forward and all
    three gradients. The 1-device ring exercises the diag (causal) and
    full (non-causal) chunk dispatch branches."""
    from apex_tpu.kernels.flash_attention import mha_reference
    from apex_tpu.transformer.context_parallel import ring_attention

    mesh = _ctx_mesh()
    q, k, v = _qkv(0)
    ring = _sharded(functools.partial(
        ring_attention, axis_name=AXIS, causal=causal), mesh)

    out = jax.jit(ring)(q, k, v)
    want = mha_reference(q, k, v, causal=causal, scale=D ** -0.5)
    _close(out, want, 2e-2)

    def lk(q, k, v):
        return jnp.sum(jnp.square(ring(q, k, v)))

    def lr(q, k, v):
        return jnp.sum(jnp.square(
            mha_reference(q, k, v, causal=causal, scale=D ** -0.5)))

    gk = jax.jit(jax.grad(lk, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gk, gr):
        _close(a, r, 2e-2, atol=1e-1)   # grad magnitudes are O(seq)


def test_ring_attention_zigzag_on_chip(tpu_backend):
    """The zigzag layout's half-chunk passes (the balanced causal ring)
    lower on silicon: sub-chunks of 128 are still block-aligned."""
    from apex_tpu.kernels.flash_attention import mha_reference
    from apex_tpu.transformer.context_parallel import (ring_attention,
                                                       zigzag_inverse,
                                                       zigzag_order)

    mesh = _ctx_mesh()
    q, k, v = _qkv(1)
    perm = zigzag_order(S, 1)
    inv = zigzag_inverse(S, 1)
    ring = _sharded(functools.partial(
        ring_attention, axis_name=AXIS, causal=True, layout="zigzag"),
        mesh)
    out = jax.jit(ring)(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    want = mha_reference(q, k, v, causal=True, scale=D ** -0.5)
    _close(out[:, :, inv], want, 2e-2)


def test_ulysses_attention_on_chip(tpu_backend):
    """The Ulysses all-to-all path (a2a → flash → inverse a2a) lowers on
    silicon end-to-end, forward and grads."""
    from apex_tpu.kernels.flash_attention import mha_reference
    from apex_tpu.transformer.context_parallel import ulysses_attention

    mesh = _ctx_mesh()
    q, k, v = _qkv(2)
    uly = _sharded(functools.partial(
        ulysses_attention, axis_name=AXIS, causal=True), mesh)
    out = jax.jit(uly)(q, k, v)
    want = mha_reference(q, k, v, causal=True, scale=D ** -0.5)
    _close(out, want, 2e-2)

    gk = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(jnp.square(uly(q, k, v))),
        argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(jnp.square(
            mha_reference(q, k, v, causal=True, scale=D ** -0.5))),
        argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gk, gr):
        _close(a, r, 2e-2, atol=1e-1)


# ------------------------------------------------- fused_dense / MLP
def test_fused_dense_gelu_dense_on_chip(tpu_backend):
    """fused_dense_function + fused_dense_gelu_dense_function fwd+bwd vs
    the fp32 composition (reference: apex/fused_dense — fused GEMM+bias
    (+gelu) epilogues; on TPU the fusion is XLA's, verified on chip)."""
    from apex_tpu.fused_dense import (fused_dense_function,
                                      fused_dense_gelu_dense_function)

    ks = jax.random.split(jax.random.PRNGKey(3), 5)
    x = jax.random.normal(ks[0], (64, 128), jnp.float32)
    w1 = jax.random.normal(ks[1], (256, 128), jnp.float32) * 0.05
    b1 = jax.random.normal(ks[2], (256,), jnp.float32) * 0.05
    w2 = jax.random.normal(ks[3], (128, 256), jnp.float32) * 0.05
    b2 = jax.random.normal(ks[4], (128,), jnp.float32) * 0.05

    def ref_dense(x, w, b):
        return x @ w.T + b

    _close(jax.jit(fused_dense_function)(x, w1, b1),
           ref_dense(x, w1, b1), 2e-2, atol=1e-4)

    def ref_gelu_dense(x, w1, b1, w2, b2):
        h = jax.nn.gelu(x @ w1.T + b1, approximate=False)
        return h @ w2.T + b2

    got = jax.jit(fused_dense_gelu_dense_function)(x, w1, b1, w2, b2)
    _close(got, ref_gelu_dense(x, w1, b1, w2, b2), 2e-2, atol=1e-4)

    gk = jax.jit(jax.grad(
        lambda *a: jnp.sum(jnp.square(
            fused_dense_gelu_dense_function(*a))), argnums=(0, 1, 2, 3, 4)))(
        x, w1, b1, w2, b2)
    gr = jax.grad(
        lambda *a: jnp.sum(jnp.square(ref_gelu_dense(*a))),
        argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
    for a, r in zip(gk, gr):
        _close(a, r, 2e-2, atol=1e-2)


def test_mlp_module_on_chip(tpu_backend):
    """The whole-MLP fused stack (reference: apex/mlp — MlpFunction)
    fwd+bwd on chip vs the per-layer fp32 composition."""
    from apex_tpu.mlp import MLP

    mlp = MLP(mlp_sizes=(128, 256, 64), activation="relu")
    x = jax.random.normal(jax.random.PRNGKey(4), (32, 128), jnp.float32)
    params = mlp.init(jax.random.PRNGKey(5), x)["params"]

    def ref(p, x):
        y = x
        for i in range(2):
            y = jnp.maximum(y @ p[f"weight_{i}"].T + p[f"bias_{i}"], 0.0)
        return y

    got = jax.jit(lambda p, x: mlp.apply({"params": p}, x))(params, x)
    _close(got, ref(params, x), 2e-2, atol=1e-4)

    gk = jax.jit(jax.grad(
        lambda p, x: jnp.sum(jnp.square(
            mlp.apply({"params": p}, x)))))(params, x)
    gr = jax.grad(lambda p, x: jnp.sum(jnp.square(ref(p, x))))(params, x)
    jax.tree_util.tree_map(lambda a, r: _close(a, r, 2e-2, atol=1e-2),
                           gk, gr)


# ------------------------------------------------- NovoGrad / Adagrad
def _np_params():
    rng = np.random.RandomState(0)
    return {"w": rng.randn(64, 32).astype(np.float32),
            "b": rng.randn(32).astype(np.float32)}


def _np_grads(i):
    rng = np.random.RandomState(100 + i)
    return {"w": rng.randn(64, 32).astype(np.float32),
            "b": rng.randn(32).astype(np.float32)}


def test_fused_novograd_steps_on_chip(tpu_backend):
    """FusedNovoGrad's functor (csrc/multi_tensor_novograd.cu semantics:
    per-tensor grad-norm v, normalized first moment) jitted on silicon
    matches a numpy reimplementation over 5 steps."""
    import optax

    from apex_tpu.optimizers import fused_novograd

    lr, b1, b2, eps, wd = 0.05, 0.95, 0.98, 1e-8, 1e-3
    opt = fused_novograd(lr, beta1=b1, beta2=b2, eps=eps, weight_decay=wd,
                         grad_averaging=True)
    params = jax.tree_util.tree_map(jnp.asarray, _np_params())
    state = opt.init(params)

    @jax.jit
    def step(params, state, grads):
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    ref = _np_params()
    m = {k: np.zeros_like(v) for k, v in ref.items()}
    v = {k: 0.0 for k in ref}
    for i in range(5):
        g = _np_grads(i)
        params, state = step(params, state,
                             jax.tree_util.tree_map(jnp.asarray, g))
        for k in ref:
            nsq = float(np.sum(g[k] * g[k]))
            v[k] = nsq if i == 0 else b2 * v[k] + (1 - b2) * nsq
            m[k] = b1 * m[k] + (1 - b1) * (g[k] / (np.sqrt(v[k]) + eps)
                                           + wd * ref[k])
            ref[k] = ref[k] - lr * m[k]
    for k in ref:
        _close(params[k], ref[k], 1e-4, atol=1e-5)


def test_fused_adagrad_steps_on_chip(tpu_backend):
    """FusedAdagrad's functor (csrc/multi_tensor_adagrad.cu: h += g²,
    p -= lr·g/(√h+eps), L2 mode) jitted on silicon matches numpy."""
    import optax

    from apex_tpu.optimizers import fused_adagrad

    lr, eps, wd = 0.05, 1e-10, 1e-4
    opt = fused_adagrad(lr, eps=eps, weight_decay=wd)
    params = jax.tree_util.tree_map(jnp.asarray, _np_params())
    state = opt.init(params)

    @jax.jit
    def step(params, state, grads):
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    ref = _np_params()
    h = {k: np.zeros_like(v) for k, v in ref.items()}
    for i in range(5):
        g = _np_grads(i)
        params, state = step(params, state,
                             jax.tree_util.tree_map(jnp.asarray, g))
        for k in ref:
            g32 = g[k] + wd * ref[k]                 # L2 into the grad
            h[k] = h[k] + g32 * g32
            ref[k] = ref[k] - lr * g32 / (np.sqrt(h[k]) + eps)
    for k in ref:
        _close(params[k], ref[k], 1e-4, atol=1e-5)


# ------------------------------------------------- detection SyncBN step
def test_detection_syncbn_train_step_on_chip(tpu_backend):
    """The detection recipe's train step — FPN-style model with true
    SyncBatchNorm (welford psum over 'data') under amp O2 + dynamic
    scaler — lowers and trains on silicon inside a 1-device data mesh."""
    import importlib.util

    import optax

    from apex_tpu import amp
    from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

    recipe = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                          "examples", "detection", "main_amp.py")
    spec = importlib.util.spec_from_file_location("_det", recipe)
    det = importlib.util.module_from_spec(spec)
    sys.modules["_det"] = det     # flax dataclass transform looks it up
    spec.loader.exec_module(det)

    norm = functools.partial(SyncBatchNorm, axis_name="data",
                             dtype=jnp.float32)
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                verbose=False)
    model = det.FPNSegModel(num_classes=5, norm=norm,
                            dtype=policy.model_dtype)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(rng, sample, train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(p, ms, batch):
        images, labels = batch
        logits, updated = model.apply({"params": p, **ms}, images,
                                      train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), labels).mean()
        return loss, updated

    init_fn, step_fn = amp.make_train_step(
        loss_fn, optax.sgd(1e-3, momentum=0.9), policy,
        with_model_state=True, grad_average_axis="data")
    state = init_fn(params, mstate)

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    jit_step = jax.jit(shard_map(
        step_fn, mesh=mesh, in_specs=(P(), (P("data"), P("data"))),
        out_specs=P(), check_vma=False))

    losses = []
    with mesh:
        for it in range(3):
            key = jax.random.PRNGKey(it)
            images = jax.random.normal(key, (2, 32, 32, 3), jnp.float32)
            labels = jax.random.randint(jax.random.fold_in(key, 1),
                                        (2, 32, 32), 0, 5)
            state, metrics = jit_step(state, (images, labels))
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert not bool(metrics["found_inf"])
    # batch stats moved off their init values — the welford psum ran
    means = jax.tree_util.tree_leaves(state.model_state["batch_stats"])
    assert any(float(jnp.abs(l).max()) > 0 for l in means)
