"""The GSPMD/pjit tier of the LM recipe (VERDICT round-4 missing #2).

SURVEY §3.3's TP row names TWO idiomatic TPU mappings for Megatron TP:
explicit shard_map collectives (mappings.py) and "pjit with sharded
weight specs — the mappings collapse into sharding constraints". The
shard_map half has carried the recipe since round 2; this module proves
the other half: ``--partitioning gspmd`` runs the SAME 1-device program
under plain ``jax.jit`` with NamedShardings built from the TP modules'
own ``kernel_partition_spec()`` — no shard_map, no explicit collectives
— and XLA's SPMD partitioner must reproduce the trajectory of both the
shard_map path and the 1-device oracle, whole canonicalized param trees
leaf-for-leaf.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow


BASE = ["--size", "tiny", "--vocab-size", "128", "--seq-len", "16",
        "-b", "16", "--iters", "6", "--deterministic",
        "--microbatches", "4"]


def _run(lm, extra, opt_level="O0"):
    args = lm.parse_args(BASE + ["--opt-level", opt_level] + extra)
    policy = amp.resolve_policy(opt_level=opt_level,
                                loss_scale=args.loss_scale, verbose=False)
    m = lm.run_parallel(args, policy)
    m["args"] = args
    return m


def _canon(lm, m):
    return lm.canonicalize_from_args(m["final_state"].params, m["args"])


def test_gspmd_matches_shard_map_and_oracle(lm, eight_devices):
    """The VERDICT done-bar: TP(+DP) under plain jit + NamedSharding
    reproduces both the explicit-collectives path and the 1-device
    oracle — same losses, same whole final param tree. This is the
    proof that mappings.py's collectives and GSPMD's propagated
    shardings compute the same math (tensor_parallel/mappings.py's
    "under plain pjit/GSPMD these mappings collapse" claim)."""
    m_seq = _run(lm, ["--data-parallel", "1", "--tensor-parallel", "1",
                      "--pipeline-parallel", "1"])
    m_smap = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2"])
    m_gspmd = _run(lm, ["--partitioning", "gspmd",
                        "--data-parallel", "2", "--tensor-parallel", "2"])
    np.testing.assert_allclose(m_gspmd["loss_history"],
                               m_seq["loss_history"], rtol=2e-4)
    np.testing.assert_allclose(m_gspmd["loss_history"],
                               m_smap["loss_history"], rtol=2e-4)
    lm.assert_trees_close(_canon(lm, m_gspmd), _canon(lm, m_seq))
    lm.assert_trees_close(_canon(lm, m_gspmd), _canon(lm, m_smap))


def test_gspmd_params_actually_sharded(lm, eight_devices):
    """The NamedShardings must DISTRIBUTE, not replicate: every column/
    row kernel (and the vocab-sharded embedding) ends up with 'model' in
    its spec and its shards spread over all 4 mesh devices — otherwise
    the tier would be a replicated no-op wearing pjit clothes."""
    m = _run(lm, ["--partitioning", "gspmd",
                  "--data-parallel", "2", "--tensor-parallel", "2",
                  "--iters", "1"])
    params = m["final_state"].params
    col = params["stages"]["col"]
    for name in ("qkv_k", "proj_k", "mlp_in_k", "mlp_out_k"):
        sh = col[name].sharding
        assert "model" in tuple(sh.spec), \
            f"{name} spec {sh.spec} does not shard over 'model'"
        assert sh.num_devices == 4, f"{name} on {sh.num_devices} devices"
    emb_sh = params["emb"]["wte"].sharding
    assert emb_sh.spec[0] == "model", f"wte spec {emb_sh.spec}"
    head_sh = params["head"]["kernel"].sharding
    assert "model" in tuple(head_sh.spec), f"head spec {head_sh.spec}"
    # masters ride the same specs as their params (O0 has none; re-check
    # cheaply via the state spec tree on an O2 run in the test below)


def test_gspmd_o2_masters_and_scaler(lm, eight_devices):
    """O2 on the GSPMD tier: finite decreasing loss, and the apex O2
    invariant — the half model params ARE the cast fp32 masters — holds
    bitwise with both trees sharded."""
    m = _run(lm, ["--partitioning", "gspmd",
                  "--data-parallel", "2", "--tensor-parallel", "2"],
             opt_level="O2")
    assert np.isfinite(float(m["loss"]))
    assert not bool(m["found_inf"])
    hist = m["loss_history"]
    assert all(np.isfinite(hist)) and hist[-1] < hist[0], hist
    state = m["final_state"]
    cast = jax.tree_util.tree_map(
        lambda mp, p: jnp.asarray(mp, p.dtype),
        state.master_params, state.params)
    lm.assert_trees_close(state.params, cast, rtol=0, atol=0)
    # masters carry the module specs too — sharded, not gathered
    msh = state.master_params["stages"]["col"]["qkv_k"].sharding
    assert "model" in tuple(msh.spec)


def test_gspmd_flag_guards(lm, eight_devices):
    """gspmd is dp x tp only (the pipe/SP/vocab/ZeRO compositions run
    under shard_map); a mesh of 1 is refused with guidance."""
    with pytest.raises(SystemExit, match="shard_map"):
        _run(lm, ["--partitioning", "gspmd", "--tensor-parallel", "2",
                  "--pipeline-parallel", "2"])
    with pytest.raises(SystemExit, match="mesh"):
        lm.main(BASE + ["--partitioning", "gspmd"])


def test_gspmd_save_resume_bitwise(lm, eight_devices, tmp_path):
    """--save/--resume on the GSPMD tier: host-restored arrays re-shard
    through the jit boundary's NamedShardings, and the resumed
    trajectory continues the uninterrupted run bitwise (same bar as the
    shard_map tier's checkpoint test)."""
    ckpt = str(tmp_path / "gspmd.npz")
    extra = ["--partitioning", "gspmd", "--data-parallel", "2",
             "--tensor-parallel", "2"]
    m_full = _run(lm, extra, opt_level="O2")
    _run(lm, extra + ["--iters", "3", "--save", ckpt], opt_level="O2")
    m_res = _run(lm, extra + ["--resume", ckpt], opt_level="O2")
    np.testing.assert_array_equal(m_res["loss_history"],
                                  m_full["loss_history"][3:])
    full_s, res_s = m_full["final_state"], m_res["final_state"]
    lm.assert_trees_close(res_s.params, full_s.params, rtol=0, atol=0)
    lm.assert_trees_close(res_s.master_params, full_s.master_params,
                          rtol=0, atol=0)
    assert float(res_s.scaler.loss_scale) == \
        float(full_s.scaler.loss_scale)


def test_gspmd_zero_is_one_partition_spec(lm, eight_devices):
    """ZeRO-1 the GSPMD way (--zero under --partitioning gspmd): the
    flat Adam m/v superbuffers carry P('data') — no collective code —
    and each device holds 1/dp of the optimizer state. The trajectory
    must match the unsharded gspmd run (sharding is layout, not
    numerics), which transitively ties it to the shard_map ZeRO and the
    1-device oracle already proven equal."""
    m_plain = _run(lm, ["--partitioning", "gspmd",
                        "--data-parallel", "2", "--tensor-parallel", "2"])
    m_zero = _run(lm, ["--partitioning", "gspmd", "--zero",
                       "--data-parallel", "2", "--tensor-parallel", "2"])
    np.testing.assert_allclose(m_zero["loss_history"],
                               m_plain["loss_history"], rtol=2e-4)
    lm.assert_trees_close(_canon(lm, m_zero), _canon(lm, m_plain))

    m_buf = m_zero["final_state"].opt_state.m
    assert "data" in tuple(m_buf.sharding.spec), m_buf.sharding
    # 4 devices in the dp2 x tp2 mesh; 'data' splits the buffer in 2 —
    # every addressable shard holds half the elements
    shard_elems = {s.data.size for s in m_buf.addressable_shards}
    assert shard_elems == {m_buf.size // 2}, \
        (m_buf.size, shard_elems)
    # the non-zero run uses the round-5 TREE layout, where each moment
    # leaf inherits its parameter's spec through _finish_gspmd's path
    # rules — TP-sharded weights get TP-sharded moments for free (a
    # memory property the replicated flat buffer never had); 'data'
    # stays out of the specs (that split is exactly what --zero adds)
    import jax as _jax

    p_leaves = _jax.tree_util.tree_leaves_with_path(
        m_plain["final_state"].params)
    m_leaves = _jax.tree_util.tree_leaves_with_path(
        m_plain["final_state"].opt_state.m)
    assert m_leaves and len(p_leaves) == len(m_leaves)
    for (p_path, p_leaf), (m_path, m_leaf) in zip(p_leaves, m_leaves):
        assert m_leaf.sharding.spec == p_leaf.sharding.spec, \
            (m_path, m_leaf.sharding, p_leaf.sharding)
        assert "data" not in tuple(m_leaf.sharding.spec), m_path
