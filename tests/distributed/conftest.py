"""Shared fixtures for the distributed tier: the LM recipe module,
exec'd ONCE per session (it is a script, not a package module — the
importlib dance with sys.modules registration is required for flax's
dataclass transform)."""

import importlib.util
import os
import sys

import pytest

_RECIPE = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                       "examples", "lm", "main_amp.py")

_LM_CACHE: list = []


def load_lm_recipe():
    """The examples/lm/main_amp.py module, loaded lazily and cached for
    the whole session (module exec deferred past pytest collection)."""
    if not _LM_CACHE:
        spec = importlib.util.spec_from_file_location("lm_recipe", _RECIPE)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["lm_recipe"] = mod
        spec.loader.exec_module(mod)
        _LM_CACHE.append(mod)
    return _LM_CACHE[0]


@pytest.fixture(scope="session")
def lm():
    return load_lm_recipe()
