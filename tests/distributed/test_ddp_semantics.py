"""Distributed-tier tests (reference: tests/distributed/).

- amp_master_params/: after a DDP step, fp32 masters and half model params
  must be consistent with each other and IDENTICAL across ranks.
- DDP/ddp_race_condition_test.py: hook/stream ordering races. Those races
  cannot exist under XLA's dataflow semantics (SURVEY §6) — the analogue
  asserted here is order-insensitivity: reversing bucket submission order
  changes nothing, and repeated runs are bit-identical.
- synced_batchnorm/test_groups.py: SyncBN over process subgroups.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import fused_sgd

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow


@pytest.fixture()
def data_mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("data",))


def _loss_fn(p, batch):
    x, y = batch
    pred = x @ jnp.asarray(p["w"], x.dtype) + jnp.asarray(p["b"], x.dtype)
    return jnp.mean((jnp.asarray(pred, jnp.float32) - y) ** 2)


def _step_setup(opt_level="O2"):
    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    params = {"w": jnp.ones((16, 8)) * 0.1, "b": jnp.zeros((8,))}
    init_fn, step_fn = amp.make_train_step(
        _loss_fn, fused_sgd(0.1, momentum=0.9), policy,
        grad_average_axis="data")
    return params, init_fn, step_fn


def _batches(n=8):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (n * 4, 16))
    y = jax.random.normal(jax.random.fold_in(k, 1), (n * 4, 8))
    return x, y


def test_amp_master_params_consistent_across_ranks(data_mesh):
    """Reference: tests/distributed/amp_master_params — after a DDP step,
    per-rank master fp32 and model half params agree across all ranks, and
    model = masters cast to half."""
    params, init_fn, step_fn = _step_setup()

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), (P("data"), P("data"))),
                       out_specs=(P("data"), P("data")), check_vma=False)
    def run(state, batch):
        new_state, _ = step_fn(state, batch)
        # expose every rank's params for cross-rank comparison
        return (jax.tree_util.tree_map(lambda l: l[None], new_state.params),
                jax.tree_util.tree_map(lambda l: l[None],
                                       new_state.master_params))

    state = init_fn(params)
    model_all, master_all = jax.jit(run)(state, _batches())
    for leaf_model, leaf_master in zip(
            jax.tree_util.tree_leaves(model_all),
            jax.tree_util.tree_leaves(master_all)):
        lm, lM = np.asarray(leaf_model), np.asarray(leaf_master)
        for r in range(1, 8):
            np.testing.assert_array_equal(lm[r], lm[0])   # identical ranks
            np.testing.assert_array_equal(lM[r], lM[0])
        # model params are the masters cast to the model dtype
        np.testing.assert_array_equal(
            lm[0], lM[0].astype(lm.dtype))


def test_grad_reduction_is_order_insensitive_and_deterministic(data_mesh):
    """The DDP-race analogue: apex's test hammers overlapping allreduce
    ordering; under XLA the reduction is part of one program, so (a) two
    identical runs are bit-identical and (b) parameter-tree ordering doesn't
    change the math."""
    params, init_fn, step_fn = _step_setup("O0")

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), (P("data"), P("data"))),
                       out_specs=P(), check_vma=False)
    def run(state, batch):
        new_state, _ = step_fn(state, batch)
        return new_state.params

    state = init_fn(params)
    out1 = jax.jit(run)(state, _batches())
    out2 = jax.jit(run)(state, _batches())
    for a, b in zip(jax.tree_util.tree_leaves(out1),
                    jax.tree_util.tree_leaves(out2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # reversed-order tree (reversed dict insertion): same values per leaf
    params_rev = dict(reversed(list(params.items())))
    state_rev = init_fn(params_rev)
    out3 = jax.jit(run)(state_rev, _batches())
    np.testing.assert_array_equal(np.asarray(out1["w"]),
                                  np.asarray(out3["w"]))
    np.testing.assert_array_equal(np.asarray(out1["b"]),
                                  np.asarray(out3["b"]))


def test_overflow_skips_step_on_all_ranks(data_mesh):
    """One rank's inf grad must freeze params AND optimizer state on every
    rank (NCCL-inf-propagation semantics; make_train_step docstring)."""
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic",
                                cast_model_type="float16")
    params = {"w": jnp.ones((4, 4))}

    def loss_fn(p, batch):
        x, poison = batch
        # poison is huge on exactly one rank → fp16 overflow there only
        return jnp.mean((x @ jnp.asarray(p["w"], x.dtype)) ** 2) * poison[0]

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_sgd(0.1), policy,
                                           grad_average_axis="data")

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), (P("data"), P("data"))),
                       out_specs=(P("data"), P("data")), check_vma=False)
    def run(state, batch):
        new_state, metrics = step_fn(state, batch)
        return (jax.tree_util.tree_map(lambda l: l[None], new_state.params),
                metrics["found_inf"][None])

    state = init_fn(params)
    x = jnp.ones((8 * 2, 4))
    poison = jnp.ones((8,)).at[3].set(1e30)  # rank 3 overflows
    out, found = jax.jit(run)(state, (x, poison))
    found = np.asarray(found)
    assert found.all(), f"found_inf must be synced to all ranks: {found}"
    w = np.asarray(out["w"])
    for r in range(8):
        np.testing.assert_array_equal(w[r], np.ones((4, 4), w.dtype))


def test_syncbn_groups(data_mesh):
    """Reference: synced_batchnorm/test_groups.py — stats sync within
    subgroups only."""
    from apex_tpu.parallel import SyncBatchNorm, create_syncbn_process_group

    groups = create_syncbn_process_group(8, 4)  # two groups of 4
    bn = SyncBatchNorm(use_running_average=False, axis_name="data",
                       axis_index_groups=groups)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    def run(x):
        variables = bn.init(jax.random.PRNGKey(0), x[0])
        y, _ = bn.apply(variables, x[0], mutable=["batch_stats"])
        return y[None]

    # group A (ranks 0-3) sees mean 0, group B (4-7) mean 10: outputs must
    # normalize within group, so both groups give ~zero-mean results even
    # though the global mean is 5
    x = jnp.concatenate([jnp.zeros((4, 1, 16, 4)),
                         jnp.full((4, 1, 16, 4), 10.0)]) \
        + jax.random.normal(jax.random.PRNGKey(1), (8, 1, 16, 4)) * 0.1
    y = np.asarray(jax.jit(run)(x))
    # per-GROUP means are ~0 (stats synced within the subgroup)...
    assert abs(y[:4].mean()) < 0.05, y[:4].mean()
    assert abs(y[4:].mean()) < 0.05, y[4:].mean()

    # ...whereas a globally-synced BN normalizes around the global mean 5,
    # pushing the two groups to opposite signs — proving the groups did
    # something
    bn_global = SyncBatchNorm(use_running_average=False, axis_name="data")

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    def run_global(x):
        variables = bn_global.init(jax.random.PRNGKey(0), x[0])
        y, _ = bn_global.apply(variables, x[0], mutable=["batch_stats"])
        return y[None]

    yg = np.asarray(jax.jit(run_global)(x))
    assert yg[:4].mean() < -0.5 and yg[4:].mean() > 0.5


def test_syncbn_ragged_counts_match_single_device_oracle(data_mesh):
    """Count-weighted Welford combine (csrc/welford.cu —
    welford_parallel_CUDA): with ragged per-rank element counts (padded rows
    marked invalid by ``mask``) the synced stats must equal the single-device
    stats over only the valid elements. A moment-averaging (pmean) combine
    gets this wrong whenever counts differ."""
    from apex_tpu.parallel import SyncBatchNorm

    rows_per_rank = 6
    feat = 4
    k = jax.random.PRNGKey(7)
    x = jax.random.normal(k, (8, rows_per_rank, feat)) * 3.0 + 1.5
    # rank r keeps r%5 + 2 valid rows → counts vary 2..6 across ranks
    valid = np.array([r % 5 + 2 for r in range(8)])
    mask = np.zeros((8, rows_per_rank, 1), np.float32)
    for r in range(8):
        mask[r, :valid[r]] = 1.0
    mask = jnp.asarray(mask)

    bn = SyncBatchNorm(use_running_average=False, axis_name="data",
                       momentum=0.9)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P("data"), P("data")),
                       out_specs=(P("data"), P()), check_vma=False)
    def run(x, m):
        variables = bn.init(jax.random.PRNGKey(0), x[0])
        y, updated = bn.apply(variables, x[0], mask=m[0],
                              mutable=["batch_stats"])
        return y[None], updated["batch_stats"]

    y, stats = jax.jit(run)(x, mask)
    y = np.asarray(y)

    # oracle: stats over ONLY the valid rows, gathered to one device
    xv = np.concatenate([np.asarray(x[r, :valid[r]]) for r in range(8)])
    mean_ref = xv.mean(axis=0)
    var_ref = xv.var(axis=0)
    n = xv.shape[0]

    # the normalized output on valid rows matches (x - mean)/sqrt(var + eps)
    yv = np.concatenate([y[r, :valid[r]] for r in range(8)])
    ref = (xv - mean_ref) / np.sqrt(var_ref + 1e-5)
    np.testing.assert_allclose(yv, ref, rtol=1e-4, atol=1e-4)

    # running stats: m*init + (1-m)*batch_stat with the unbiased global var
    np.testing.assert_allclose(np.asarray(stats["mean"]),
                               0.1 * mean_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]),
                               0.9 + 0.1 * var_ref * n / (n - 1),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_ddp_matches_single_process(data_mesh, opt_level):
    """Reference: tests/L1/cross_product — the DDP axis of the matrix: an
    8-way DDP run on a global batch must match the single-process run on
    the same batch (grad averaging over equal shards == global mean)."""
    params, init_fn, step_fn = _step_setup(opt_level)
    x, y = _batches()

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), (P("data"), P("data"))),
                       out_specs=(P(), P()), check_vma=False)
    def run_ddp(state, batch):
        new_state, metrics = step_fn(state, batch)
        return new_state.params, metrics["loss"]

    ddp_params, ddp_loss = jax.jit(run_ddp)(init_fn(params), (x, y))

    # single-process step on the full batch (no grad_average_axis)
    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    sp_init, sp_step = amp.make_train_step(
        _loss_fn, fused_sgd(0.1, momentum=0.9), policy)
    sp_state, sp_metrics = jax.jit(sp_step)(sp_init(params), (x, y))

    np.testing.assert_allclose(float(ddp_loss), float(sp_metrics["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ddp_params),
                    jax.tree_util.tree_leaves(sp_state.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-3, atol=2e-3)


def test_syncbn_large_mean_stability(data_mesh):
    """welford_parallel (Chan fold of per-rank triples) must stay finite
    where a psum of (sum, sumsq) cancels catastrophically: activations at
    mean >> std."""
    from apex_tpu.parallel import SyncBatchNorm

    bn = SyncBatchNorm(use_running_average=False, axis_name="data")
    x = 4096.0 + jax.random.normal(jax.random.PRNGKey(11),
                                   (8, 64, 4)) * 0.01

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    def run(x):
        variables = bn.init(jax.random.PRNGKey(0), x[0])
        y, _ = bn.apply(variables, x[0], mutable=["batch_stats"])
        return y[None]

    y = np.asarray(jax.jit(run)(x))
    assert np.isfinite(y).all()
    # the normalized output matches the fp64 oracle over the global batch
    x64 = np.asarray(x, np.float64).reshape(-1, 4)
    ref = (x64 - x64.mean(0)) / np.sqrt(x64.var(0) + 1e-5)
    np.testing.assert_allclose(y.reshape(-1, 4), ref, rtol=5e-2, atol=5e-2)
