"""Vocab-parallel fused LM-head + CE under shard_map (8 virtual devices).

The op's ``axis_name`` mode is the Megatron vocab_parallel_cross_entropy
reduction set (pmax + psums of the online-logsumexp pieces) fused with
the head GEMM. Bar: loss AND both cotangents match the single-device op
(which itself matches the unfused oracle — tests/L0/test_lm_head_loss.py)
at fp32-roundoff tolerance, dx arriving fully psummed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.kernels.lm_head_loss import lm_head_xentropy

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

N, H, V = 32, 64, 1024
TP = 8


def _mesh():
    devs = jax.devices()
    if len(devs) < TP:
        pytest.skip(f"needs {TP} devices, have {len(devs)}")
    return Mesh(np.array(devs[:TP]), ("model",))


def _setup(seed=0, v=V):
    rng = jax.random.PRNGKey(seed)
    x = jax.random.normal(rng, (N, H))
    w = jax.random.normal(jax.random.fold_in(rng, 1), (v, H)) * 0.1
    y = jax.random.randint(jax.random.fold_in(rng, 2), (N,), 0, v)
    return x, w, y


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("v,chunk", [
    (1024, 8192),   # V_loc=128, single aligned chunk per shard
    (2048, 128),    # V_loc=256, chunk=128: nc=2 WITHIN each shard
    (1008, 8192),   # V_loc=126 pads to 128: pad cols alias the NEXT
                    # shard's global ids — the masked regime (labels
                    # over the full vocab include every shard's first
                    # ids, the exact aliasing the fwd/bwd gates guard)
])
def test_vocab_parallel_matches_single_device(smoothing, v, chunk):
    """Sharded coverage of all three chunk regimes: aligned single
    chunk, multi-chunk scan per shard, and padded shards whose pad
    columns alias the next shard's vocab ids.

    Grads are taken INSIDE shard_map (value_and_grad in the mapped
    function) — the recipes' actual pattern. Differentiating THROUGH a
    shard_map with a replicated (P()) output instead hands each rank
    the cotangent pre-divided by the axis size (the convention the
    recipes compensate with their loss/tp returns), which would scale
    the shard-local dW by 1/tp and say nothing about the op."""
    mesh = _mesh()
    x, w, y = _setup(v=v)

    def tp_step(x, w_shard, y):
        def loss_fn(x, w_shard):
            return lm_head_xentropy(x, w_shard, y, smoothing=smoothing,
                                    chunk=chunk, axis_name="model").mean()
        loss, (gx, gw) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            x, w_shard)
        return loss, gx, gw

    got, gx_t, gw_t = jax.jit(shard_map(
        tp_step, mesh=mesh,
        in_specs=(P(), P("model", None), P()),
        out_specs=(P(), P(), P("model", None)), check_vma=False))(x, w, y)

    def single(x, w):
        return lm_head_xentropy(x, w, y, smoothing=smoothing).mean()

    want, (gx_s, gw_s) = jax.jit(jax.value_and_grad(
        single, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx_t), np.asarray(gx_s),
                               rtol=2e-4, atol=2e-6)
    np.testing.assert_allclose(np.asarray(gw_t), np.asarray(gw_s),
                               rtol=2e-4, atol=2e-6)


def test_vocab_parallel_matches_megatron_ce():
    """Cross-check against the repo's own vocab_parallel_cross_entropy
    composed with an explicit sharded head GEMM — the exact pair the
    fused mode replaces in a Megatron-style TP tail."""
    from apex_tpu.transformer.tensor_parallel import (
        copy_to_tensor_model_parallel_region, vocab_parallel_cross_entropy)

    mesh = _mesh()
    x, w, y = _setup(1)

    def fused(x, w_shard, y):
        return lm_head_xentropy(x, w_shard, y,
                                axis_name="model").mean()

    def composed(x, w_shard, y):
        hh = copy_to_tensor_model_parallel_region(x, "model")
        logits = jnp.dot(hh, w_shard.T)
        return vocab_parallel_cross_entropy(
            logits, y, axis_name="model").mean()

    kw = dict(mesh=mesh, in_specs=(P(), P("model", None), P()),
              out_specs=P(), check_vma=False)
    f_loss = shard_map(fused, **kw)
    c_loss = shard_map(composed, **kw)
    np.testing.assert_allclose(float(f_loss(x, w, y)),
                               float(c_loss(x, w, y)), rtol=1e-5)
    gf = jax.jit(jax.grad(f_loss, argnums=(0, 1)))(x, w, y)
    gc = jax.jit(jax.grad(c_loss, argnums=(0, 1)))(x, w, y)
    for a, b in zip(gf, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_loss_replicated_across_ranks():
    """out_specs=P('model') would expose per-rank values; assert they
    are identical (the combine leaves every rank with the global loss)."""
    mesh = _mesh()
    x, w, y = _setup(2)

    per_rank = shard_map(
        lambda x, w_shard, y: lm_head_xentropy(
            x, w_shard, y, axis_name="model").mean()[None],
        mesh=mesh, in_specs=(P(), P("model", None), P()),
        out_specs=P("model"), check_vma=False)(x, w, y)
    assert per_rank.shape == (TP,)
    np.testing.assert_allclose(np.asarray(per_rank),
                               np.full(TP, float(per_rank[0])), rtol=0)
