"""The LM recipe's model-parallel tier (VERDICT round-2 missing #2).

One command trains an LM with dp x tp x pp on the 8-device CPU mesh, the
hand-scheduled 1F1B composed with amp O2 master weights + dynamic scaler
through make_train_step(grad_fn=...). Mirrors the reference pattern of
Megatron trainers driving apex TP/PP layers + amp (SURVEY P22-P24, §4.5).

Parity is asserted on FULL FINAL PARAM TREES, not loss scalars (VERDICT
round-3 weak #2): canonicalize_params inverts each configuration's
(pipe, model) scatter so the whole parameter trajectory — every weight,
bias, embedding, and head — must agree leaf-for-leaf with the single-rank
oracle. This is the reference's cross-rank master-param consistency check
(SURVEY §5 — examples/simple/distributed/amp_master_params/compare.py)
made configuration-invariant.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow



BASE = ["--size", "tiny", "--vocab-size", "128", "--seq-len", "16",
        "-b", "16", "--iters", "6", "--deterministic",
        "--microbatches", "4"]


def _run(lm, extra, opt_level="O0"):
    args = lm.parse_args(BASE + ["--opt-level", opt_level] + extra)
    policy = amp.resolve_policy(opt_level=opt_level,
                                loss_scale=args.loss_scale, verbose=False)
    m = lm.run_parallel(args, policy)
    m["args"] = args
    return m


def _canon(lm, m):
    """This run's final params in the configuration-invariant layout."""
    return lm.canonicalize_from_args(m["final_state"].params, m["args"])


def _assert_trees_close(lm, *args, **kwargs):
    """Leaf-for-leaf allclose with the failing leaf's key path — the
    recipe's own helper, shared with the multichip dryrun. Takes the
    ``lm`` fixture module (importing conftest directly is unsupported
    under --import-mode=importlib)."""
    return lm.assert_trees_close(*args, **kwargs)


_BASELINES: dict = {}


def _baseline(lm, extra_key=()):
    """Single-rank oracle trajectory, cached per flag-set — several tests
    compare against the identical dp1/tp1/pp1 run."""
    key = tuple(extra_key)
    if key not in _BASELINES:
        _BASELINES[key] = _run(lm, list(extra_key)
                               + ["--data-parallel", "1",
                                  "--tensor-parallel", "1",
                                  "--pipeline-parallel", "1"])
    return _BASELINES[key]


def test_one_command_trains_dp_tp_pp(lm, eight_devices):
    """The VERDICT done-bar: one command, dp2 x tp2 x pp2 over 8 devices,
    O2 master weights + dynamic scaler, finite decreasing loss — and the
    O2 invariant that the half model params ARE the cast masters."""
    m = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2",
                  "--pipeline-parallel", "2"], opt_level="O2")
    assert np.isfinite(float(m["loss"]))
    assert not bool(m["found_inf"])
    hist = m["loss_history"]
    assert all(np.isfinite(hist))
    assert hist[-1] < hist[0], f"loss did not decrease: {hist}"
    state = m["final_state"]
    cast = jax.tree_util.tree_map(
        lambda mp, p: jnp.asarray(mp, p.dtype),
        state.master_params, state.params)
    _assert_trees_close(lm, state.params, cast, rtol=0, atol=0)


def test_parallel_trajectory_matches_single_rank_oracle(lm, eight_devices):
    """Canonical-init scatter makes the math identical at every dp/tp/pp:
    the full dp2 x tp2 x pp2 trajectory reproduces the 1-device (grad-
    accumulation, no collectives) trajectory — end-to-end evidence that TP
    sharding, 1F1B scheduling, embedding-cotangent and head-grad plumbing,
    and the DDP psum all compute the sequential gradients. Asserted on the
    whole final param tree, loss included."""
    m_seq = _baseline(lm)
    m_par = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2",
                      "--pipeline-parallel", "2"])
    np.testing.assert_allclose(float(m_par["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_par), _canon(lm, m_seq))


def test_interleaved_vpp_trajectory_matches(lm, eight_devices):
    """vpp=2 (interleaved 1F1B) computes the same trajectory — final
    param tree compared through the chunk-round-robin un-permutation."""
    m_seq = _baseline(lm, ("--layers", "4"))
    m_vpp = _run(lm, ["--layers", "4", "--pipeline-parallel", "2",
                      "--virtual-pipeline", "2"])
    np.testing.assert_allclose(float(m_vpp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_vpp), _canon(lm, m_seq))


def test_sequence_parallel_trajectory_matches(lm, eight_devices):
    """--sequence-parallel (Megatron SP: seq-sharded LN/residual region,
    col all-gather / row reduce-scatter) computes the same trajectory as
    the single-rank oracle, through both the 1F1B (pp2) and the
    grad-accumulation (tp-only) paths."""
    m_seq = _baseline(lm)
    m_sp_pp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                        "2", "--sequence-parallel"])
    np.testing.assert_allclose(float(m_sp_pp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_sp_pp), _canon(lm, m_seq))
    m_sp_tp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                        "1", "--sequence-parallel"])
    np.testing.assert_allclose(float(m_sp_tp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_sp_tp), _canon(lm, m_seq))


def test_vocab_parallel_head_trajectory_matches(lm, eight_devices):
    """--vocab-parallel (Megatron parallel LM head: copy_to before the
    head, vocab-sharded kernel, all-reduce-based parallel cross entropy)
    computes the same trajectory through both pp and tp-only paths."""
    m_seq = _baseline(lm)
    m_vp_pp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                        "2", "--vocab-parallel"])
    np.testing.assert_allclose(float(m_vp_pp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_vp_pp), _canon(lm, m_seq))
    m_vp_tp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                        "1", "--vocab-parallel"])
    np.testing.assert_allclose(float(m_vp_tp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_vp_tp), _canon(lm, m_seq))


def test_vocab_parallel_fused_head_trajectory_matches(lm, eight_devices):
    """--vocab-parallel --fused-head (the kernels/lm_head_loss axis_name
    mode replacing copy_to + materialized logits + parallel CE) stays on
    the SAME trajectory as the oracle and the unfused vp path — the
    fused reductions are the same math, reassociated."""
    m_seq = _baseline(lm)
    m_f_tp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                       "1", "--vocab-parallel", "--fused-head"])
    np.testing.assert_allclose(float(m_f_tp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_f_tp), _canon(lm, m_seq))
    # and through pp2, where the head lives on the last stage
    m_f_pp = _run(lm, ["--tensor-parallel", "2", "--pipeline-parallel",
                       "2", "--vocab-parallel", "--fused-head"])
    np.testing.assert_allclose(float(m_f_pp["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_f_pp), _canon(lm, m_seq))


def test_full_combo_dp_tp_pp_vpp_trajectory(lm, eight_devices):
    """Every axis at once — dp2 x tp2 x pp2 with vpp2 (8 devices, 4 logical
    stages) reproduces the single-device trajectory, whole param tree."""
    m_seq = _baseline(lm, ("--layers", "4"))
    m_all = _run(lm, ["--layers", "4", "--data-parallel", "2",
                      "--tensor-parallel", "2", "--pipeline-parallel", "2",
                      "--virtual-pipeline", "2"])
    np.testing.assert_allclose(float(m_all["loss"]), float(m_seq["loss"]),
                               rtol=2e-4)
    _assert_trees_close(lm, _canon(lm, m_all), _canon(lm, m_seq))


def test_zero_sharded_optimizer_trajectory_matches(lm, eight_devices):
    """--zero (contrib DistributedFusedAdam: mean-reduce-scatter grads,
    1/dp optimizer-state shard per rank, all-gather params) reproduces the
    plain fused_adam trajectory at dp2 x tp2 x pp2 — ZeRO sharding is a
    memory layout, not a numerics change. Asserted on the final param
    tree AND the first-moment superbuffers, de-interleaved shard-to-shard.
    """
    # --opt-layout flat on the plain side: the superbuffer comparison
    # below de-interleaves FLAT rank-local buffers (the tree default is
    # bitwise-identical — tests/L0/test_fused_optimizers.py — but stores
    # per-leaf state this shard arithmetic doesn't address)
    m_adam = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2",
                       "--pipeline-parallel", "2", "--opt-layout", "flat"])
    m_zero = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2",
                       "--pipeline-parallel", "2", "--zero"])
    np.testing.assert_allclose(float(m_zero["loss"]), float(m_adam["loss"]),
                               rtol=2e-4)
    # same configuration on both sides: params trees compare directly
    _assert_trees_close(lm, m_zero["final_state"].params,
                        m_adam["final_state"].params)

    # first moments: fused_adam's global m is the (pipe, model) stack of
    # rank-local flat buffers [pp*tp, local]; ZeRO's is the same buffers
    # split 1/dp with data outermost [dp, pp*tp, pad_local/dp] (plus a
    # divisibility pad at each buffer's tail). De-interleave and trim.
    dp = pp = tp = 2
    m_flat = np.asarray(m_adam["final_state"].opt_state.m)
    local = m_flat.size // (pp * tp)
    m_ref = m_flat.reshape(pp * tp, local)
    z_flat = np.asarray(m_zero["final_state"].opt_state.m_shard)
    shard = z_flat.size // (dp * pp * tp)
    m_got = (z_flat.reshape(dp, pp * tp, shard).transpose(1, 0, 2)
             .reshape(pp * tp, dp * shard)[:, :local])
    np.testing.assert_allclose(m_got, m_ref, rtol=2e-4, atol=1e-7)

    # and the documented O2 composition: masters + dynamic scaler + ZeRO
    m_zero_o2 = _run(lm, ["--data-parallel", "2", "--tensor-parallel", "2",
                          "--pipeline-parallel", "2", "--zero"],
                     opt_level="O2")
    assert np.isfinite(float(m_zero_o2["loss"]))
    assert not bool(m_zero_o2["found_inf"])


def test_real_data_through_the_parallel_tier(lm, eight_devices):
    """--data (pre-tokenized .npy) drives the model-parallel path: the
    tp2 x pp2 trajectory on the checked-in token stream reproduces the
    1-device oracle on the SAME data — window sampler shared, canonical
    param trees leaf-for-leaf (SURVEY P38: real-data-first recipes)."""
    data = os.path.join(os.path.dirname(__file__), os.pardir, "data",
                        "tiny_lm_tokens.npy")
    extra = ["--data", data]
    m_par = _run(lm, extra + ["--tensor-parallel", "2",
                              "--pipeline-parallel", "2"])
    m_seq = _run(lm, extra + ["--data-parallel", "1",
                              "--tensor-parallel", "1",
                              "--pipeline-parallel", "1"])
    np.testing.assert_allclose(m_par["loss_history"], m_seq["loss_history"],
                               rtol=2e-4)
    assert m_par["loss_history"][-1] < m_par["loss_history"][0]
    _assert_trees_close(lm, _canon(lm, m_par), _canon(lm, m_seq))


def test_save_resume_continues_trajectory_exactly(lm, eight_devices,
                                                  tmp_path):
    """--save/--resume on the full parallel tier (reference recipes are
    checkpoint-first: imagenet --resume, BERT phase1→phase2): an O2+ZeRO
    dp2 x tp2 x pp2 run interrupted at step 3 and resumed reproduces the
    uninterrupted 6-step run BITWISE — params, fp32 masters, sharded
    first moments, and the remaining loss history."""
    ckpt = str(tmp_path / "lm_parallel.npz")
    extra = ["--data-parallel", "2", "--tensor-parallel", "2",
             "--pipeline-parallel", "2", "--zero"]
    m_full = _run(lm, extra, opt_level="O2")
    _run(lm, extra + ["--iters", "3", "--save", ckpt], opt_level="O2")
    m_res = _run(lm, extra + ["--resume", ckpt], opt_level="O2")
    np.testing.assert_array_equal(m_res["loss_history"],
                                  m_full["loss_history"][3:])
    full_s, res_s = m_full["final_state"], m_res["final_state"]
    _assert_trees_close(lm, res_s.params, full_s.params, rtol=0, atol=0)
    _assert_trees_close(lm, res_s.master_params, full_s.master_params,
                        rtol=0, atol=0)
    np.testing.assert_array_equal(
        np.asarray(res_s.opt_state.m_shard),
        np.asarray(full_s.opt_state.m_shard))
    assert float(res_s.scaler.loss_scale) == \
        float(full_s.scaler.loss_scale)


def test_o2_skip_on_overflow_across_pipe(lm, eight_devices):
    """apex semantics through the pipelined step (VERDICT item 3): an
    overflow on ANY rank must skip the step on EVERY rank — params, master
    weights, and optimizer state all frozen, loss scale halved."""
    args = lm.parse_args(BASE + ["--opt-level", "O2",
                                 "--data-parallel", "2",
                                 "--tensor-parallel", "2",
                                 "--pipeline-parallel", "2"])
    policy = amp.resolve_policy(opt_level="O2", half_dtype=jnp.float16,
                                loss_scale="dynamic", verbose=False)
    mesh, state, jit_step, _ = lm.build_parallel_lm(args, policy)

    # poison the embedding: 1e30 overflows the fp16 model params, so the
    # forward (and therefore every rank's gradients) becomes non-finite.
    # Poison the fp32 MASTERS consistently — on a skipped step the model
    # params are re-derived from the (frozen) masters, so "untouched"
    # means equal to the masters' cast, exactly apex's O2 invariant.
    bad_params = dict(state.params)
    bad_params["emb"] = jax.tree_util.tree_map(
        lambda l: jnp.full_like(l, 1e30), state.params["emb"])
    bad_masters = dict(state.master_params)
    bad_masters["emb"] = jax.tree_util.tree_map(
        lambda l: jnp.full_like(l, 1e30), state.master_params["emb"])
    state = state.replace(params=bad_params, master_params=bad_masters)

    # numpy snapshot: jit_step donates the state, deleting the old buffers
    before = [np.asarray(l) for l in jax.tree_util.tree_leaves(
        (state.params, state.master_params, state.opt_state))]
    scale_before = float(state.scaler.loss_scale)

    rng = jax.random.PRNGKey(0)
    batch = lm.synthetic_tokens(rng, args.batch_size, args.seq_len,
                                args.vocab_size)
    with mesh:
        state2, metrics = jit_step(state, batch)

    assert bool(metrics["found_inf"])
    after = jax.tree_util.tree_leaves(
        (state2.params, state2.master_params, state2.opt_state))
    for a, b in zip(before, after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(state2.scaler.loss_scale) == scale_before / 2
