"""Rank worker for the REAL 2-process ``jax.distributed`` bootstrap test
(tests/distributed/test_multiprocess_bootstrap.py — VERDICT round-4
missing #1).

Each OS process owns 4 virtual CPU devices; ``comm.initialize_distributed``
joins them through the coordination service into one 8-device world, and
``comm.make_hybrid_mesh`` lays the 'data' axis ACROSS the processes — the
mesh position multi-slice layouts put on DCN. The DDP train step (amp O2 +
dynamic scaler, grads pmean'd over every mesh axis) then runs shard_mapped
over the global mesh with each process feeding only its OWN batch rows via
``jax.make_array_from_process_local_data`` — the reference's
multi-process-per-node NCCL tier (SURVEY §5), TPU-shaped.

Run: ``python _jaxdist_worker.py <rank> <coordinator> <outdir> [mode]``;
``mode`` is ``shard_map`` (default — explicit collectives) or ``gspmd``
(plain jit + NamedShardings over the same hybrid mesh: the production
multi-host TPU pattern, where XLA partitions one global program across
the processes). Writes ``rank<r>.npz`` with the final
params/masters/scaler for the parent test to compare across ranks.
"""

import os
import sys

N_STEPS = 5
BATCH = 32


def training_setup(grad_axes=("data", "model")):
    """ONE copy of the model/optimizer constants, shared by the rank
    worker and the parent test's single-process oracle — hand-synced
    duplicates would turn a tuning edit into a numeric-mismatch hunt.
    ``grad_axes=None`` builds the GSPMD flavor: no explicit grad psum —
    the loss is the global-batch mean and XLA inserts the reduction."""
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.optimizers import fused_adam

    params = {"w": jnp.ones((16, 8)) * 0.5, "b": jnp.zeros((8,))}

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ jnp.asarray(p["w"], x.dtype) + jnp.asarray(p["b"], x.dtype)
        return jnp.mean((jnp.asarray(pred, jnp.float32) - y) ** 2)

    policy = amp.resolve_policy(opt_level="O2", verbose=False)
    init_fn, step_fn = amp.make_train_step(
        loss_fn, fused_adam(1e-2), policy, grad_average_axis=grad_axes)
    return params, init_fn, step_fn


def batch_at(it):
    """Deterministic global batch for step ``it`` (both sides draw the
    same stream; ranks slice their own rows)."""
    import jax
    import numpy as np

    k = jax.random.PRNGKey(100 + it)
    x = np.asarray(jax.random.normal(k, (BATCH, 16)))
    y = np.asarray(jax.random.normal(jax.random.fold_in(k, 1), (BATCH, 8)))
    return x, y


def main():
    rank = int(sys.argv[1])
    coord = sys.argv[2]
    outdir = sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "shard_map"
    if mode not in ("shard_map", "gspmd"):
        raise SystemExit(f"unknown mode {mode!r}")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir, os.pardir))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    import jax

    # config (not env): the axon sitecustomize pins jax_platforms at
    # interpreter start, overriding JAX_PLATFORMS (see comm.ensure_devices)
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:
        # older jax has no such config option; the XLA flag does the
        # same and still bites here (backends are uninitialized)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4").strip()

    from apex_tpu import comm

    try:
        comm.initialize_distributed(coordinator_address=coord,
                                    num_processes=2, process_id=rank)
    except Exception as e:  # noqa: BLE001 — parent turns this into a skip
        print(f"BOOTSTRAP_FAILED: {type(e).__name__}: {e}", flush=True)
        sys.exit(42)

    import numpy as np
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    mesh = comm.make_hybrid_mesh(ici_axes={"model": 4},
                                 dcn_axes={"data": 2})
    assert mesh.shape == {"data": 2, "model": 4}
    axes = ("data", "model")

    metrics = None
    try:
        if mode == "gspmd":
            # one GLOBAL program partitioned by XLA across both processes:
            # replicated state, batch sharded over every mesh dim, no
            # explicit collectives anywhere in user code
            params, init_fn, step_fn = training_setup(grad_axes=None)
            rep = NamedSharding(mesh, P())
            bsh = NamedSharding(mesh, P(axes))
            state_sh = jax.tree_util.tree_map(
                lambda _: rep, jax.eval_shape(init_fn, params))
            state = jax.jit(init_fn, out_shardings=state_sh)(params)
            step = jax.jit(step_fn, in_shardings=(state_sh, (bsh, bsh)))
        else:
            params, init_fn, step_fn = training_setup()
            state = init_fn(params)
            step = jax.jit(shard_map(step_fn, mesh=mesh,
                                     in_specs=(P(), (P(axes), P(axes))),
                                     out_specs=(P(), P()), check_vma=False),
                           donate_argnums=(0,))
            bsh = NamedSharding(mesh, P(axes))
        for it in range(N_STEPS):
            x, y = batch_at(it)
            # this process contributes ONLY its own half of the global batch
            lo, hi = rank * BATCH // 2, (rank + 1) * BATCH // 2
            xg = jax.make_array_from_process_local_data(bsh, x[lo:hi])
            yg = jax.make_array_from_process_local_data(bsh, y[lo:hi])
            state, metrics = step(state, (xg, yg))
    except Exception as e:  # noqa: BLE001 — env gap, not a logic failure
        if "Multiprocess computations aren't implemented" in str(e):
            # this jax's CPU backend cannot RUN cross-process programs
            # even though bootstrap succeeded — same environment
            # limitation as a refused bootstrap, so same skip signal
            print(f"BOOTSTRAP_FAILED: {type(e).__name__}: {e}", flush=True)
            sys.exit(42)
        raise

    # half params (bf16) round-trip npz as raw void bytes; fp32 holds
    # every bf16 exactly, so the cast keeps the cross-rank check bitwise
    np.savez(
        os.path.join(outdir, f"rank{rank}.npz"),
        w=np.asarray(state.params["w"], np.float32),
        b=np.asarray(state.params["b"], np.float32),
        mw=np.asarray(state.master_params["w"], np.float32),
        loss=np.asarray(metrics["loss"], np.float32),
        loss_scale=np.asarray(state.scaler.loss_scale, np.float32),
        unskipped=np.asarray(state.scaler.unskipped, np.int32))
    print(f"RANK_OK {rank} mode={mode} "
          f"loss={float(metrics['loss']):.6f}", flush=True)


if __name__ == "__main__":
    main()
