"""BERT-LAMB recipe's --data-parallel path (the reference's multi-GPU
BERT-LAMB shape: apex DDP + FusedLAMB, here one grad psum over 'data').
"""

import importlib.util
import os

import numpy as np
import pytest

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

_RECIPE = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                       "examples", "bert_lamb", "main_amp.py")


@pytest.fixture(scope="module")
def bl():
    spec = importlib.util.spec_from_file_location("bert_lamb_recipe",
                                                  _RECIPE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BASE = ["--bert-model", "tiny", "--train_batch_size", "8",
        "--max_seq_length", "32", "--max_predictions_per_seq", "4",
        "--max_steps", "4"]


def test_ddp_trains(bl, eight_devices):
    m = bl.main(BASE + ["--data-parallel", "4"])
    assert np.isfinite(float(m["loss"]))
    assert not bool(m["found_inf"])


def test_batch_divisibility_rejected(bl, eight_devices):
    with pytest.raises(SystemExit, match="divide"):
        bl.main(BASE + ["--data-parallel", "3"])
