"""End-to-end DDP facade tests (VERDICT round-1 item 9): N training steps
through DistributedDataParallel.reduce_gradients + the scaler facade must
match make_train_step's integrated path — the reference's recipe shape
(wrap the model, then train manually: examples/simple/distributed/ +
apex/amp README manual loop)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.amp import init_scaler, unscale, update_scale
from apex_tpu.amp.scaler import scale_loss as scale_loss_fn
from apex_tpu.parallel import DistributedDataParallel

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow


@pytest.fixture()
def data_mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("data",))


def _model(p, x):
    return jax.nn.relu(x @ p["w1"]) @ p["w2"]


def _loss(p, batch):
    x, y = batch
    return optax.softmax_cross_entropy_with_integer_labels(
        jnp.asarray(_model(p, x), jnp.float32), y).mean()


def _params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w1": jax.random.normal(k1, (16, 32)) * 0.1,
            "w2": jax.random.normal(k2, (32, 10)) * 0.1}


def _batches(steps, per_rank=4, world=8):
    ks = jax.random.split(jax.random.PRNGKey(1), steps)
    return [(jax.random.normal(k, (per_rank * world, 16)),
             jax.random.randint(jax.random.fold_in(k, 9),
                                (per_rank * world,), 0, 10))
            for k in ks]


@pytest.mark.parametrize("predivide", [1.0, 2.0])
def test_manual_ddp_loop_matches_make_train_step(data_mesh, predivide):
    params = _params()
    steps = 5
    batches = _batches(steps)

    # --- path A: the facade (DDP wrapper + functional scaler, hand loop)
    ddp = DistributedDataParallel(module=_model, axis_name="data",
                                  gradient_predivide_factor=predivide)
    tx = optax.sgd(0.1, momentum=0.9)

    def manual_step(params, opt_state, scaler, batch):
        def scaled(p):
            x, y = batch
            loss = optax.softmax_cross_entropy_with_integer_labels(
                jnp.asarray(ddp(p, x), jnp.float32), y).mean()
            return scale_loss_fn(loss, scaler), loss

        grads, loss = jax.grad(scaled, has_aux=True)(params)
        grads = ddp.reduce_gradients(grads)
        grads, found_inf = unscale(grads, scaler, jnp.float32)

        def do(_):
            upd, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), new_opt

        params2, opt2 = jax.lax.cond(
            found_inf, lambda _: (params, opt_state), do, operand=None)
        return params2, opt2, update_scale(scaler, found_inf)

    run_manual = jax.jit(functools.partial(
        shard_map, mesh=data_mesh,
        in_specs=(P(), P(), P(), (P("data"), P("data"))),
        out_specs=(P(), P(), P()), check_vma=False)(manual_step))

    p_a, opt_a, sc_a = params, tx.init(params), init_scaler("dynamic")
    for b in batches:
        p_a, opt_a, sc_a = run_manual(p_a, opt_a, sc_a, b)

    # --- path B: make_train_step integrated
    policy = amp.resolve_policy("O0", loss_scale="dynamic")
    init_fn, step_fn = amp.make_train_step(
        _loss, optax.sgd(0.1, momentum=0.9), policy,
        grad_average_axis="data", gradient_predivide_factor=predivide)
    run_b = jax.jit(functools.partial(
        shard_map, mesh=data_mesh,
        in_specs=(P(), (P("data"), P("data"))), out_specs=P(),
        check_vma=False)(step_fn))
    st = init_fn(params)
    for b in batches:
        st, _ = run_b(st, b)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_a[k]),
                                   np.asarray(st.params[k]),
                                   rtol=1e-5, atol=1e-6)
    # scaler trajectories agree too (same unskipped count, same scale)
    np.testing.assert_array_equal(np.asarray(sc_a.loss_scale),
                                  np.asarray(st.scaler.loss_scale))


def test_scale_loss_context_facade():
    """The imperative amp.scale_loss context (apex/amp/handle.py) scales by
    the registered scaler's current scale and advances its schedule."""
    amp.initialize((None, None), optimizers=None, opt_level="O2",
                   loss_scale=128.0, verbosity=0)
    with amp.scale_loss(jnp.asarray(2.0)) as scaled:
        assert float(scaled) == 2.0 * 128.0


def test_ddp_allreduce_always_fp32(data_mesh):
    """apex's allreduce_always_fp32: half grads are reduced in fp32 and cast
    back; the result equals the fp32 mean within half precision."""
    ddp = DistributedDataParallel(module=_model, axis_name="data",
                                  allreduce_always_fp32=True)

    @functools.partial(shard_map, mesh=data_mesh, in_specs=P("data"),
                       out_specs=P(), check_vma=False)
    def reduce(gs):
        out = ddp.reduce_gradients({"g": gs[0]})
        return out["g"]

    gs = jnp.arange(8.0, dtype=jnp.bfloat16)[:, None] * jnp.ones(
        (8, 4), jnp.bfloat16)
    out = reduce(gs)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.full((4,), 3.5), rtol=1e-2)
