"""REAL multi-process distributed bootstrap (VERDICT round-4 missing #1).

Everything else in this tier fakes multi-node hermetically (8 virtual
devices in ONE process — SURVEY §5's "multi-GPU faked in one process"
mechanic). The reference's distributed tier ALSO spawns real processes
over real NCCL; this module is that mechanic's TPU analogue: two OS
processes, each owning 4 virtual CPU devices, joined by
``comm.initialize_distributed`` (jax.distributed coordination service,
SURVEY §3.4) into one 8-device world, with ``make_hybrid_mesh`` laying
the 'data' axis across the process boundary — the mesh position that
rides DCN on a real multi-slice pod. The DDP train step must leave every
rank with BITWISE-identical params and scaler state, and the 2-process
trajectory must match the same math run single-process.

Skip policy: if the sandbox refuses the coordination-service sockets the
workers exit 42 with a BOOTSTRAP_FAILED line and the test SKIPS with that
reason recorded — any other failure is a hard fail (anti-silent-skip).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "_jaxdist_worker.py")

_ORACLE_CACHE: list = []


def _single_process_oracle():
    """The shard_map training run on this process's own 8 virtual
    devices — ONE copy (cached), shared by both mode tests; the program
    constants come from the worker module itself."""
    if _ORACLE_CACHE:
        return _ORACLE_CACHE[0]
    import importlib.util as _ilu

    import jax
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    spec = _ilu.spec_from_file_location("_jaxdist_worker", _WORKER)
    w = _ilu.module_from_spec(spec)
    spec.loader.exec_module(w)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "model"))
    axes = ("data", "model")
    params, init_fn, step_fn = w.training_setup()
    state = init_fn(params)
    step = jax.jit(shard_map(step_fn, mesh=mesh,
                             in_specs=(P(), (P(axes), P(axes))),
                             out_specs=(P(), P()), check_vma=False),
                   donate_argnums=(0,))
    metrics = None
    for it in range(w.N_STEPS):
        state, metrics = step(state, w.batch_at(it))
    _ORACLE_CACHE.append((state, metrics))
    return _ORACLE_CACHE[0]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_world(tmp_path, mode="shard_map"):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), f"127.0.0.1:{port}",
             str(tmp_path), mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for r in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for p, out in zip(procs, outs):
        if p.returncode == 42:
            line = next((ln for ln in out.splitlines()
                         if "BOOTSTRAP_FAILED" in ln), "BOOTSTRAP_FAILED")
            pytest.skip(f"sandbox refused jax.distributed bootstrap: {line}")
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "RANK_OK" in out


def test_two_process_ddp_identical_ranks(tmp_path):
    _spawn_world(tmp_path)

    r0 = np.load(tmp_path / "rank0.npz")
    r1 = np.load(tmp_path / "rank1.npz")
    # DDP contract: after N steps every rank holds the SAME model — params,
    # fp32 masters, loss, and the whole scaler trajectory, bitwise
    for key in ("w", "b", "mw", "loss", "loss_scale", "unskipped"):
        np.testing.assert_array_equal(r0[key], r1[key], err_msg=key)
    assert float(r0["loss_scale"]) == 65536.0  # no overflow on this data
    assert np.all(np.isfinite(r0["w"]))

    # and the 2-process world computes the SAME math as one process:
    # the cached single-process oracle, same program constants
    state, metrics = _single_process_oracle()
    np.testing.assert_allclose(
        np.asarray(state.params["w"], np.float32),
        np.asarray(r0["w"], np.float32), rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(float(metrics["loss"]), float(r0["loss"]),
                               rtol=1e-6)


def test_two_process_gspmd_one_global_program(tmp_path):
    """Multi-host GSPMD — the production TPU pattern: ONE global jit
    program (replicated state, batch sharded over the hybrid mesh's
    data×model dims, zero explicit collectives in user code) partitioned
    by XLA across two OS processes. Ranks must end bitwise-identical,
    and the trajectory must match the single-process shard_map oracle
    (different reduction ORDER, same math — allclose)."""
    _spawn_world(tmp_path, mode="gspmd")
    r0 = np.load(tmp_path / "rank0.npz")
    r1 = np.load(tmp_path / "rank1.npz")
    for key in ("w", "b", "mw", "loss", "loss_scale", "unskipped"):
        np.testing.assert_array_equal(r0[key], r1[key], err_msg=key)
    assert float(r0["loss_scale"]) == 65536.0

    state, metrics = _single_process_oracle()
    # The two flavors compute the same MATH with different float
    # reduction orders (global-batch mean vs mean of 8 shard means);
    # once a bf16 model param lands one ulp apart the trajectories
    # genuinely diverge a little, so after N steps this is a 0.1%%
    # sanity anchor — the STRONG invariant is the bitwise cross-rank
    # agreement asserted above.
    np.testing.assert_allclose(
        np.asarray(state.master_params["w"], np.float32),
        np.asarray(r0["mw"], np.float32), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(state.params["w"], np.float32),
        np.asarray(r0["w"], np.float32), rtol=5e-3, atol=2e-3)
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(r0["loss"]), rtol=1e-3)
