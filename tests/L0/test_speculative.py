"""Speculative decoding: n-gram draft-and-verify, bitwise-greedy parity.

The acceptance bar from the speculative-decoding issue, as tests:

- **the parity pin**: greedy speculative output is bitwise identical to
  plain decode across a request stream with prompt lengths below / at /
  straddling chunk boundaries, on BOTH cache layouts, and matches one
  teacher-forcing full recompute (every emitted token is the verify
  program's own greedy target — the structural argument — and the
  verify/decode programs agree token-for-token — the pinned one);
- **acceptance mechanics at the engine level**: a draft equal to the
  plain-decode continuation is fully accepted (tokens == the next K+1
  plain tokens); a draft wrong at position i accepts exactly i and the
  stream CONTINUES correctly through plain decode afterwards — the
  rollback pin: rejected-tail K/V written by the verify step never
  becomes visible;
- **compiled-programs pin**: the verify program is exactly ONE new
  executable — 4 paged (5 contiguous) across a stream that varies
  drafts, offsets, draft lengths and slots (drafting never retraces);
- **chaos composition**: a seeded FaultPlan (verify-site exceptions +
  non-finite injection into a verifying slot) over a speculative run —
  un-faulted requests bitwise vs the fault-free speculative run, zero
  leaked pages at drain, zero new traces;
- drafter units: most-recent-occurrence prompt lookup, n-gram size
  degradation, draft truncation, empty-draft fallbacks, SpecConfig
  validation;
- registry wiring: a scheduler-only registry auto-propagates to a
  registry-less engine (so engine-side counters like
  ``serving.faults.nonfinite`` are never silently dropped), and a loud
  warning fires when both are set and differ.

Everything hermetic on CPU with the tiny test model at policy O0 (the
kernels take their interpret/reference paths — same math, pinned
bitwise against the Pallas paths by the kernel test tiers).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultPolicy, FaultSpec,
                              Request, RequestStatus, Scheduler,
                              SpecConfig, draft_tokens)

pytestmark = pytest.mark.serving

VOCAB = 101
CHUNK = 8
K = 3


def _tiny_lm(max_seq_len=128, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, paged=True, slots=3, seed=5, spec=True,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=128, prefill_len=24,
                  chunk_len=CHUNK, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  spec=SpecConfig(draft_len=K, ngram=2) if spec else None,
                  **kw)


@pytest.fixture(scope="module")
def engines(lm_and_params):
    """One spec-enabled engine per layout, shared module-wide: parity
    comparisons run plain and speculative passes through the SAME
    compiled programs, and the trace pin at the end of the module
    covers every test in between."""
    return {"paged": _mk_engine(lm_and_params, paged=True),
            "contiguous": _mk_engine(lm_and_params, paged=False)}


def _boundary_reqs():
    """Prompt lengths below (5), at (8), straddling one (13) and two
    (21) chunk boundaries at chunk_len=8 — the issue's sweep — with
    budgets that exercise full verify windows AND the endgame
    plain-decode tail."""
    rng = np.random.default_rng(42)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 16), (8, 12), (13, 10), (21, 8)]]


# ------------------------------------------------------------------ drafter
def test_spec_config_validation():
    with pytest.raises(ValueError, match="draft_len"):
        SpecConfig(draft_len=0)
    with pytest.raises(ValueError, match="ngram"):
        SpecConfig(ngram=0)
    with pytest.raises(ValueError, match="min_ngram"):
        SpecConfig(ngram=2, min_ngram=3)
    cfg = SpecConfig(draft_len=4, ngram=3)
    assert (cfg.draft_len, cfg.ngram, cfg.min_ngram) == (4, 3, 1)


def test_draft_tokens_prompt_lookup():
    cfg = SpecConfig(draft_len=3, ngram=2)
    # suffix [1, 2] occurs at index 0; drafts the 3 followers
    assert draft_tokens([1, 2, 3, 9, 1, 2], cfg) == [3, 9, 1]
    # most RECENT occurrence wins (index 4, not 0)
    assert draft_tokens([1, 2, 7, 9, 1, 2, 8, 5, 1, 2], cfg) == [8, 5, 1]
    # followers may overlap into the suffix itself (how repetition
    # drafting works): [5, 6] at index 0 is followed by [7, 5, 6]
    assert draft_tokens([5, 6, 7, 5, 6], cfg) == [7, 5, 6]
    # truncated draft when fewer followers exist than draft_len wants
    assert draft_tokens([7, 7], cfg) == [7]
    # no 2-gram match -> degrade to 1-gram (min_ngram=1 default)
    assert draft_tokens([4, 9, 2, 4, 7, 3, 4], cfg) == [7, 3, 4]
    # nothing repeats at all -> empty draft (plain-decode fallback)
    assert draft_tokens([1, 2, 3, 4, 5], cfg) == []
    # min_ngram=2 refuses the 1-gram fallback
    assert draft_tokens([4, 9, 2, 4, 7, 3, 4],
                        SpecConfig(draft_len=3, ngram=2,
                                   min_ngram=2)) == []
    # max_draft caps below draft_len
    assert draft_tokens([1, 2, 3, 9, 1, 2], cfg, max_draft=1) == [3]
    # too short to match anything: never raises
    assert draft_tokens([7], cfg) == []
    assert draft_tokens([], cfg) == []


def test_draft_repetition_drafts_the_loop():
    # a repeating tail drafts its own continuation — the generated-text
    # case where speculation wins big (tiny greedy models loop). The
    # full-follower-window preference matters exactly here: the newest
    # match ends right next to the sequence end and would truncate
    # every draft to the period length, so the drafter backs up to the
    # most recent occurrence that can fill draft_len.
    cfg = SpecConfig(draft_len=4, ngram=2)
    assert draft_tokens([7, 8, 7, 8, 7, 8], cfg) == [7, 8, 7, 8]
    assert draft_tokens([9] * 8, cfg) == [9, 9, 9, 9]
    # too short for a full window: truncated draft, not an empty one
    assert draft_tokens([9, 9, 9, 9], cfg) == [9]


# ------------------------------------------------ engine-level verify pins
def _plain_greedy(engine, prompt, n):
    """n greedy tokens via prefill + plain decode on slot 0 — the
    reference stream (same compiled programs as the spec path)."""
    engine.reset()
    tok = engine.prefill_chunked(0, prompt)
    out = [tok]
    last = np.zeros(engine.slots, np.int32)
    active = np.zeros(engine.slots, bool)
    active[0] = True
    temps = np.zeros(engine.slots, np.float32)
    for _ in range(n - 1):
        last[0] = out[-1]
        out.append(int(engine.decode_step(last, active, temps)[0]))
    return out


@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_verify_accepts_correct_draft_and_rejects_wrong(engines, layout):
    """A draft equal to the plain continuation accepts fully and the
    returned tokens are the next K+1 plain tokens; a draft wrong at
    position i accepts exactly i tokens; plain decode AFTER the
    rejection reproduces the reference stream — the rejected tail's
    K/V (written into the cache by the verify program) never became
    visible."""
    eng = engines[layout]
    prompt = [3, 17, 91, 42, 8]
    ref = _plain_greedy(eng, prompt, 10)
    offset = len(prompt)

    eng.reset()
    t0 = eng.prefill_chunked(0, prompt)
    assert t0 == ref[0]
    toks, m = eng.verify_step(0, t0, ref[1:1 + K], offset)
    assert m == K, "the true continuation must be fully accepted"
    assert toks.tolist() == ref[1:1 + K + 1]

    # wrong draft at position 2 -> exactly 1 accepted
    eng.reset()
    t0 = eng.prefill_chunked(0, prompt)
    wrong = [ref[1], (ref[2] + 1) % VOCAB, ref[3]]
    toks, m = eng.verify_step(0, t0, wrong, offset)
    assert m == 1
    assert toks.tolist()[:2] == ref[1:3]

    # rollback pin: plain decode continues the reference stream
    out = [ref[0], int(toks[0]), int(toks[1])]
    last = np.zeros(eng.slots, np.int32)
    active = np.zeros(eng.slots, bool)
    active[0] = True
    temps = np.zeros(eng.slots, np.float32)
    while len(out) < len(ref):
        last[0] = out[-1]
        out.append(int(eng.decode_step(last, active, temps)[0]))
    assert out == ref, "stale rejected-tail K/V leaked into decode"

    # short (padded) draft: one executable, acceptance capped at the
    # real draft length
    eng.reset()
    t0 = eng.prefill_chunked(0, prompt)
    toks, m = eng.verify_step(0, t0, ref[1:2], offset)
    assert m == 1 and toks.tolist()[:2] == ref[1:3]


@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_verify_step_validation(engines, layout, lm_and_params):
    eng = engines[layout]
    eng.reset()
    eng.prefill_chunked(0, [1, 2, 3])
    with pytest.raises(ValueError, match="draft length"):
        eng.verify_step(0, 1, [], 3)
    with pytest.raises(ValueError, match="draft length"):
        eng.verify_step(0, 1, [1] * (K + 1), 3)
    with pytest.raises(ValueError, match="slot"):
        eng.verify_step(eng.slots, 1, [1], 3)
    with pytest.raises(ValueError, match="verify window"):
        eng.verify_step(0, 1, [1], eng.max_len - K)   # window spills
    if layout == "paged":
        with pytest.raises(ValueError, match="disagrees"):
            eng.verify_step(0, 1, [1], 7)             # committed len is 3
    no_spec = _mk_engine(lm_and_params, paged=(layout == "paged"),
                         spec=False)
    with pytest.raises(RuntimeError, match="SpecConfig"):
        no_spec.verify_step(0, 1, [1], 3)
    with pytest.raises(ValueError, match="speculative=True requires"):
        Scheduler(no_spec, speculative=True)


def test_engine_spec_validation(lm_and_params):
    m, params = lm_and_params
    with pytest.raises(TypeError, match="SpecConfig"):
        Engine(m, params, slots=1, max_len=32, prefill_len=16, spec=3)
    with pytest.raises(ValueError, match="cannot fit max_len"):
        Engine(m, params, slots=1, max_len=4, prefill_len=4,
               spec=SpecConfig(draft_len=4))


# --------------------------------------------------------- the parity pin
@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_speculative_bitwise_parity_and_recompute(engines, layout,
                                                  lm_and_params):
    """THE acceptance pin: a greedy stream with prompt lengths below /
    at / straddling chunk boundaries served speculative vs plain on the
    same engine — bitwise-identical token streams, real acceptances,
    and agreement with one teacher-forcing full recompute."""
    m, params = lm_and_params
    eng = engines[layout]
    eng.reset()
    plain = _boundary_reqs()
    Scheduler(eng, speculative=False).run(plain)
    base = [list(r.output_tokens) for r in plain]
    assert all(r.spec_drafted == 0 for r in plain), \
        "speculative=False must keep today's path untouched"

    eng.reset()
    reg = telemetry.MetricsRegistry()
    sp = _boundary_reqs()
    Scheduler(eng, registry=reg, speculative=True).run(sp)
    got = [list(r.output_tokens) for r in sp]
    assert got == base, "speculative greedy output diverged from plain"
    snap = reg.snapshot()
    drafted = snap["counters"].get("serving.spec.drafted", 0)
    accepted = snap["counters"].get("serving.spec.accepted", 0)
    assert drafted > 0, "the drafter never fired — the test is vacuous"
    assert accepted > 0, "nothing accepted — speculation never engaged"
    assert accepted == sum(r.spec_accepted for r in sp)
    assert snap["histograms"]["serving.spec.acceptance_rate"]["count"] \
        > 0
    assert "serving.spec.tokens_per_step" in snap["gauges"]

    # teacher-forcing: one full forward re-derives every greedy step
    for r in sp:
        seq = jnp.asarray([list(r.prompt) + r.output_tokens], jnp.int32)
        full = m.apply({"params": params}, seq, train=False)
        want = np.asarray(jnp.argmax(full[0], axis=-1))
        for i, tok in enumerate(r.output_tokens):
            assert tok == int(want[len(r.prompt) - 1 + i]), \
                f"prompt len {len(r.prompt)}: divergence at token {i}"


def test_speculative_with_eos_matches_plain(engines):
    """EOS inside an accepted run truncates exactly where plain decode
    stops (emitted tokens past the EOS are discarded)."""
    eng = engines["paged"]
    eng.reset()
    prompt = [3, 17, 91, 42, 8]
    ref = _plain_greedy(eng, prompt, 8)
    eos = ref[4]                 # finishes mid-stream in both modes
    mk = lambda: [Request(prompt=list(prompt), max_new_tokens=16)]
    eng.reset()
    plain = mk()
    Scheduler(eng, eos_id=eos, speculative=False).run(plain)
    eng.reset()
    sp = mk()
    Scheduler(eng, eos_id=eos, speculative=True).run(sp)
    assert sp[0].output_tokens == plain[0].output_tokens
    assert sp[0].finish_reason == plain[0].finish_reason == "eos"


# --------------------------------------------------------- batched verify
@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_verify_batch_matches_sequential_per_slot(engines, layout):
    """The batched-verify satellite's parity pin: B verify-eligible
    slots through ONE [slots, K+1] call emit bitwise the same tokens
    and acceptance counts as B sequential single-slot verify_step calls
    — the wrapper routes through the SAME executable, so this is the
    per-row-independence guarantee (a slot's verify never reads or
    writes a batchmate's rows), on both layouts."""
    eng = engines[layout]
    prompts = {0: [3, 17, 91, 42, 8], 1: [7, 7, 9, 7, 7, 9, 2],
               2: [11, 4, 11, 4, 11]}
    drafts = {0: [5, 9, 1], 1: [7, 9], 2: [11]}   # varied draft lengths

    def prep():
        eng.reset()
        return {s: eng.prefill_chunked(s, p) for s, p in prompts.items()}

    first = prep()
    toks_b, acc_b = eng.verify_batch(
        {s: (first[s], drafts[s]) for s in prompts})
    assert toks_b.shape == (eng.slots, K + 1)
    assert acc_b.shape == (eng.slots,)
    assert eng.last_verify_finite_slots.all()
    first = prep()
    for s in prompts:
        toks_s, m_s = eng.verify_step(s, first[s], drafts[s],
                                      len(prompts[s]))
        assert int(acc_b[s]) == m_s, f"slot {s}: acceptance diverged"
        assert toks_b[s].tolist() == toks_s.tolist(), \
            f"slot {s}: batched verify diverged from per-slot verify"
    eng.reset()


def test_verify_batch_leaves_nonverifying_slots_untouched(engines):
    """Fixed-shape safety: a decoding slot NOT in the verify batch must
    keep its exact cache bytes — its subsequent plain-decode stream is
    bitwise the reference stream even though a batched verify ran on a
    batchmate in between (paged: the passenger's table-row operand is
    zeroed so writes land on the sentinel; this is the guarantee that
    lets the scheduler verify some slots while others decode)."""
    eng = engines["paged"]
    prompt = [3, 17, 91, 42, 8]
    ref = _plain_greedy(eng, prompt, 8)

    eng.reset()
    t0 = eng.prefill_chunked(0, prompt)             # the bystander
    t1 = eng.prefill_chunked(1, [7, 7, 9, 7, 7, 9, 2])  # the verifier
    eng.verify_batch({1: (t1, [7, 7, 9])})
    out = [t0]
    last = np.zeros(eng.slots, np.int32)
    active = np.zeros(eng.slots, bool)
    active[0] = True
    temps = np.zeros(eng.slots, np.float32)
    while len(out) < len(ref):
        last[0] = out[-1]
        out.append(int(eng.decode_step(last, active, temps)[0]))
    assert out == ref, "a batched verify on slot 1 corrupted slot 0's " \
        "cache"
    eng.reset()


def test_verify_batch_validation(engines):
    eng = engines["paged"]
    eng.reset()
    eng.prefill_chunked(0, [1, 2, 3])
    with pytest.raises(ValueError, match="at least one"):
        eng.verify_batch({})
    with pytest.raises(ValueError, match="draft length"):
        eng.verify_batch({0: (1, [])})
    with pytest.raises(ValueError, match="draft length"):
        eng.verify_batch({0: (1, [1] * (K + 1))})
    with pytest.raises(ValueError, match="slot"):
        eng.verify_batch({eng.slots: (1, [1])})
    eng.reset()


@pytest.mark.parametrize("paged", [True, False])
def test_verify_batch_window_and_offset_raise_on_both_layouts(
        lm_and_params, paged):
    """Loud-failure contract, BOTH layouts (review finding: the
    contiguous path used to mask a spilling window in-program and
    return n_accepted=0 — indistinguishable from a real zero-accept,
    so the caller would emit a token whose K/V never landed): a
    verifying slot whose committed length leaves no room for the
    padded [K+1] window raises BEFORE anything mutates, and a caller
    offset that disagrees with the committed length raises on the
    contiguous layout too (the old per-slot path only checked paged)."""
    m, params = lm_and_params
    eng = Engine(m, params, slots=2, max_len=8, prefill_len=8,
                 chunk_len=8, paged=paged,
                 policy=resolve_policy("O0", verbose=False),
                 spec=SpecConfig(draft_len=K, ngram=2))
    t = eng.prefill_chunked(0, [1, 2, 3, 4, 5])   # committed length 5
    with pytest.raises(ValueError, match="verify window"):
        eng.verify_batch({0: (t, [1, 2])})        # [5, 9) spills 8
    t1 = eng.prefill_chunked(1, [1, 2, 3])        # committed length 3
    with pytest.raises(ValueError, match="disagrees"):
        eng.verify_batch({1: (t1, [1])}, offsets={1: 4})  # fits, drifts
    assert eng.verify_traces == 0, \
        "validation must fire before the program ever traces"
    # tokens_generated counted nothing for the refused calls
    assert eng.tokens_generated == 2              # the prefill tokens


# ------------------------------------------------- compiled-programs pin
@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_exactly_one_new_executable(engines, layout):
    """The compiled-programs pin, updated: across everything this
    module ran on the shared engines — streams varying drafts, offsets,
    slots, draft lengths, plus the monolithic baseline — the verify
    program traced EXACTLY once (drafting never retraces), moving the
    pin 3 -> 4 paged and 4 -> 5 contiguous."""
    eng = engines[layout]
    eng.reset()
    # make sure every program family has actually run at least once
    eng.prefill(0, [5, 9, 2])
    if layout == "contiguous":
        eng.copy_kv(0, 1, 3)
    sched = Scheduler(eng, speculative=True)
    sched.run(_boundary_reqs())
    assert eng.verify_traces == 1, "the verify program retraced"
    assert (eng.chunk_traces, eng.decode_traces, eng.prefill_traces) \
        == (1, 1, 1)
    if layout == "paged":
        assert eng.copy_traces == 0
        assert eng.compiled_programs == 4
    else:
        assert eng.copy_traces == 1
        assert eng.compiled_programs == 5


# ------------------------------------------------------ chaos composition
@pytest.mark.chaos
def test_chaos_composition_speculative(engines):
    """Satellite pin: a seeded FaultPlan — a verify-site exception plus
    non-finite logits routed into a verifying slot — over a speculative
    run. Un-faulted requests are bitwise identical to the fault-free
    SPECULATIVE run, faulted requests reach typed terminals, zero new
    programs traced, zero pages leaked at drain."""
    eng = engines["paged"]
    eng.reset()
    policy = FaultPolicy(backoff_base_s=0.0, audit_every_n=1)
    clean_reqs = _boundary_reqs()
    Scheduler(eng, speculative=True, fault_policy=policy).run(clean_reqs)
    clean = [list(r.output_tokens) for r in clean_reqs]
    traces0 = (eng.chunk_traces, eng.decode_traces, eng.prefill_traces,
               eng.verify_traces)

    eng.reset()
    # tick 1 is DETERMINISTIC: the chaos schedule is identical to the
    # clean one until the first injection, and in the clean schedule
    # slot 0 takes a verify step at tick 1 — so the non-finite spec is
    # routed through the VERIFY program's guard (take_nonfinite), not
    # the decode batch. The verify-site exceptions are sprayed over a
    # tick range because quarantines reshuffle slots afterwards — at
    # least one must land on a live verify call (asserted below).
    plan = FaultPlan(
        [FaultSpec(kind="nonfinite", tick=1, slot=0)]
        + [FaultSpec(kind="exception", tick=t, site="verify")
           for t in range(3, 7)])
    reg = telemetry.MetricsRegistry()
    eng.set_registry(reg)
    sched = Scheduler(eng, registry=reg, speculative=True,
                      fault_policy=policy, fault_plan=plan)
    reqs = _boundary_reqs()
    try:
        done = sched.run(reqs)
    finally:
        eng.set_registry(None)
    assert len(done) == len(reqs)
    assert plan.stats()["injected_exceptions"] >= 1, \
        "no verify-site exception ever fired — the site is dead"
    assert plan.stats()["injected_nonfinite"] == 1
    faulted = [r for r in reqs if r.retries > 0
               or r.status is RequestStatus.FAILED]
    assert faulted, "the plan must actually fault requests"
    for r in reqs:
        assert r.status.terminal
    for i, r in enumerate(reqs):
        if r.status is RequestStatus.FINISHED:
            assert list(r.output_tokens) == clean[i], \
                f"request {i} diverged under chaos"
    # containment + injection added ZERO compiled programs
    assert (eng.chunk_traces, eng.decode_traces, eng.prefill_traces,
            eng.verify_traces) == traces0
    assert reg.snapshot()["counters"]["serving.faults.nonfinite"] >= 1
    assert sched.auditor.audit(eng)["pages_in_use"] == 0
    eng.reset()


# -------------------------------------------------------- registry wiring
def test_scheduler_registry_propagates_to_engine(lm_and_params):
    """Satellite pin (PR 7 NOTE): a scheduler-only registry silently
    missed every engine-emitted metric (serving.faults.nonfinite above
    all). The scheduler now hands its registry to a registry-less
    engine at construction."""
    eng = _mk_engine(lm_and_params, spec=False)
    assert eng._registry is None
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(eng, registry=reg)
    assert eng._registry is reg
    sched.run([Request(prompt=[1, 2, 3], max_new_tokens=2)])
    snap = reg.snapshot()
    # engine-side metrics now land in the scheduler's registry
    assert snap["counters"]["serving.prefill.chunks"] >= 1
    assert snap["counters"]["serving.tokens_generated"] >= 2


def test_scheduler_registry_conflict_logs_loudly(lm_and_params):
    # the package logger keeps propagate=False (log_util), so capture
    # with a handler on the serving logger rather than caplog
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = logging.getLogger("apex_tpu.serving")
    logger.addHandler(handler)
    try:
        eng = _mk_engine(lm_and_params, spec=False)
        eng.set_registry(telemetry.MetricsRegistry())
        other = telemetry.MetricsRegistry()
        Scheduler(eng, registry=other)
    finally:
        logger.removeHandler(handler)
        eng.set_registry(None)
    assert any(r.levelno >= logging.WARNING
               and "DIFFERENT telemetry registries" in r.getMessage()
               for r in records), \
        "conflicting registries must warn loudly"
    assert eng._registry is not other, \
        "a deliberate split must not be overwritten"
