"""apex_tpu.RNN tests — torch-CPU as the numerics oracle.

Mirrors the reference's strategy (apex/RNN cells were validated against
torch.nn RNNs): copy torch's weights into the flax module (names/layouts
match by design) and assert fwd outputs + final states allclose.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from apex_tpu.RNN import GRU, LSTM, ReLU, Tanh, mLSTM  # noqa: E402


def _params_from_torch(mod):
    return {name: jnp.asarray(p.detach().numpy())
            for name, p in mod.named_parameters()}


def _run_pair(torch_cls, jax_cls, mode_kwargs, T=7, B=3, F=10, H=8):
    torch.manual_seed(0)
    tm = torch_cls(F, H, **mode_kwargs)
    params = _params_from_torch(tm)
    jm = jax_cls(input_size=F, hidden_size=H, **mode_kwargs)
    x = np.random.RandomState(1).randn(T, B, F).astype(np.float32)
    if mode_kwargs.get("batch_first"):
        x = np.transpose(x, (1, 0, 2))
    with torch.no_grad():
        t_out, t_hid = tm(torch.from_numpy(x))
    j_out, j_hid = jm.apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(j_out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)
    if isinstance(t_hid, tuple):
        for t_h, j_h in zip(t_hid, j_hid):
            np.testing.assert_allclose(np.asarray(j_h), t_h.numpy(),
                                       rtol=1e-4, atol=1e-5)
    else:
        np.testing.assert_allclose(np.asarray(j_hid), t_hid.numpy(),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("kwargs", [
    {},
    {"num_layers": 2},
    {"bidirectional": True},
    {"num_layers": 2, "bidirectional": True, "batch_first": True},
    {"bias": False},
])
def test_lstm_matches_torch(kwargs):
    _run_pair(torch.nn.LSTM, LSTM, kwargs)


@pytest.mark.parametrize("kwargs", [{}, {"num_layers": 2},
                                    {"bidirectional": True}])
def test_gru_matches_torch(kwargs):
    _run_pair(torch.nn.GRU, GRU, kwargs)


def test_vanilla_rnn_matches_torch():
    _run_pair(lambda F, H, **kw: torch.nn.RNN(F, H, nonlinearity="tanh", **kw),
              Tanh, {})
    _run_pair(lambda F, H, **kw: torch.nn.RNN(F, H, nonlinearity="relu", **kw),
              ReLU, {"num_layers": 2})


def test_lstm_initial_hidden():
    T, B, F, H = 5, 2, 6, 4
    torch.manual_seed(2)
    tm = torch.nn.LSTM(F, H)
    params = _params_from_torch(tm)
    jm = LSTM(input_size=F, hidden_size=H)
    rs = np.random.RandomState(3)
    x = rs.randn(T, B, F).astype(np.float32)
    h0 = rs.randn(1, B, H).astype(np.float32)
    c0 = rs.randn(1, B, H).astype(np.float32)
    with torch.no_grad():
        t_out, _ = tm(torch.from_numpy(x),
                      (torch.from_numpy(h0), torch.from_numpy(c0)))
    j_out, _ = jm.apply({"params": params}, jnp.asarray(x),
                        (jnp.asarray(h0), jnp.asarray(c0)))
    np.testing.assert_allclose(np.asarray(j_out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)


def _np_mlstm_ref(x, p, H):
    """numpy oracle for apex/RNN/cells.py — mLSTMCell."""
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    T, B = x.shape[0], x.shape[1]
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    ys = []
    for t in range(T):
        m = (x[t] @ p["weight_mih_l0"].T) * (h @ p["weight_mhh_l0"].T)
        gates = (x[t] @ p["weight_ih_l0"].T + p["bias_ih_l0"]
                 + m @ p["weight_hh_l0"].T + p["bias_hh_l0"])
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        ys.append(h)
    return np.stack(ys), h, c


def test_mlstm_matches_numpy_reference():
    T, B, F, H = 6, 2, 5, 4
    jm = mLSTM(input_size=F, hidden_size=H)
    x = np.random.RandomState(4).randn(T, B, F).astype(np.float32)
    variables = jm.init(jax.random.PRNGKey(0), jnp.asarray(x))
    p = {k: np.asarray(v) for k, v in variables["params"].items()}
    j_out, (j_h, j_c) = jm.apply(variables, jnp.asarray(x))
    ref_y, ref_h, ref_c = _np_mlstm_ref(x, p, H)
    np.testing.assert_allclose(np.asarray(j_out), ref_y, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(j_h[0]), ref_h, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(j_c[0]), ref_c, rtol=1e-4,
                               atol=1e-5)


def test_bf16_io_fp32_gates():
    jm = LSTM(input_size=8, hidden_size=8, dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 8), jnp.float32)
    variables = jm.init(jax.random.PRNGKey(1), x)
    out, (h, c) = jm.apply(variables, x)
    assert out.dtype == jnp.bfloat16 and h.dtype == jnp.bfloat16
    # fp32 reference from the same params stays within bf16 tolerance
    jm32 = LSTM(input_size=8, hidden_size=8)
    out32, _ = jm32.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(out32), rtol=0.1, atol=0.05)


def test_grad_flows():
    jm = GRU(input_size=6, hidden_size=5, num_layers=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 2, 6), jnp.float32)
    variables = jm.init(jax.random.PRNGKey(1), x)

    def loss(params):
        out, _ = jm.apply({"params": params}, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(variables["params"])
    total = sum(float(jnp.sum(jnp.abs(v))) for v in g.values())
    assert np.isfinite(total) and total > 0
