"""Content-addressed KV prefix reuse (PR 5), hermetic.

The acceptance bar from the issue, as tests:

- a prefix-cache HIT is bitwise token-exact against BOTH the cold
  chunked path and one teacher-forcing full recompute, for shared
  prefixes below / at / straddling a block boundary (and for a prompt
  that is entirely cached, where the final block must still prefill —
  the copy program produces no logits to sample from);
- a request stream exercising hit, miss, eviction AND the monolithic
  baseline is served by exactly FOUR compiled programs (chunk prefill +
  decode + monolithic prefill + the KV row-copy), pinned by trace
  counters;
- LRU eviction with refcount pinning: a prefix in use by a live slot is
  never evicted, and a full, fully-pinned pool degrades gracefully to
  the cold path (request served, retention skipped, ``pool_full``
  counted);
- telemetry carries ``serving.prefix.*`` and the per-request completion
  record carries ``reused_tokens``.

Everything runs on CPU with a tiny model at policy O0 (exact fp32), the
same shared-program discipline as test_serving.py: the hit path and the
cold path literally execute the same XLA programs, so exactness is
bitwise, not approximately.

These engines are built ``paged=False`` on purpose: this file pins the
CONTIGUOUS layout's prefix machinery (pool rows, the compiled row-copy,
refcount pinning, the exactly-FOUR-programs discipline), which the
paged default keeps as its parity oracle. The paged layout's prefix
story — copy-on-write page sharing, zero-copy hits, the THREE-program
pin — lives in tests/L0/test_paged_kv.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, PrefixCache, PrefixMatch, Request,
                              Scheduler)

pytestmark = pytest.mark.serving

VOCAB = 101
CHUNK = 8


def _tiny_lm(max_seq_len=128, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, pool=2, slots=3, seed=5):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=128, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=False,
                  policy=resolve_policy("O0", verbose=False), seed=seed)


@pytest.fixture(scope="module")
def pool2_pair(lm_and_params):
    """One retain-capable engine + one cold-reference engine (identical
    geometry, pool=2), shared across the e2e tests — each test starts
    from reset(clear_prefixes=True), and jit caching means the compile
    cost is paid once for the whole module."""
    return _mk_engine(lm_and_params), _mk_engine(lm_and_params)


@pytest.fixture(scope="module")
def pool1_engine(lm_and_params):
    """Shared 1-row-pool engine (eviction/pool-full pressure tests)."""
    return _mk_engine(lm_and_params, pool=1)


# --------------------------------------------------- host-side PrefixCache
def _pc(pool=2):
    return PrefixCache(block_len=4, pool_rows=range(8, 8 + pool))


def test_match_is_block_aligned_and_capped_below_the_prompt():
    pc = _pc()
    copies = []
    assert pc.register(list(range(1, 11)),
                       lambda row, n: copies.append((row, n))) \
        == "registered"
    assert copies == [(9, 8)]       # 10 tokens -> 2 full blocks retained
    # identical 8-token prefix, longer prompt: match the 2 blocks
    m = pc.match(list(range(1, 9)) + [77, 78, 79])
    assert (m.row, m.length) == (9, 8)
    # the whole prompt cached (exact 8 tokens): cap at aligned(7) = 4 —
    # the final block must prefill to produce the first token's logits
    m = pc.match(list(range(1, 9)))
    assert m.length == 4
    # shares only one block
    assert pc.match([1, 2, 3, 4] + [9, 9, 9, 9, 9]).length == 4
    # diverges inside the first block: miss
    assert pc.match([1, 2, 3, 99, 5, 6, 7, 8, 9]) is None
    # shorter than one block + 1: nothing block-aligned to reuse
    assert pc.match([1, 2, 3, 4]) is None
    assert pc.hits == 3 and pc.misses == 2
    assert pc.tokens_reused == 8 + 4 + 4


def test_probe_reads_like_match_but_mutates_nothing():
    """The router's affinity probe: identical verified-longest-prefix
    answer as match(), with ZERO bookkeeping — no hit/miss counters, no
    LRU refresh, no refcounts. A probe that counted would poison every
    non-chosen replica's hit_rate N-1 times per routed request."""
    pc = _pc()
    pc.register(list(range(1, 11)), lambda row, n: None)
    prompt = list(range(1, 9)) + [77, 78, 79]
    assert pc.probe(prompt) == pc.match(prompt).length == 8
    stats0 = pc.stats()
    clock0 = pc._entries[9].last_used
    # hits, misses and LRU order are all untouched by any probe outcome
    assert pc.probe(prompt) == 8
    assert pc.probe([5, 5, 5, 5, 5]) == 0          # a miss probes as 0
    assert pc.probe(prompt, keys=pc.block_keys(prompt, 2)) == 8
    assert pc.stats() == stats0
    assert pc._entries[9].last_used == clock0
    # and the verified-tokens guarantee holds: a would-be hash hit over
    # different tokens probes as 0, never a wrong length
    assert pc.probe([1, 2, 3, 99] + list(range(5, 12))) == 0


def test_stats_since_reads_window_deltas_across_warm_resets():
    """The counters are run-scoped on purpose (they survive clear() and
    warm engine resets), so per-window accounting — the router's
    per-replica affinity rates, the bench's measured windows — must be
    a delta: stats_since(baseline) isolates the window, where reading
    hit_rate directly would blend every prior window in."""
    pc = _pc()
    pc.register([1] * 8, lambda row, n: None)
    assert pc.match([1] * 9) is not None            # warmup hit
    assert pc.match([7] * 9) is None                # warmup miss
    base = pc.stats()
    # a warm reset drops entries but NOT counters — the PR 11 quirk
    pc.clear()
    assert pc.hits == 1 and pc.misses == 1
    pc.register([2] * 8, lambda row, n: None)
    assert pc.match([2] * 12) is not None
    assert pc.match([2] * 12) is not None
    assert pc.match([9] * 9) is None
    delta = pc.stats_since(base)
    assert delta["hits"] == 2 and delta["misses"] == 1
    assert delta["hit_rate"] == pytest.approx(2 / 3)
    assert delta["registrations"] == 1
    assert delta["tokens_reused"] == 16
    # the cumulative view is (deliberately) different from the window's
    assert pc.hit_rate == pytest.approx(3 / 5)
    # occupancy is state, not a counter: reported as-of-now
    assert delta["entries"] == pc.size == 1
    # an empty window reads all-zero, hit_rate 0.0 (not NaN/raise)
    empty = pc.stats_since(pc.stats())
    assert empty["hits"] == empty["misses"] == 0
    assert empty["hit_rate"] == 0.0


def test_register_dedupes_and_rejects_too_short():
    pc = _pc()
    calls = []
    fn = lambda row, n: calls.append((row, n))
    assert pc.register([1, 2, 3], fn) == "too_short"
    assert pc.register(list(range(1, 10)), fn) == "registered"
    # same aligned prefix again (different tail): no second copy
    assert pc.register(list(range(1, 9)) + [55], fn) == "duplicate"
    assert len(calls) == 1 and pc.registrations == 1


def test_lru_eviction_prefers_least_recently_used():
    pc = _pc(pool=2)
    fn = lambda row, n: None
    a, b, c = ([1] * 8), ([2] * 8), ([3] * 8)
    assert pc.register(a, fn) == "registered"
    assert pc.register(b, fn) == "registered"
    assert pc.match(a + [7]) is not None       # refresh A
    assert pc.register(c, fn) == "registered"  # pool full -> evict LRU: B
    assert pc.evictions == 1
    assert pc.match(b + [7]) is None           # B gone
    assert pc.match(a + [7]) is not None       # A survived (recently used)
    assert pc.match(c + [7]) is not None


def test_refcount_pins_against_eviction_and_degrades_when_all_pinned():
    pc = _pc(pool=2)
    fn = lambda row, n: None
    a, b, c, d = ([1] * 8), ([2] * 8), ([3] * 8), ([4] * 8)
    pc.register(a, fn)
    pc.register(b, fn)
    ma = pc.match(a + [7])
    mb = pc.match(b + [7])
    pc.acquire(ma)                  # A pinned by a live slot
    assert pc.register(c, fn) == "registered"   # evicts B (refcount 0)
    assert pc.match(a + [7]) is not None, "pinned entry was evicted"
    mc = pc.match(c + [7])
    pc.acquire(mc)                  # now A and C both pinned
    assert pc.register(d, fn) == "pool_full"    # graceful degradation
    assert pc.pool_full == 1 and pc.evictions == 1
    pc.release(ma)
    assert pc.register(d, fn) == "registered"   # A evictable again
    assert pc.match(a + [7]) is None
    pc.release(mb)                  # releasing an evicted match: no-op


def test_eviction_rebinds_shared_shorter_prefix_keys():
    """A shorter shared prefix addressed by an evicted entry is still
    resident inside a surviving longer entry — eviction must rebind the
    key, not orphan the depth."""
    pc = _pc(pool=2)
    fn = lambda row, n: None
    base = [5, 5, 5, 5]
    pc.register(base + [1, 1, 1, 1], fn)        # owns H_1 (base)
    pc.register(base + [2, 2, 2, 2], fn)        # same H_1 kept by first
    pc.register([9] * 8, fn)                    # evicts the LRU (first)
    m = pc.match(base + [7, 7, 7, 7, 7])
    assert m is not None and m.length == 4, \
        "depth-1 key orphaned by eviction despite a surviving cover"


def test_hash_collision_cannot_fake_a_hit(monkeypatch):
    import apex_tpu.serving.prefix_cache as mod

    monkeypatch.setattr(mod, "_roll", lambda h, block: 42)  # all collide
    pc = _pc(pool=2)
    pc.register([1] * 8, lambda row, n: None)
    assert pc.match([2] * 9) is None    # same key, different tokens
    m = pc.match([1] * 9)
    assert m is not None                # real content still matches


def test_copy_failure_does_not_leak_the_pool_row():
    pc = _pc(pool=1)

    def boom(row, n):
        raise RuntimeError("device fell over")

    with pytest.raises(RuntimeError):
        pc.register([1] * 8, boom)
    assert pc.register([1] * 8, lambda row, n: None) == "registered"


def test_prefix_cache_validates():
    with pytest.raises(ValueError, match="block_len"):
        PrefixCache(block_len=0, pool_rows=[1])
    with pytest.raises(ValueError, match="distinct"):
        PrefixCache(block_len=4, pool_rows=[1, 1])


# -------------------------------------------------------- engine + copy
def test_engine_copy_kv_validation(lm_and_params, pool2_pair):
    eng, _ = pool2_pair                 # 3 slots + 2 pool rows
    with pytest.raises(ValueError, match="copy rows"):
        eng.copy_kv(0, 5, 4)
    with pytest.raises(ValueError, match="must differ"):
        eng.copy_kv(2, 2, 4)
    with pytest.raises(ValueError, match="copy length"):
        eng.copy_kv(0, 3, 0)
    with pytest.raises(ValueError, match="copy length"):
        eng.copy_kv(0, 3, 129)
    with pytest.raises(ValueError, match="prefix_pool"):
        _mk_engine(lm_and_params, pool=-1)


def test_scheduler_retain_prefixes_validation(lm_and_params, pool2_pair):
    eng_no_pool = _mk_engine(lm_and_params, pool=0)   # never traced: cheap
    with pytest.raises(ValueError, match="prefix_pool"):
        Scheduler(eng_no_pool, retain_prefixes=True)
    with pytest.raises(ValueError, match="chunked"):
        Scheduler(pool2_pair[0], retain_prefixes=True, chunked=False)


# --------------------------------------------------- end-to-end exactness
def _cases():
    """(shared_prefix_len, expected_reuse_on_hit) for prefixes below /
    at / straddling one block boundary and spanning two blocks, at
    CHUNK=8. Tails are 3 tokens, so e.g. pre=13 registers aligned(16)=16
    donor tokens of which only the first block matches the next prompt."""
    rng = np.random.default_rng(42)
    out = []
    for pre_len, want in [(5, 0), (8, 8), (13, 8), (16, 16)]:
        pre = list(rng.integers(1, VOCAB, size=pre_len))
        tail_a = list(rng.integers(1, VOCAB, size=3))
        tail_b = list(rng.integers(1, VOCAB, size=3))
        out.append((pre + tail_a, pre + tail_b, want))
    return out


def test_prefix_hit_bitwise_exact_vs_cold_and_recompute(lm_and_params,
                                                        pool2_pair):
    """The tentpole acceptance bar: after request A registers its
    prefix, request B (same shared prefix, different tail) is served
    from the cache — and its greedy tokens are bitwise identical to a
    retention-off engine's AND to one teacher-forcing full recompute."""
    m, params = lm_and_params
    eng_hot, eng_cold = pool2_pair
    eng_hot.reset(clear_prefixes=True)
    eng_cold.reset()
    sched_hot = Scheduler(eng_hot, retain_prefixes=True)
    sched_cold = Scheduler(eng_cold, retain_prefixes=False)
    for prompt_a, prompt_b, want_reuse in _cases():
        (ra,) = sched_hot.run([Request(prompt=prompt_a, max_new_tokens=6)])
        (rb,) = sched_hot.run([Request(prompt=prompt_b, max_new_tokens=6)])
        assert rb.reused_tokens == want_reuse, \
            f"prefix len {len(prompt_a) - 3}: reused {rb.reused_tokens}"
        assert ra.reused_tokens == 0
        (cb,) = sched_cold.run([Request(prompt=prompt_b,
                                        max_new_tokens=6)])
        assert rb.output_tokens == cb.output_tokens, \
            f"hit path diverged from cold (prefix len {len(prompt_a) - 3})"
        # skipped chunks are real: the hit ran fewer prefill steps
        assert rb.chunks == eng_hot.chunks_for(len(prompt_b)) \
            - want_reuse // CHUNK
        # teacher-forcing recompute: one full forward re-derives every
        # greedy step (identical-program discipline of test_serving.py)
        seq = jnp.asarray([list(prompt_b) + rb.output_tokens], jnp.int32)
        full = m.apply({"params": params}, seq, train=False)
        want = np.asarray(jnp.argmax(full[0], axis=-1))
        for i, tok in enumerate(rb.output_tokens):
            assert tok == int(want[len(prompt_b) - 1 + i]), \
                f"recompute divergence at token {i}"


def test_fully_cached_prompt_still_prefills_its_final_block(pool2_pair):
    """A prompt whose every token is cached must still run >= 1 chunk:
    the copy program moves K/V but samples nothing — the first output
    token's logits only exist if the last block goes through chunk
    prefill. The cap (aligned(n-1)) enforces exactly that."""
    eng, eng_cold = pool2_pair
    eng.reset(clear_prefixes=True)
    eng_cold.reset()
    sched = Scheduler(eng, retain_prefixes=True)
    prompt = list(np.random.default_rng(3).integers(1, VOCAB, size=16))
    sched.run([Request(prompt=prompt, max_new_tokens=4)])
    (r2,) = sched.run([Request(prompt=list(prompt), max_new_tokens=4)])
    assert r2.reused_tokens == 8            # aligned(15), not 16
    assert r2.chunks == 1
    (cold,) = Scheduler(eng_cold, retain_prefixes=False).run(
        [Request(prompt=list(prompt), max_new_tokens=4)])
    assert r2.output_tokens == cold.output_tokens


def test_exactly_four_compiled_programs_over_hit_miss_evict(pool1_engine):
    """The compiled-program pin, one up from PR 4's three: a stream
    driving hits, misses, registrations and LRU evictions through a
    1-row pool, plus the monolithic baseline, traces exactly one chunk-
    prefill + one decode + one monolithic prefill + one KV row-copy
    program — the copy is slot-, direction- and length-agnostic."""
    eng = pool1_engine
    eng.reset(clear_prefixes=True)
    pc = eng.prefix_cache
    hits0, miss0, evic0 = pc.hits, pc.misses, pc.evictions
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(1)
    pre1 = list(rng.integers(1, VOCAB, size=8))
    pre2 = list(rng.integers(1, VOCAB, size=16))
    stream = [
        pre1 + [7, 8],            # miss, registers pre1
        pre1 + [9],               # hit (copy pool->slot)
        pre2 + [3],               # miss, registers pre2 (evicts pre1)
        pre1[:5] + [5, 6],        # miss (evicted; too short to register)
        pre2 + [1, 2, 3],         # hit at 16
    ]
    for p in stream:
        sched.run([Request(prompt=p, max_new_tokens=3)])
    assert (pc.hits - hits0, pc.misses - miss0) == (2, 3)
    assert pc.evictions - evic0 >= 1
    eng.prefill(0, [5, 9, 2])     # the monolithic baseline still compiles
    assert (eng.chunk_traces, eng.decode_traces, eng.prefill_traces,
            eng.copy_traces) == (1, 1, 1, 1)
    assert eng.compiled_programs == 4


def test_pool_full_with_live_pins_degrades_to_cold_path(pool1_engine):
    """Every pool row pinned by a live slot: a new registration is
    skipped (pool_full), nothing is evicted, and the request itself is
    served normally — graceful degradation, not an error."""
    eng = pool1_engine
    eng.reset(clear_prefixes=True)
    pool_full0, evic0 = eng.prefix_cache.pool_full, \
        eng.prefix_cache.evictions
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(eng, retain_prefixes=True, registry=reg)
    rng = np.random.default_rng(9)
    pre = list(rng.integers(1, VOCAB, size=8))
    sched.run([Request(prompt=pre + [1], max_new_tokens=2)])
    # b hits pre and stays live (big budget, stepped manually): its pin
    # holds the only pool row
    b = Request(prompt=pre + [2], max_new_tokens=50)
    sched.submit(b)
    while b.status != "running":
        sched.step()
    assert b.reused_tokens == 8
    other = list(rng.integers(1, VOCAB, size=9))
    c = Request(prompt=other, max_new_tokens=2)
    sched.submit(c)
    while c.status not in ("finished", "expired"):   # b (budget 50) outlives c
        sched.step()
    assert b.status == "running", "pin holder must still be live"
    assert c.status == "finished" and len(c.output_tokens) == 2
    pc = eng.prefix_cache
    assert pc.pool_full - pool_full0 >= 1 and pc.evictions == evic0
    assert pc.match(pre + [3]) is not None, "pinned entry evicted"
    assert reg.snapshot()["counters"]["serving.prefix.pool_full"] >= 1
    # draining b releases the pin; the next registration may now evict
    while sched.pending:
        sched.step()
    (d,) = sched.run([Request(prompt=other, max_new_tokens=2)])
    assert eng.prefix_cache.evictions == evic0 + 1


def test_prefix_telemetry_and_request_records(pool2_pair):
    reg = telemetry.MetricsRegistry()
    eng, _ = pool2_pair
    eng.reset(clear_prefixes=True)
    eng.set_registry(reg)
    sched = Scheduler(eng, retain_prefixes=True, registry=reg)
    rng = np.random.default_rng(11)
    pre = list(rng.integers(1, VOCAB, size=16))
    reqs = [Request(prompt=pre + [1], max_new_tokens=3),
            Request(prompt=pre + [2, 3], max_new_tokens=3)]
    try:
        sched.run([reqs[0]])
        sched.run([reqs[1]])
    finally:
        eng.set_registry(None)
    snap = reg.snapshot()
    c = snap["counters"]
    assert c["serving.prefix.hits"] == 1
    assert c["serving.prefix.misses"] == 1
    assert c["serving.prefix.tokens_reused"] == 16
    assert c["serving.prefix.chunks_skipped"] == 2
    assert c["serving.prefix.registrations"] == 1   # second is duplicate
    # the gauge tracks the cache's cumulative rate (shared engine: the
    # pcache's counters span the module, the registry's are this test's)
    assert snap["gauges"]["serving.prefix.hit_rate"] \
        == pytest.approx(eng.prefix_cache.hit_rate)
    assert snap["histograms"]["serving.prefix.copy_s"]["count"] >= 2
    recs = {rec["uid"]: rec for rec in reg.records
            if rec.get("tag") == "serving.request"}
    assert recs[reqs[0].uid]["reused_tokens"] == 0
    assert recs[reqs[1].uid]["reused_tokens"] == 16
    assert recs[reqs[1].uid]["chunks_per_prompt"] == 1


def test_reset_keeps_warm_prefixes_unless_cleared(pool2_pair):
    eng, _ = pool2_pair
    eng.reset(clear_prefixes=True)
    sched = Scheduler(eng, retain_prefixes=True)
    pre = list(np.random.default_rng(13).integers(1, VOCAB, size=8))
    sched.run([Request(prompt=pre + [1], max_new_tokens=2)])
    eng.reset()
    assert eng.lengths()[:eng.slots].tolist() == [0, 0, 0]
    (r,) = Scheduler(eng, retain_prefixes=True).run(
        [Request(prompt=pre + [2], max_new_tokens=2)])
    assert r.reused_tokens == 8, "reset() must not drop warm prefixes"
    eng.reset(clear_prefixes=True)
    assert eng.prefix_cache.size == 0
    (r2,) = Scheduler(eng, retain_prefixes=True).run(
        [Request(prompt=pre + [3], max_new_tokens=2)])
    assert r2.reused_tokens == 0
