"""Sharded checkpoint save/restore (utils/sharded_checkpoint.py).

Hermetic multi-device version of the pod pattern: shard a pytree over the
8-device CPU mesh, save per-process shard files, restore under the same and
under a DIFFERENT sharding (resharded restore), and through the amp state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils.sharded_checkpoint import load_sharded, save_sharded


@pytest.fixture()
def mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("data",))


def _sharded_state(mesh, spec_w=P("data", None)):
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(16, 8), jnp.float32)
    b = jnp.asarray(rng.randn(4), jnp.float32)
    step_count = jnp.asarray(3, jnp.int32)
    state = {
        "w": jax.device_put(w, NamedSharding(mesh, spec_w)),
        "b": jax.device_put(b, NamedSharding(mesh, P())),   # replicated
        "count": step_count,
    }
    return state, {"w": np.asarray(w), "b": np.asarray(b), "count": 3}


def test_roundtrip_same_sharding(mesh, tmp_path):
    state, ref = _sharded_state(mesh)
    save_sharded(str(tmp_path), state, step=7)
    restored, step = load_sharded(str(tmp_path), state)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(restored["w"]), ref["w"])
    np.testing.assert_array_equal(np.asarray(restored["b"]), ref["b"])
    assert int(restored["count"]) == ref["count"]
    # sharding preserved from the template
    assert restored["w"].sharding.spec == P("data", None)


def test_resharded_restore(mesh, tmp_path):
    """Save sharded over rows, restore sharded over COLUMNS — the topology-
    change case. Values must be identical; placement must follow template."""
    state, ref = _sharded_state(mesh, spec_w=P("data", None))
    save_sharded(str(tmp_path), state, step=1)

    template = dict(state)
    template["w"] = jax.device_put(
        jnp.zeros_like(state["w"]), NamedSharding(mesh, P(None, "data")))
    restored, _ = load_sharded(str(tmp_path), template)
    np.testing.assert_array_equal(np.asarray(restored["w"]), ref["w"])
    assert restored["w"].sharding.spec == P(None, "data")


def test_shape_mismatch_raises(mesh, tmp_path):
    state, _ = _sharded_state(mesh)
    save_sharded(str(tmp_path), state)
    bad = dict(state)
    bad["w"] = jnp.zeros((8, 4), jnp.float32)
    with pytest.raises(ValueError, match="shape"):
        load_sharded(str(tmp_path), bad)


def test_dtype_mismatch_raises(mesh, tmp_path):
    """Restoring into a different precision configuration must fail loudly
    (same contract as load_checkpoint), never silently keep the saved
    dtype."""
    state, _ = _sharded_state(mesh)
    save_sharded(str(tmp_path), state)
    bad = dict(state)
    bad["w"] = jax.device_put(
        jnp.zeros((16, 8), jnp.bfloat16),
        state["w"].sharding)
    with pytest.raises(ValueError, match="dtype"):
        load_sharded(str(tmp_path), bad)


def test_stale_shard_files_ignored(mesh, tmp_path):
    """A stale shards_p*.npz from an earlier save with a different process
    count must not leak into the restore — load reads exactly the files the
    manifest names."""
    state, ref = _sharded_state(mesh)
    save_sharded(str(tmp_path), state, step=5)
    # plant a stale file from a fictitious second process with junk data
    np.savez(str(tmp_path / "shards_p1.npz"),
             __step__=np.asarray(3, np.int64),
             leaf0_s0=np.full(64, 255, np.uint8),
             leaf0_s0_idx=np.asarray([[0, 2], [0, 8]], np.int64))
    restored, step = load_sharded(str(tmp_path), state)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]), ref["w"])


def test_step_stamp_mismatch_raises(mesh, tmp_path):
    """A preempted/mixed save (manifest step != shard-file step) must error
    instead of restoring mixed-step weights."""
    import json as _json
    state, _ = _sharded_state(mesh)
    save_sharded(str(tmp_path), state, step=5)
    meta_path = tmp_path / "sharded_meta.json"
    meta = _json.loads(meta_path.read_text())
    meta["step"] = 6
    meta_path.write_text(_json.dumps(meta))
    with pytest.raises(ValueError, match="step"):
        load_sharded(str(tmp_path), state)


def test_leaf_count_mismatch_raises(mesh, tmp_path):
    state, _ = _sharded_state(mesh)
    save_sharded(str(tmp_path), state)
    with pytest.raises(ValueError, match="leaves"):
        load_sharded(str(tmp_path), {"w": state["w"]})


def test_amp_state_roundtrip(mesh, tmp_path):
    """The production shape: an amp train state with dp-sharded params
    survives save → restore and continues training identically."""
    from apex_tpu import amp
    from apex_tpu.optimizers import fused_sgd

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ jnp.asarray(p["w"], x.dtype)
        return jnp.mean((jnp.asarray(pred, jnp.float32) - y) ** 2)

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_sgd(0.1), policy)
    params = {"w": jnp.ones((8, 4), jnp.float32)}
    state = init_fn(params)
    x = jnp.ones((4, 8)); y = jnp.zeros((4, 4))
    state, _ = jax.jit(step_fn)(state, (x, y))

    save_sharded(str(tmp_path), state, step=1)
    restored, _ = load_sharded(str(tmp_path), state)

    next_a, ma = jax.jit(step_fn)(state, (x, y))
    next_b, mb = jax.jit(step_fn)(restored, (x, y))
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(next_a.params),
                    jax.tree_util.tree_leaves(next_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_sharded_checkpointer(mesh, tmp_path):
    """Async variant: snapshot on the caller's thread, write in background;
    wait() surfaces write failures; result identical to the sync save."""
    from apex_tpu.utils.sharded_checkpoint import AsyncShardedCheckpointer

    state, ref = _sharded_state(mesh)
    ck = AsyncShardedCheckpointer()
    ck.save(str(tmp_path), state, step=9)
    ck.wait()
    restored, step = load_sharded(str(tmp_path), state)
    assert step == 9
    np.testing.assert_array_equal(np.asarray(restored["w"]), ref["w"])

    # write failure surfaces on wait (unwritable directory)
    bad = tmp_path / "f"
    bad.write_text("not a dir")
    ck2 = AsyncShardedCheckpointer()
    ck2.save(str(bad), state, step=1)
    with pytest.raises(Exception):
        ck2.wait()
