"""Tuned-key lint: every block knob the kernel/serving tier references
must exist in the packaged tuned tables (or be explicitly allowlisted).

The override registry (:mod:`apex_tpu.kernels.vmem`) is stringly typed:
``get_override("decode.blokc_k", ...)`` is not an error, it is a silent
fall-through to the untuned default — a typo'd key costs real tokens/s
on silicon and nothing ever flags it. This lint closes the loop: the
set of key literals referenced by ``apex_tpu/kernels/`` and
``apex_tpu/serving/`` source must be a subset of the union of keys
across ``apex_tpu/kernels/tuned/*.json`` plus the documented
``EXPLICITLY_DEFAULTED`` set, and the tables must not carry keys no
code consumes (a stale table row is a sweep that no longer tunes
anything).
"""

import glob
import json
import os
import re

import pytest

pytestmark = pytest.mark.serving

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
TUNED_DIR = os.path.join(ROOT, "apex_tpu", "kernels", "tuned")
SCAN_DIRS = [os.path.join(ROOT, "apex_tpu", "kernels"),
             os.path.join(ROOT, "apex_tpu", "serving")]

# Keys a call site may reference without a packaged tuned value: add an
# entry here ONLY with a comment saying why the heuristic default is the
# intended production value.
EXPLICITLY_DEFAULTED: set = set()


def _table_keys():
    keys = set()
    files = glob.glob(os.path.join(TUNED_DIR, "*.json"))
    assert files, f"no tuned tables under {TUNED_DIR}"
    for path in files:
        with open(path) as f:
            keys |= set(json.load(f))
    return keys


def _referenced_keys(prefixes):
    """Quoted ``family.knob`` literals in the scanned sources, filtered
    to known tuned-key families so einsum specs / file names / metric
    names never false-positive."""
    pat = re.compile(r'["\']([a-z0-9_]+\.[a-z0-9_]+)["\']')
    refs = {}
    for d in SCAN_DIRS:
        for path in glob.glob(os.path.join(d, "**", "*.py"),
                              recursive=True):
            with open(path) as f:
                for key in pat.findall(f.read()):
                    if key.split(".", 1)[0] in prefixes:
                        refs.setdefault(key, []).append(
                            os.path.relpath(path, ROOT))
    return refs


def test_every_referenced_tuned_key_exists_in_the_tables():
    table = _table_keys()
    prefixes = {k.split(".", 1)[0] for k in table}
    refs = _referenced_keys(prefixes)
    assert refs, "lint found no tuned-key references at all — the " \
        "regex or scan dirs are broken, not the code"
    missing = {k: v for k, v in refs.items()
               if k not in table and k not in EXPLICITLY_DEFAULTED}
    assert not missing, (
        f"tuned keys referenced in code but absent from every table in "
        f"{TUNED_DIR} (typo, or add the key to the tables / "
        f"EXPLICITLY_DEFAULTED): {missing}")


def test_no_stale_table_keys():
    table = _table_keys()
    prefixes = {k.split(".", 1)[0] for k in table}
    refs = set(_referenced_keys(prefixes))
    stale = table - refs
    assert not stale, (
        f"tuned tables carry keys no kernel/serving code references "
        f"(dead sweep rows — delete them or wire a consumer): {stale}")


def test_chunk_prefill_keys_are_tuned():
    """The chunked-prefill kernel's knobs ship tuned values (the PR 4
    satellite): a fresh engine on v5e silicon must not fall back to
    emulator-era defaults for its hottest new program."""
    table = _table_keys()
    for key in ("decode.chunk_block_q", "decode.chunk_block_k",
                "decode.block_k", "decode.prefill_block_q",
                "decode.prefill_block_k"):
        assert key in table, f"{key} missing from the tuned tables"


def test_paged_kernel_keys_are_tuned():
    """The paged-pool satellite: the block-table kernels' knobs ship
    tuned values — ``decode.page_block_q`` (the paged prefill kernel's
    q block; the KV block is pinned to one pool page) and
    ``decode.page_len`` (the Engine's default page size — the pool's
    sharing/DMA granule). A fresh paged engine on v5e silicon must not
    fall back to emulator-era defaults for its two hottest programs."""
    table = _table_keys()
    for key in ("decode.page_block_q", "decode.page_len"):
        assert key in table, f"{key} missing from the tuned tables"
    refs = _referenced_keys({"decode"})
    for key in ("decode.page_block_q", "decode.page_len"):
        assert key in refs, f"{key} is in the tables but no code " \
            "consumes it (stale sweep row)"


def test_prefix_copy_sources_are_linted_and_carry_no_tuned_keys():
    """The PR 5 prefix-reuse satellite, tightened by the paged-pool
    refactor that RETIRED the copy from the hit path: the contiguous
    KV row-copy program is pure data movement (one dynamic-slice pair,
    no Pallas kernel) and the paged path replaces it with host-side
    page sharing (no program at all) — so neither owes the tables any
    key, and NO ``decode.copy_*`` row may remain (a stale row would be
    a dead sweep, caught here by name rather than only via the generic
    stale check). Also pins that the lint's scan covers the sources the
    retired path and its replacement live in, so any key a future copy
    or paging kernel DOES reference gets the existence/staleness
    treatment automatically."""
    table = _table_keys()
    stale_copy = {k for k in table if k.startswith("decode.copy_")}
    assert not stale_copy, (
        f"tuned tables carry decode.copy_* keys but neither the "
        f"contiguous KV row-copy nor the paged zero-copy hit path "
        f"consumes tuned knobs: {stale_copy}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving",
                        "prefix_cache.py") in scanned
    assert os.path.join("apex_tpu", "serving", "engine.py") in scanned
    assert os.path.join("apex_tpu", "serving", "kv_cache.py") in scanned


def test_speculative_verify_owes_the_tables_no_keys():
    """The speculative-decoding satellite, in the copy-program pattern:
    the verify program is the chunk-append machinery at a different
    shape — its attention rides the EXISTING ``decode.chunk_block_*`` /
    ``decode.page_block_q`` knobs and the drafter is pure host python —
    so no ``decode.verify_*`` key may exist in the tables (a row no
    code consumes would be a dead sweep; if a dedicated verify kernel
    ever lands, its keys get the existence/staleness treatment
    automatically because the scan covers speculative.py and
    engine.py)."""
    table = _table_keys()
    stale_verify = {k for k in table if k.startswith("decode.verify_")
                    or k.startswith("decode.spec_")}
    assert not stale_verify, (
        f"tuned tables carry verify/spec keys but the verify program "
        f"reuses the chunk-attention knobs: {stale_verify}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving",
                        "speculative.py") in scanned


def test_quantized_kv_owes_the_tables_no_new_keys():
    """The quantized-KV satellite, in the copy/verify/sharding
    pattern: dequantization is FUSED into the existing attention
    kernels (a per-head scalar multiply on the logit and accumulator
    updates — no new grid, block shape or index map), so the int8 tier
    introduces NO new ``decode.*`` table key; its kernels reuse the
    block knobs already swept. Any ``decode.qkv_*`` / ``decode.kv_*``
    row (a quantized-qkv or quant-specific sweep that no code consumes)
    is a dead row named loudly here; if a dedicated quant kernel ever
    lands, its keys get the existence/staleness treatment automatically
    because the scan covers serving/kv_quant.py and the two attention
    kernel files."""
    table = _table_keys()
    stale_quant = {k for k in table
                   if k.startswith(("decode.qkv_", "decode.kv_"))}
    assert not stale_quant, (
        f"tuned tables carry quantized-KV keys but the int8 tier "
        f"reuses the existing attention block knobs: {stale_quant}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving", "kv_quant.py") in scanned
    assert os.path.join("apex_tpu", "kernels",
                        "decode_attention.py") in scanned
    assert os.path.join("apex_tpu", "kernels",
                        "prefill_attention.py") in scanned


def test_quantized_weights_owe_the_tables_no_new_keys():
    """The quantized-weights satellite, in the quantized-KV pattern:
    dequantization is FOLDED into the existing GEMMs' epilogues (a
    per-output-channel scale multiply on the accumulator — no new
    kernel, grid or block shape), so the int8 weight tier introduces NO
    new ``decode.*`` table key. Any ``decode.wq_*`` / ``decode.weight_*``
    row would be a dead sweep, named loudly here; and the lint's scan
    must cover weight_quant.py and the shared quant core so any key a
    future dedicated int8-GEMM kernel DOES reference gets the
    existence/staleness treatment automatically."""
    table = _table_keys()
    stale_wq = {k for k in table
                if k.startswith(("decode.wq_", "decode.weight_"))}
    assert not stale_wq, (
        f"tuned tables carry quantized-weight keys but the int8 tier "
        f"folds dequant into the existing GEMM epilogues: {stale_wq}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving",
                        "weight_quant.py") in scanned
    assert os.path.join("apex_tpu", "serving",
                        "quant_common.py") in scanned


def test_host_tier_owes_the_tables_no_new_keys():
    """The hierarchical-KV satellite, in the copy-program pattern: the
    host tier is pure data movement — swap-out is one fixed-shape
    page-block gather and swap-in one fixed-shape page-block scatter
    (no attention, no Pallas kernel, no grid; both shard_map over the
    pool's heads axis under a mesh with zero collectives) —
    so it introduces NO new ``decode.*`` tuned key; restored pages are
    read back through the EXISTING paged-attention knobs. Any
    ``decode.swap_*`` / ``decode.host_*`` row would be a dead sweep,
    named loudly here; and the lint's scan must cover host_tier.py so
    any key a future swap-DMA kernel DOES reference gets the
    existence/staleness treatment automatically."""
    table = _table_keys()
    stale_swap = {k for k in table
                  if k.startswith(("decode.swap_", "decode.host_"))}
    assert not stale_swap, (
        f"tuned tables carry host-tier keys but swap-in/out is pure "
        f"data movement over the existing programs: {stale_swap}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving",
                        "host_tier.py") in scanned


def test_lora_tier_owes_the_tables_no_new_keys():
    """The multi-tenant LoRA satellite, in the copy/verify/host-tier
    pattern: the adapter epilogue is two skinny GEMMs fused onto the
    EXISTING projection matmuls (``acc + (x @ A) @ B * alpha`` — rank
    is 4–64, far below any block-tiling threshold; no new grid, block
    shape or Pallas kernel) and the arena swap path is pure data
    movement (one ``.at[row].set`` per site), so the tier introduces
    NO new ``decode.*`` tuned key. Any ``decode.lora_*`` /
    ``decode.adapter_*`` row would be a dead sweep, named loudly here;
    and the lint's scan must cover serving/lora.py so any key a future
    dedicated grouped-LoRA kernel DOES reference gets the
    existence/staleness treatment automatically."""
    table = _table_keys()
    stale_lora = {k for k in table
                  if k.startswith(("decode.lora_", "decode.adapter_"))}
    assert not stale_lora, (
        f"tuned tables carry LoRA keys but the adapter epilogue rides "
        f"the existing projection GEMMs' knobs: {stale_lora}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving", "lora.py") in scanned


def test_sharded_serving_owes_the_tables_no_new_keys():
    """The tensor-parallel satellite, in the copy/verify pattern: the
    sharded programs run the EXISTING paged kernels over fewer heads
    per shard (the grid's heads dimension shrinks; no index map or
    block shape changes), so sharding introduces NO new ``decode.*``
    table key — the per-shard kernels reuse the block knobs already
    swept (same shapes per head, fewer heads). The decode.* table
    surface is pinned by name, so a future sharded-attention knob must
    land here AND in the tables deliberately; and the lint's scan must
    cover serving/sharding.py so any key it ever does reference gets
    the existence/staleness treatment automatically."""
    table = {k for k in _table_keys() if k.startswith("decode.")}
    assert table == {
        "decode.block_k", "decode.chunk_block_q", "decode.chunk_block_k",
        "decode.prefill_block_q", "decode.prefill_block_k",
        "decode.page_block_q", "decode.page_len",
    }, (f"decode.* table surface changed: {sorted(table)} — if a "
        "sharded-attention knob landed, update this pin deliberately")
    stale_tp = {k for k in _table_keys()
                if k.startswith(("decode.tp_", "decode.shard_"))}
    assert not stale_tp, (
        f"tuned tables carry tensor-parallel keys but the sharded "
        f"kernels reuse the existing block knobs: {stale_tp}")
    scanned = {os.path.relpath(p, ROOT)
               for d in SCAN_DIRS
               for p in glob.glob(os.path.join(d, "**", "*.py"),
                                  recursive=True)}
    assert os.path.join("apex_tpu", "serving", "sharding.py") in scanned
