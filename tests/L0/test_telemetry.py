"""apex_tpu.telemetry — registry/histogram math, JSONL round-trip, the
one-callback-per-step contract under jit, overflow-event emission from a
forced inf grad, comm accounting, the bench crash contract, and the
summarize CLI on a golden run file."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import apex_tpu.telemetry as telemetry
from apex_tpu.amp import init_scaler, make_train_step, resolve_policy
from apex_tpu.telemetry import (JsonlSink, MemorySink, MetricsRegistry,
                                StreamingHistogram)
from apex_tpu.telemetry.summarize import (load_records, render_summary,
                                          summarize_records)

pytestmark = pytest.mark.telemetry


@pytest.fixture
def spy_registry():
    """Fresh default registry with a MemorySink spy; the previous default
    is restored afterwards so tests don't leak sinks into each other."""
    old = telemetry.get_registry()
    spy = MemorySink()
    reg = telemetry.configure(sinks=[spy])
    yield reg, spy
    telemetry.set_registry(old)


# --------------------------------------------------------------- histogram

def test_streaming_histogram_exact_stats_and_quantiles():
    h = StreamingHistogram()
    for v in range(1, 101):          # 1..100, all inside the reservoir
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 100
    assert s["min"] == 1.0 and s["max"] == 100.0
    assert s["mean"] == pytest.approx(50.5)
    # exact linear-interpolated quantiles of 1..100
    assert s["p50"] == pytest.approx(50.5)
    assert s["p95"] == pytest.approx(95.05)
    assert s["p99"] == pytest.approx(99.01)


def test_streaming_histogram_reservoir_bounded_and_deterministic():
    a = StreamingHistogram(reservoir_size=64)
    b = StreamingHistogram(reservoir_size=64)
    for v in range(10_000):
        a.observe(v)
        b.observe(v)
    assert len(a._sample) == 64
    assert a.count == 10_000 and a.total == b.total
    # fixed-seed RNG: two identically-fed instances agree bit-for-bit
    assert a.summary() == b.summary()
    # the reservoir median of uniform 0..9999 lands near the middle
    assert 2000 < a.quantile(0.5) < 8000


def test_streaming_histogram_skips_nan_counts_real():
    h = StreamingHistogram()
    h.observe(1.0)
    h.observe(float("nan"))
    h.observe(3.0)
    assert h.count == 2
    assert h.mean == pytest.approx(2.0)
    assert not math.isnan(h.quantile(0.5))


# ---------------------------------------------------------------- registry

def test_registry_counters_gauges_and_ring():
    reg = MetricsRegistry(ring_size=4)
    assert reg.counter_inc("n") == 1.0
    assert reg.counter_inc("n", 2.5) == 3.5
    reg.gauge_set("g", 7)
    for i in range(10):
        reg.record_step({"loss": float(i)})
    assert len(reg.records) == 4                       # ring evicts oldest
    assert [r["loss"] for r in reg.records] == [6.0, 7.0, 8.0, 9.0]
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 3.5
    assert snap["gauges"]["g"] == 7.0
    assert snap["histograms"]["train.loss"]["count"] == 10


def test_registry_step_time_and_overflow_counter():
    reg = MetricsRegistry()
    reg.record_step({"found_inf": 0})
    rec = reg.record_step({"found_inf": True})
    assert "step_time_s" in rec and rec["step_time_s"] >= 0.0
    assert rec["found_inf"] == 1                       # bool → numeric
    reg.record_step({"found_inf": np.bool_(True)})
    assert reg.counters["overflow_events"] == 2.0
    assert reg.histograms["train.step_time_s"].count == 2


def test_registry_snapshot_record_reaches_sinks():
    spy = MemorySink()
    reg = MetricsRegistry(sinks=[spy])
    reg.record_step({"loss": 1.0})
    reg.counter_inc("comm.all_reduce.bytes", 4096)
    final = reg.emit_snapshot()
    assert spy.records[-1] is final
    assert final["counters"]["comm.all_reduce.bytes"] == 4096
    assert final["tag"] == "summary"


# ------------------------------------------------------------------- JSONL

def test_jsonl_sink_round_trip(tmp_path):
    path = tmp_path / "run.jsonl"
    sink = JsonlSink(str(path))
    reg = MetricsRegistry(sinks=[sink])
    for i in range(3):
        reg.record_step({"loss": float(i), "loss_scale": 256.0})
    reg.emit_snapshot()
    reg.close()
    records = load_records(str(path))
    assert len(records) == 4
    assert [r["loss"] for r in records[:3]] == [0.0, 1.0, 2.0]
    assert records[3]["histograms"]["train.loss"]["count"] == 3
    # a crashed run's truncated last line is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"loss": 9, "tru')
    assert len(load_records(str(path))) == 4


# ------------------------------------------------- in-jit emission contract

def _amp_setup(telemetry_opt):
    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    init_fn, step_fn = make_train_step(loss_fn, optax.sgd(0.1), policy,
                                       telemetry=telemetry_opt)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    state = state.replace(scaler=init_scaler("dynamic", init_scale=256.0))
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    return jax.jit(step_fn), state, (x, y)


def test_amp_step_exactly_one_callback_per_step(spy_registry):
    """The acceptance contract: N executed steps of the jitted amp O2
    train step produce exactly N host callbacks (== N spy records), each
    bundling >= 5 distinct metric series."""
    reg, spy = spy_registry
    step, state, batch = _amp_setup(True)
    n = 7
    for _ in range(n):
        state, _ = step(state, batch)
    jax.effects_barrier()
    assert len(spy.records) == n
    series = set(spy.records[0]) - {"tag", "seq", "time", "step_time_s"}
    assert {"loss", "grad_norm", "loss_scale", "found_inf",
            "overflows"} <= series
    assert all(r["tag"] == "amp" for r in spy.records)
    # host-side wall time per step rides along from the second record on
    assert all("step_time_s" in r for r in spy.records[1:])
    assert reg.histograms["amp.loss"].count == n


def test_amp_step_telemetry_off_stages_nothing(spy_registry):
    _, spy = spy_registry
    step, state, batch = _amp_setup(False)
    for _ in range(3):
        state, _ = step(state, batch)
    jax.effects_barrier()
    assert spy.records == []


def test_amp_step_pinned_registry_bypasses_default(spy_registry):
    _, default_spy = spy_registry
    pinned_spy = MemorySink()
    pinned = MetricsRegistry(sinks=[pinned_spy])
    step, state, batch = _amp_setup(pinned)
    state, _ = step(state, batch)
    jax.effects_barrier()
    assert len(pinned_spy.records) == 1
    assert default_spy.records == []


def test_forced_inf_grad_emits_overflow_event(spy_registry):
    reg, spy = spy_registry
    step, state, batch = _amp_setup(True)
    x, y = batch
    state, _ = step(state, (x, y))                       # clean step
    bad = (x.at[0, 0].set(jnp.float32(1e30)), y)         # overflows f16
    state, metrics = step(state, bad)
    jax.effects_barrier()
    assert bool(metrics["found_inf"])
    clean, overflowed = spy.records
    assert clean["found_inf"] == 0 and overflowed["found_inf"] == 1
    # record_step counted the event and the scaler trajectory moved
    assert reg.counters["overflow_events"] == 1.0
    assert overflowed["loss_scale"] == 256.0             # scale AT the step
    assert float(state.scaler.loss_scale) == 128.0       # halved after


def test_emit_metrics_outside_jit(spy_registry):
    reg, spy = spy_registry
    telemetry.emit_metrics({"x": jnp.float32(2.0), "y": 3}, tag="eager")
    jax.effects_barrier()
    (rec,) = spy.records
    assert rec["tag"] == "eager" and rec["x"] == 2.0 and rec["y"] == 3


def test_accum_window_emits_one_callback_with_window_size(spy_registry):
    """Under accum_steps=N the callback contract is per OPTIMIZER window:
    W executed windows (each scanning N microbatches) produce exactly W
    host callbacks, and every record carries the window size."""
    reg, spy = spy_registry
    n, windows = 4, 3

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    init_fn, step_fn = make_train_step(loss_fn, optax.sgd(0.1), policy,
                                       telemetry=True, accum_steps=n)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    state = state.replace(scaler=init_scaler("dynamic", init_scale=256.0))
    step = jax.jit(step_fn)
    batch = (jnp.ones((n, 2, 4), jnp.float32),
             jnp.zeros((n, 2, 2), jnp.float32))
    for _ in range(windows):
        state, _ = step(state, batch)
    jax.effects_barrier()
    assert len(spy.records) == windows          # one per window, not per mb
    assert all(r["accum_steps"] == n for r in spy.records)
    assert reg.histograms["amp.loss"].count == windows


# ------------------------------------------------------------- comm health

def test_account_collective_counters(spy_registry):
    reg, _ = spy_registry
    from apex_tpu import comm

    tree = {"a": jnp.zeros((8, 4), jnp.float32),
            "b": jnp.zeros((16,), jnp.bfloat16)}
    telemetry.account_collective("ddp.allreduce", tree)
    assert reg.counters["comm.ddp.allreduce.calls"] == 1.0
    assert reg.counters["comm.ddp.allreduce.bytes"] == 8 * 4 * 4 + 16 * 2
    assert reg.counters["comm.ddp.allreduce.leaves"] == 2.0

    # the comm collectives account at trace time — once per compilation
    mesh_devs = jax.devices()[:2]
    if len(mesh_devs) == 2:
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.array(mesh_devs), ("data",))
        f = shard_map(lambda x: comm.all_reduce(x, "data"), mesh=mesh,
                      in_specs=P("data"), out_specs=P())
        jax.jit(f)(jnp.ones((2, 3), jnp.float32))
        assert reg.counters["comm.all_reduce.calls"] == 1.0
        assert reg.counters["comm.all_reduce.bytes"] == 1 * 3 * 4


def test_timed_context_manager(spy_registry):
    reg, _ = spy_registry
    with telemetry.timed("ckpt.save"):
        pass
    assert reg.counters["ckpt.save.calls"] == 1.0
    assert reg.histograms["ckpt.save"].count == 1


# ------------------------------------------------------ bench crash contract

@pytest.fixture(autouse=True)
def _no_retry_backoff(monkeypatch):
    """Guard retries sleep an exponential backoff in production; zero it
    here so the transient-retry tests stay instant (the backoff itself
    is covered by test_guard_bench_main_backoff_schedule, which restores
    a nonzero base)."""
    monkeypatch.setattr(telemetry, "_RETRY_BACKOFF_S", 0.0)


def test_every_bench_driver_routes_through_guard_bench_main():
    """Every bench_*.py entry point must end in a parseable JSON line on
    ANY outcome — i.e. wrap its main in guard_bench_main. A new bench
    leg that forgets the guard reintroduces the '"parsed": null' failure
    mode this contract exists to kill."""
    import glob

    root = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)
    drivers = sorted(glob.glob(os.path.join(root, "bench*.py")))
    assert len(drivers) >= 5        # bench, kernels, memory, schedule, serving
    for path in drivers:
        with open(path) as f:
            src = f.read()
        assert "guard_bench_main(" in src, \
            f"{os.path.basename(path)} does not route through " \
            "guard_bench_main"


@pytest.mark.slow          # subprocess re-imports jax: ~15s of wall
def test_bench_py_emits_json_line_even_when_env_parsing_fails():
    """The PR 5 satellite: bench.py's guard contract must hold for
    failures that used to fire BEFORE the guard was armed (module-level
    env parsing / heavy imports — the BENCH_r05 '"parsed": null' shape).
    A poisoned BENCH_* value now dies inside guarded main(): the LAST
    stdout line is the parseable failure JSON, rc 1."""
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)
    env = dict(os.environ, BENCH_BATCH="banana", JAX_PLATFORMS="cpu",
               APEX_TPU_BENCH_RETRIES="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    parsed = json.loads(lines[-1])          # the contract: LAST line parses
    assert parsed["rc"] == 1 and "BENCH_BATCH" in parsed["error"]
    assert parsed["metric"] == "resnet50_amp_o2_train_img_per_sec_per_chip"
    assert parsed["transient"] is False


def test_guard_bench_main_failure_ends_in_json_line(capsys):
    def exploding_main():
        raise RuntimeError("backend init failed")

    with pytest.raises(SystemExit) as exc:
        telemetry.guard_bench_main(exploding_main, "resnet50_img_per_sec")
    assert exc.value.code == 1
    last = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(last)
    assert parsed == {"metric": "resnet50_img_per_sec",
                      "error": "RuntimeError: backend init failed",
                      "rc": 1, "transient": False}


def test_guard_bench_main_success_passes_through(capsys):
    assert telemetry.guard_bench_main(lambda: 42, "m") == 42
    with pytest.raises(SystemExit) as exc:      # clean exits untouched
        telemetry.guard_bench_main(lambda: (_ for _ in ()).throw(
            SystemExit(0)), "m")
    assert exc.value.code == 0


def test_guard_bench_main_retries_transient_then_succeeds(capsys):
    """VERDICT r5 next-round #1: one tunnel flake (remote_compile read
    body) must not erase the perf record — the retry recovers it and no
    failure JSON is emitted."""
    calls = []

    def flaky_main():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("remote_compile: read body")
        return 42

    assert telemetry.guard_bench_main(flaky_main, "m") == 42
    assert len(calls) == 2
    out = capsys.readouterr().out
    assert "rc" not in out                       # no failure line printed
    # the retry boundary is marked so row aggregators can discard the
    # partial first attempt of a multi-row driver
    marker = json.loads(out.strip().splitlines()[0])
    assert marker["event"] == "transient_retry"
    assert marker["discard_preceding"] is True


def test_guard_bench_main_classifies_bench_r05_error_transient(capsys):
    """The tensor-parallel PR's guard satellite: the VERBATIM BENCH_r05
    failure — a JaxRuntimeError whose message is the axon remote-compile
    tunnel reset — must classify as transient end-to-end: retried (with
    the ``transient_retry`` discard marker), recovered when the retry
    succeeds, and tagged ``"transient": true`` when it persists, so one
    flaky backend can never zero out a bench round again."""

    class JaxRuntimeError(RuntimeError):
        pass

    R05 = ("INTERNAL: http://127.0.0.1:8103/remote_compile: read body: "
           "response body closed before all bytes were read")
    assert telemetry._is_transient_error(f"JaxRuntimeError: {R05}")
    calls = []

    def r05_flaky():
        calls.append(1)
        if len(calls) == 1:
            raise JaxRuntimeError(R05)
        return {"value": 1.0}

    assert telemetry.guard_bench_main(r05_flaky, "m") == {"value": 1.0}
    assert len(calls) == 2
    marker = json.loads(
        capsys.readouterr().out.strip().splitlines()[0])
    assert marker["event"] == "transient_retry"
    assert "remote_compile" in marker["error"]

    calls.clear()

    def r05_persistent():
        calls.append(1)
        raise JaxRuntimeError(R05)

    with pytest.raises(SystemExit) as exc:
        telemetry.guard_bench_main(r05_persistent, "m", retries=2)
    assert exc.value.code == 1
    assert len(calls) == 3                       # original + two retries
    parsed = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert parsed["transient"] is True, \
        "the BENCH_r05 remote-compile reset must be read as infra " \
        "noise, not a perf regression"
    assert parsed["error"] == f"JaxRuntimeError: {R05}"


def test_guard_bench_main_persistent_transient_tags_true(capsys):
    calls = []

    def always_flaky():
        calls.append(1)
        raise RuntimeError("remote_compile: read body")

    with pytest.raises(SystemExit) as exc:
        telemetry.guard_bench_main(always_flaky, "m", retries=1)
    assert exc.value.code == 1
    assert len(calls) == 2                       # original + one retry
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert parsed["transient"] is True
    assert parsed["rc"] == 1


def test_guard_bench_main_deterministic_error_never_retries(capsys):
    calls = []

    def broken_main():
        calls.append(1)
        raise ValueError("BENCH_WINDOWS must be >= 1")

    with pytest.raises(SystemExit):
        telemetry.guard_bench_main(broken_main, "m", retries=3)
    assert len(calls) == 1                       # no retry on real bugs
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert parsed["transient"] is False


def test_guard_bench_main_transient_systemexit_retries():
    """SystemExit with a transient message string retries too (some
    drivers wrap backend errors in SystemExit)."""
    calls = []

    def flaky_exit():
        calls.append(1)
        if len(calls) == 1:
            raise SystemExit("UNAVAILABLE: connection reset by peer")
        return "ok"

    assert telemetry.guard_bench_main(flaky_exit, "m") == "ok"
    assert len(calls) == 2


def test_guard_bench_main_retries_default_from_env(monkeypatch, capsys):
    """APEX_TPU_BENCH_RETRIES raises the retry budget without touching
    any bench driver (PR 4 satellite: BENCH_r05 exhausted its single
    retry on back-to-back remote_compile resets)."""
    monkeypatch.setenv("APEX_TPU_BENCH_RETRIES", "3")
    calls = []

    def triple_flaky():
        calls.append(1)
        if len(calls) <= 3:
            raise RuntimeError("remote_compile: read body")
        return 42

    assert telemetry.guard_bench_main(triple_flaky, "m") == 42
    assert len(calls) == 4                       # original + 3 retries


def test_guard_bench_main_env_retries_zero_and_malformed(monkeypatch):
    monkeypatch.setenv("APEX_TPU_BENCH_RETRIES", "0")
    calls = []

    def flaky():
        calls.append(1)
        raise RuntimeError("connection reset")

    with pytest.raises(SystemExit):
        telemetry.guard_bench_main(flaky, "m")
    assert len(calls) == 1                       # env 0 → no retry
    # malformed env degrades to the default of 1, never crashes
    monkeypatch.setenv("APEX_TPU_BENCH_RETRIES", "yes please")
    assert telemetry._env_retries() == 1
    monkeypatch.setenv("APEX_TPU_BENCH_RETRIES", "-2")
    assert telemetry._env_retries() == 0         # clamped, not negative
    monkeypatch.delenv("APEX_TPU_BENCH_RETRIES")
    assert telemetry._env_retries() == 1


def test_guard_bench_main_backoff_schedule(monkeypatch):
    """Transient retries back off exponentially (0.5, 1, 2, ... capped)
    instead of hammering the same mid-hiccup infrastructure."""
    monkeypatch.setattr(telemetry, "_RETRY_BACKOFF_S", 0.5)
    sleeps = []
    monkeypatch.setattr(telemetry.time, "sleep",
                        lambda s: sleeps.append(s))

    def always_flaky():
        raise RuntimeError("remote_compile: read body")

    with pytest.raises(SystemExit):
        telemetry.guard_bench_main(always_flaky, "m", retries=6)
    assert sleeps == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0]   # capped at 8 s


# -------------------------------------------------------------- summarize

GOLDEN = os.path.join(os.path.dirname(__file__), os.pardir, "data",
                      "telemetry_golden.jsonl")


def test_summarize_golden_run_file():
    records = load_records(GOLDEN)
    summary = summarize_records(records)
    assert summary["steps"] == {"amp": 8}
    loss = summary["metrics"]["amp.loss"]
    assert loss["count"] == 8
    assert loss["mean"] == pytest.approx(4.5)
    assert loss["p50"] == pytest.approx(4.5)
    assert loss["p95"] == pytest.approx(7.65)
    # counters come from the run's final snapshot record
    assert summary["counters"]["overflow_events"] == 1
    text = render_summary(summary)
    assert "amp.loss" in text and "p95" in text and "overflow_events" in text


def test_summarize_cli_on_golden_file(capsys):
    from apex_tpu.telemetry.__main__ import main

    assert main(["summarize", GOLDEN]) == 0
    out = capsys.readouterr().out
    for col in ("count", "mean", "p50", "p95"):
        assert col in out
    assert "amp.loss" in out and "steps: amp=8" in out

    assert main(["summarize", GOLDEN, "--json"]) == 0
    machine = json.loads(capsys.readouterr().out)
    assert machine["metrics"]["amp.loss"]["count"] == 8


def test_summarize_cli_rejects_empty_file(tmp_path):
    from apex_tpu.telemetry.__main__ import main

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(SystemExit):
        main(["summarize", str(empty)])


# ------------------------------------------------------------- prometheus

def test_render_prometheus_golden():
    """The exposition format is a wire contract: pin an exact golden
    render — counter/gauge typing, name sanitization (dots → ``_``),
    the per-replica router-gauge namespace collapsing into ONE labeled
    family, the fixed histogram bucket ladder with exact cumulative
    counts, and deterministic ordering (sorted families, sorted label
    sets) so scrapes diff cleanly."""
    reg = MetricsRegistry()
    reg.counter_inc("serving.faults.nonfinite", 2)
    reg.counter_inc("overflow_events")
    reg.gauge_set("serving.kv.bytes_per_token", 512)
    reg.gauge_set("serving.router.replica1.queue_depth", 1)
    reg.gauge_set("serving.router.replica0.queue_depth", 3)
    for v in (0.25, 0.75, 3.0):          # exact binary floats: sum == 4
        reg.observe("serving.ttft_s", v)
    golden = "\n".join([
        "# TYPE overflow_events counter",
        "overflow_events 1",
        "# TYPE serving_faults_nonfinite counter",
        "serving_faults_nonfinite 2",
        "# TYPE serving_kv_bytes_per_token gauge",
        "serving_kv_bytes_per_token 512",
        "# TYPE serving_router_replica_queue_depth gauge",
        'serving_router_replica_queue_depth{replica="0"} 3',
        'serving_router_replica_queue_depth{replica="1"} 1',
        "# TYPE serving_ttft_s histogram",
        'serving_ttft_s_bucket{le="0.0005"} 0',
        'serving_ttft_s_bucket{le="0.001"} 0',
        'serving_ttft_s_bucket{le="0.0025"} 0',
        'serving_ttft_s_bucket{le="0.005"} 0',
        'serving_ttft_s_bucket{le="0.01"} 0',
        'serving_ttft_s_bucket{le="0.025"} 0',
        'serving_ttft_s_bucket{le="0.05"} 0',
        'serving_ttft_s_bucket{le="0.075"} 0',
        'serving_ttft_s_bucket{le="0.1"} 0',
        'serving_ttft_s_bucket{le="0.25"} 1',
        'serving_ttft_s_bucket{le="0.5"} 1',
        'serving_ttft_s_bucket{le="0.75"} 2',
        'serving_ttft_s_bucket{le="1"} 2',
        'serving_ttft_s_bucket{le="2.5"} 2',
        'serving_ttft_s_bucket{le="5"} 3',
        'serving_ttft_s_bucket{le="7.5"} 3',
        'serving_ttft_s_bucket{le="10"} 3',
        'serving_ttft_s_bucket{le="25"} 3',
        'serving_ttft_s_bucket{le="50"} 3',
        'serving_ttft_s_bucket{le="100"} 3',
        'serving_ttft_s_bucket{le="+Inf"} 3',
        "serving_ttft_s_sum 4",
        "serving_ttft_s_count 3",
    ]) + "\n"
    assert reg.render_prometheus() == golden
    # identical state renders identically (scrape-diff stability)
    assert reg.render_prometheus() == golden


def test_render_prometheus_sanitizes_malformed_names():
    """Anything outside ``[a-zA-Z0-9_:]`` becomes ``_`` and a leading
    digit gets a ``_`` prefix — a malformed metric name must never
    produce a line a Prometheus scraper rejects (one bad line fails
    the WHOLE scrape)."""
    import re

    reg = MetricsRegistry()
    reg.counter_inc("3bad.metric-name!x")
    reg.gauge_set("weird metric/name", 1)
    text = reg.render_prometheus()
    assert "_3bad_metric_name_x 1" in text
    assert "weird_metric_name 1" in text
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), \
            f"invalid prometheus metric name in exposition: {name!r}"


def test_render_prometheus_reservoir_scaled_buckets_exact_sum_count():
    """Past the reservoir, bucket counts are uniformly scaled estimates
    but ``_sum``/``_count`` stay exact — with every observation equal,
    the scaled buckets are exact too, pinning the scale arithmetic."""
    reg = MetricsRegistry(reservoir_size=64)
    for _ in range(10_000):
        reg.observe("h", 0.5)
    text = reg.render_prometheus()
    assert 'h_bucket{le="0.25"} 0' in text
    assert 'h_bucket{le="0.5"} 10000' in text
    assert 'h_bucket{le="+Inf"} 10000' in text
    assert "h_sum 5000" in text
    assert "h_count 10000" in text


# ------------------------------------------------------------ env opt-in

def test_from_env_unset_is_noop(monkeypatch):
    monkeypatch.delenv(telemetry.ENV_VAR, raising=False)
    before = telemetry.get_registry()
    assert telemetry.from_env() is None
    assert telemetry.get_registry() is before


def test_from_env_starts_run(monkeypatch, tmp_path):
    old = telemetry.get_registry()
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv(telemetry.ENV_VAR, str(path))
    try:
        reg = telemetry.from_env()
        assert reg is telemetry.get_registry() and reg is not old
        reg.record_step({"loss": 1.0})
        reg.close()
        assert len(load_records(str(path))) == 1
    finally:
        telemetry.set_registry(old)


# ------------------------------------------------------- logging promotion

def test_get_logger_namespace_and_transformer_alias():
    import logging

    import apex_tpu
    from apex_tpu.transformer.log_util import (get_transformer_logger,
                                               set_logging_level)

    assert apex_tpu.get_logger("amp").name == "apex_tpu.amp"
    assert apex_tpu.get_logger().name == "apex_tpu"
    # the transformer helpers are thin aliases over the same namespace
    assert get_transformer_logger("x").name == "apex_tpu.transformer.x"
    set_logging_level(logging.DEBUG)
    assert logging.getLogger("apex_tpu.transformer").level == logging.DEBUG
    set_logging_level(logging.WARNING)
