"""apex_tpu.normalization module tests.

Mirror of the reference's tests/L0/run_fused_layer_norm/test_fused_layer_norm.py
strategy: compare the fused module against a composed fp32 reference
(flax LayerNorm / hand jnp) with dtype-dependent tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.layer_norm import layer_norm_reference
from apex_tpu.normalization import (FusedLayerNorm, FusedRMSNorm,
                                    MixedFusedLayerNorm)


def _ref_ln(x, scale, bias, eps=1e-5):
    # shared oracle (same one tests/L0/test_fused_layer_norm.py uses)
    x32 = jnp.asarray(np.asarray(x), jnp.float32)
    w = None if np.isscalar(scale) and scale == 1.0 \
        else jnp.asarray(np.asarray(scale, np.float32).reshape(-1))
    b = None if np.isscalar(bias) and bias == 0.0 \
        else jnp.asarray(np.asarray(bias, np.float32).reshape(-1))
    return np.asarray(layer_norm_reference(x32, w, b, eps=eps))


@pytest.mark.parametrize("hidden", [128, 96])
def test_fused_layer_norm_module(hidden):
    m = FusedLayerNorm(normalized_shape=hidden)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, hidden), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)
    scale = np.asarray(variables["params"]["scale"])
    bias = np.asarray(variables["params"]["bias"])
    np.testing.assert_allclose(np.asarray(y), _ref_ln(np.asarray(x), scale,
                                                      bias),
                               rtol=2e-5, atol=2e-5)


def test_fused_layer_norm_no_affine():
    m = FusedLayerNorm(normalized_shape=64, elementwise_affine=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    assert "params" not in variables or not variables["params"]
    y = m.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y),
                               _ref_ln(np.asarray(x), 1.0, 0.0),
                               rtol=2e-5, atol=2e-5)


def test_fused_rms_norm_module():
    m = FusedRMSNorm(normalized_shape=128)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 128), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)
    x32 = np.asarray(x, np.float32)
    ref = x32 / np.sqrt((x32 ** 2).mean(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-5, atol=2e-5)


def test_mixed_fused_layer_norm_bf16_io():
    """Mixed = half I/O, fp32 params + stats (reference: MixedFusedLayerNorm)."""
    m = MixedFusedLayerNorm(normalized_shape=128, dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 128), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    assert variables["params"]["scale"].dtype == jnp.float32
    y = m.apply(variables, x)
    ref = _ref_ln(np.asarray(x), 1.0, 0.0)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref,
                               rtol=2e-2, atol=2e-2)


def test_multidim_normalized_shape():
    m = FusedLayerNorm(normalized_shape=(4, 32))
    x = jax.random.normal(jax.random.PRNGKey(0), (6, 4, 32), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)
    assert y.shape == x.shape
    flat = np.asarray(x).reshape(6, 128)
    scale = np.asarray(variables["params"]["scale"])
    bias = np.asarray(variables["params"]["bias"])
    ref = _ref_ln(flat, scale, bias).reshape(6, 4, 32)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-5, atol=2e-5)


def test_grad_flows():
    m = FusedLayerNorm(normalized_shape=128)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 128), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)

    def loss(v, x):
        return jnp.sum(m.apply(v, x) ** 2)

    g = jax.grad(loss)(variables, x)
    assert np.isfinite(np.asarray(g["params"]["scale"])).all()
