"""Decode-attention kernel: Pallas path vs jnp oracle (interpret/CPU).

Mirrors the flash-attention test strategy: the reference implementation
is the oracle (never golden files), the Pallas path runs in interpret
mode on CPU, and the unaligned/fallback dispatch must agree with the
aligned path on what it accepts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import vmem
from apex_tpu.kernels.decode_attention import (decode_attention,
                                               decode_attention_reference)

pytestmark = pytest.mark.serving


def _rand(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


@pytest.mark.parametrize("lengths", [[1, 5, 256], [0, 37, 128],
                                     [256, 256, 256]])
def test_pallas_matches_reference_aligned(lengths):
    rng = np.random.default_rng(0)
    B, h, L, d = 3, 4, 256, 64
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray(lengths, jnp.int32)
    ref = decode_attention_reference(q, k, v, lens, scale=1.0 / d ** 0.5)
    out = decode_attention(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zero_length_rows_are_zero():
    rng = np.random.default_rng(1)
    B, h, L, d = 2, 2, 128, 8
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([0, 4], jnp.int32)
    out = np.asarray(decode_attention(q, k, v, lens))
    assert np.all(out[0] == 0.0)
    assert np.any(out[1] != 0.0)


def test_masking_ignores_positions_past_length():
    """Garbage K/V past a row's length must not move its output — the
    write-then-attend cache contract depends on it."""
    rng = np.random.default_rng(2)
    B, h, L, d = 2, 4, 256, 16
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([9, 200], jnp.int32)
    base = np.asarray(decode_attention(q, k, v, lens))
    k2 = k.at[0, :, 9:].set(1e4)   # poison past-length positions, row 0
    v2 = v.at[0, :, 9:].set(-1e4)
    pert = np.asarray(decode_attention(q, k2, v2, lens))
    np.testing.assert_allclose(pert[0], base[0], rtol=1e-6, atol=1e-6)


def test_unaligned_falls_back_and_matches_reference():
    rng = np.random.default_rng(3)
    B, h, L, d = 2, 3, 100, 12     # L%128 != 0, d%8 != 0
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([10, 100], jnp.int32)
    out = decode_attention(q, k, v, lens)
    ref = decode_attention_reference(q, k, v, lens, scale=1.0 / d ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_bf16_io_close_to_fp32_oracle():
    rng = np.random.default_rng(4)
    B, h, L, d = 2, 4, 256, 32
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([17, 256], jnp.int32)
    ref = decode_attention_reference(q, k, v, lens, scale=1.0 / d ** 0.5)
    out = decode_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                           v.astype(jnp.bfloat16), lens)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_tuned_block_override_changes_nothing_numerically():
    rng = np.random.default_rng(5)
    B, h, L, d = 2, 2, 512, 16
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([3, 400], jnp.int32)
    base = np.asarray(decode_attention(q, k, v, lens))
    vmem.set_override("decode.block_k", 128)
    try:
        tuned = np.asarray(decode_attention(q, k, v, lens))
    finally:
        vmem.remove_override("decode.block_k")
    np.testing.assert_allclose(tuned, base, rtol=2e-5, atol=2e-5)


def test_shape_validation():
    q = jnp.zeros((2, 2, 8))
    k = jnp.zeros((2, 2, 16, 8))
    with pytest.raises(ValueError, match="lengths"):
        decode_attention(q, k, k, jnp.zeros((3,), jnp.int32))
    with pytest.raises(ValueError, match="do not match"):
        decode_attention(q, k[:, :1], k, jnp.zeros((2,), jnp.int32))


def test_jit_and_explicit_block_k():
    rng = np.random.default_rng(6)
    B, h, L, d = 1, 2, 256, 8
    q = _rand(rng, (B, h, d))
    k = _rand(rng, (B, h, L, d))
    v = _rand(rng, (B, h, L, d))
    lens = jnp.asarray([129], jnp.int32)
    out = jax.jit(lambda *a: decode_attention(*a, block_k=128))(q, k, v,
                                                                lens)
    ref = decode_attention_reference(q, k, v, lens, scale=1.0 / d ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_int8_dequant_in_kernel_matches_dequant_oracle():
    """The quantized-cache tier (kv_quant): int8 K/V + per-head scales
    through the SAME kernel, dequantized in-kernel, vs quantizing the
    oracle's inputs up front — same math, fused vs materialised. Also
    pins that garbage int8 past a row's length stays masked."""
    rng = np.random.default_rng(11)
    B, h, L, d = 3, 4, 256, 16
    q = _rand(rng, (B, h, d))
    k8 = jnp.asarray(rng.integers(-127, 128, size=(B, h, L, d)), jnp.int8)
    v8 = jnp.asarray(rng.integers(-127, 128, size=(B, h, L, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.06, size=h), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.06, size=h), jnp.float32)
    lens = jnp.asarray([1, 37, 256], jnp.int32)
    ref = decode_attention_reference(
        jnp.asarray(q, jnp.float32),
        jnp.asarray(k8, jnp.float32) * ks[None, :, None, None],
        jnp.asarray(v8, jnp.float32) * vs[None, :, None, None],
        lens, scale=1.0 / d ** 0.5)
    out = decode_attention(q, k8, v8, lens, k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # garbage codes past the length must not move the output
    k_dirty = k8.at[:, :, 40:].set(127)
    out2 = decode_attention(q, k_dirty, v8,
                            jnp.asarray([1, 37, 40], jnp.int32),
                            k_scale=ks, v_scale=vs)
    base = decode_attention(q, k8, v8,
                            jnp.asarray([1, 37, 40], jnp.int32),
                            k_scale=ks, v_scale=vs)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(base))
