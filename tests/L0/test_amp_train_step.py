"""End-to-end amp step semantics — the observable order apex tests check
(tests/L0/run_amp/test_checkpointing.py, amp_master_params): master weights,
skip-on-overflow with NO optimizer-state advance, scale schedule."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.amp import make_train_step, resolve_policy


def _loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


def _setup(opt_level="O2", half=jnp.float16, **over):
    policy = resolve_policy(opt_level, half_dtype=half, verbose=False, **over)
    opt = optax.sgd(0.1)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    params = {"w": jnp.ones((4, 2), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    state = init_fn(params)
    if state.scaler.dynamic:
        # 2**16 would overflow this toy batch's fp16 grads on step one (real
        # amp behavior: halve until it fits); a small init scale keeps the
        # happy-path tests deterministic. Overflow paths are tested explicitly.
        from apex_tpu.amp import init_scaler
        state = state.replace(scaler=init_scaler("dynamic", init_scale=256.0))
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    return policy, jax.jit(step_fn), state, (x, y)


def test_o2_master_weights_exist_and_params_half():
    policy, step, state, batch = _setup("O2")
    assert state.master_params is not None
    assert state.master_params["w"].dtype == jnp.float32
    assert state.params["w"].dtype == jnp.float16
    new_state, metrics = step(state, batch)
    # params moved and stayed half; masters stayed fp32 and mirror params
    assert new_state.params["w"].dtype == jnp.float16
    assert new_state.master_params["w"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(new_state.params["w"], np.float32),
        np.asarray(new_state.master_params["w"]).astype(np.float16).astype(np.float32))
    assert not bool(metrics["found_inf"])


def test_o0_trains_fp32_no_masters():
    policy, step, state, batch = _setup("O0")
    assert state.master_params is None
    assert state.params["w"].dtype == jnp.float32
    new_state, metrics = step(state, batch)
    assert float(metrics["loss"]) > 0
    assert not np.allclose(np.asarray(new_state.params["w"]),
                           np.asarray(state.params["w"]))


def test_overflow_skips_step_and_halves_scale():
    policy, step, state, batch = _setup("O2")
    x, y = batch
    bad = (x.at[0, 0].set(jnp.float32(1e30)), y)  # overflows f16 grads via loss scale
    new_state, metrics = step(state, bad)
    assert bool(metrics["found_inf"])
    # optimizer state did not advance, params unchanged
    np.testing.assert_array_equal(np.asarray(new_state.master_params["w"]),
                                  np.asarray(state.master_params["w"]))
    np.testing.assert_array_equal(np.asarray(new_state.params["w"], np.float32),
                                  np.asarray(state.params["w"], np.float32))
    assert float(new_state.scaler.loss_scale) == 128.0  # halved from 256
    assert int(new_state.scaler.unskipped) == 0


def test_overflow_freezes_stateful_optimizer_bitwise():
    """Regression for the cond→select skip rewrite: with a STATEFUL
    optimizer (adam mu/nu + count), an overflow step must leave every
    opt-state leaf bitwise frozen — the select path computes the update
    on inf/NaN grads and must discard all of it, count increment
    included. sgd-based overflow tests can't see this (no state leaves)."""
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False,
                            loss_scale=256.0)
    opt = optax.adam(1e-2)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32),
                     "b": jnp.zeros((2,), jnp.float32)})
    step = jax.jit(step_fn)
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    state, m = step(state, (x, y))           # clean step: state advances
    assert not bool(m["found_inf"])
    bad = (x.at[0, 0].set(jnp.float32(1e30)), y)
    new_state, m = step(state, bad)
    assert bool(m["found_inf"])
    before = jax.tree_util.tree_leaves(state.opt_state)
    after = jax.tree_util.tree_leaves(new_state.opt_state)
    assert before and len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(new_state.master_params["w"]),
                                  np.asarray(state.master_params["w"]))


def test_clean_steps_grow_scale():
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    opt = optax.sgd(1e-4)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    params = {"w": jnp.zeros((4, 2), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    state = init_fn(params)
    x = jnp.ones((8, 4), jnp.float32) * 0.01
    y = jnp.zeros((8, 2), jnp.float32)
    step = jax.jit(step_fn)
    # shrink window via a fresh scaler config
    from apex_tpu.amp import init_scaler
    sc = init_scaler("dynamic", init_scale=1.0, scale_window=3)
    state = state.replace(scaler=sc)
    for _ in range(3):
        state, m = step(state, (x, y))
        assert not bool(m["found_inf"])
    assert float(state.scaler.loss_scale) == 2.0


def test_static_loss_scale_o3():
    policy, step, state, batch = _setup("O3")
    assert state.master_params is None
    assert state.params["w"].dtype == jnp.float16
    new_state, metrics = step(state, batch)
    assert float(new_state.scaler.loss_scale) == 1.0


def test_o3_stateful_optimizer_traces():
    """Regression: O3 (half params, no masters) + momentum must not hit a
    lax.cond branch dtype mismatch — optimizer state stays in param dtype."""
    policy = resolve_policy("O3", half_dtype=jnp.float16, verbose=False)
    opt = optax.sgd(0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32),
                     "b": jnp.zeros((2,), jnp.float32)})
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    new_state, m = jax.jit(step_fn)(state, (x, y))
    assert new_state.params["w"].dtype == jnp.float16
    assert not bool(m["found_inf"])


def test_o1_casts_batch_to_half_compute():
    """O1 leaves params fp32 but runs compute (and thus batch inputs) in the
    half dtype — the op-table policy's coarse-grained application."""
    policy = resolve_policy("O1", verbose=False)
    seen = {}

    def probe_loss(params, batch):
        x, y = batch
        seen["x_dtype"] = x.dtype
        pred = x @ params["w"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y.astype(jnp.float32)) ** 2)

    init_fn, step_fn = make_train_step(probe_loss, optax.sgd(0.1), policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    assert state.params["w"].dtype == jnp.float32  # O1 keeps model fp32
    state, m = step_fn(state, (jnp.ones((8, 4)), jnp.zeros((8, 2))))
    assert seen["x_dtype"] == jnp.bfloat16


def test_master_params_rejects_optimizer_object():
    import pytest as _pytest
    from apex_tpu import amp as _amp

    with _pytest.raises(TypeError):
        _amp.master_params(optax.sgd(0.1))


# ------------------------------------------------- microbatch accumulation

def _mlp_loss(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w"].astype(x.dtype))
    pred = h @ params["v"].astype(x.dtype) + params["b"].astype(x.dtype)
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


def _mlp_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(4, 8) * 0.3, jnp.float32),
            "v": jnp.asarray(rng.randn(8, 2) * 0.3, jnp.float32),
            "b": jnp.zeros((2,), jnp.float32)}


def _microbatches(n, rows=2, seed=0):
    rng = np.random.RandomState(100 + seed)
    x = jnp.asarray(rng.randn(n * rows, 4), jnp.float32)
    y = jnp.asarray(rng.randn(n * rows, 2), jnp.float32)
    return (x.reshape(n, rows, 4), y.reshape(n, rows, 2))


def test_accum_bitwise_matches_manual_accumulation():
    """THE acceptance bar: accum_steps=N at scale 1 produces bitwise-
    identical params to N sequential single-microbatch grad computations
    accumulated in fp32, averaged, and fed to ONE optimizer application
    — apex's delay_unscale recipe done by hand. The one optimizer
    application reuses the step machinery via grad_fn (identical traced
    update program), so the assertion isolates the accumulation scan —
    any deviation in sum order, averaging, or dtype shows up bitwise."""
    n = 4
    params = _mlp_params()
    opt = optax.adam(1e-2)
    policy = resolve_policy("O0", verbose=False)
    init_fn, step_fn = make_train_step(_mlp_loss, opt, policy,
                                       accum_steps=n)
    state = init_fn(params)
    mb = _microbatches(n)
    new_state, m = jax.jit(step_fn)(state, mb)
    assert not bool(m["found_inf"])

    # manual reference: per-microbatch jitted grads (N independent
    # compilations — truly sequential single-microbatch backward passes),
    # sequential fp32 accumulation, sum/N ...
    grad_one = jax.jit(jax.grad(_mlp_loss))
    acc = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    loss_sum = 0.0
    for i in range(n):
        one = jax.tree_util.tree_map(lambda l: l[i], mb)
        g = grad_one(state.params, one)
        acc = jax.tree_util.tree_map(
            lambda a, gg: a + jnp.asarray(gg, a.dtype), acc, g)
        loss_sum += float(_mlp_loss(state.params, one))
    avg = jax.tree_util.tree_map(lambda a: a / n, acc)
    # ... then the optimizer applied ONCE on the averaged grads, through
    # the same step pipeline (grad_fn passes the grads through untouched)
    init_ref, step_ref = make_train_step(
        None, opt, policy, grad_fn=lambda p, g, scale: (jnp.float32(0.0), g))
    ref_state = init_ref(params)
    want, _ = jax.jit(step_ref)(ref_state, avg)
    for k in params:
        np.testing.assert_array_equal(np.asarray(new_state.params[k]),
                                      np.asarray(want.params[k]),
                                      err_msg=f"leaf {k} not bitwise")
    # the reported loss is the window mean
    assert float(m["loss"]) == pytest.approx(loss_sum / n, rel=1e-6)


def test_accum_overflow_any_microbatch_freezes_whole_window():
    """delay_unscale semantics: ONE poisoned microbatch anywhere in the
    window ⇒ the whole window is skipped — stateful (adam) optimizer
    state bitwise frozen, masters untouched, scale backed off ONCE
    (the stateful extension of
    test_overflow_freezes_stateful_optimizer_bitwise)."""
    n = 4
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    init_fn, step_fn = make_train_step(_mlp_loss, optax.adam(1e-2), policy,
                                       accum_steps=n)
    from apex_tpu.amp import init_scaler
    state = init_fn(_mlp_params())
    state = state.replace(scaler=init_scaler("dynamic", init_scale=256.0))
    step = jax.jit(step_fn)
    mb = _microbatches(n)
    state, m = step(state, mb)                   # clean window: advances
    assert not bool(m["found_inf"])
    x, y = _microbatches(n)
    # poison microbatch 2 only — the overflow must survive accumulation
    bad = (x.at[2, 0, 0].set(jnp.float32(1e30)), y)
    new_state, m = step(state, bad)
    assert bool(m["found_inf"])
    before = jax.tree_util.tree_leaves(state.opt_state)
    after = jax.tree_util.tree_leaves(new_state.opt_state)
    assert before and len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(new_state.master_params["w"]),
                                  np.asarray(state.master_params["w"]))
    # backed off exactly once for the whole window, not once per microbatch
    assert float(new_state.scaler.loss_scale) == 128.0


def test_accum_scaler_trajectory_matches_single_step_path():
    """The scaler schedule counts OPTIMIZER steps: W windows at
    accum_steps=N move the scaler state exactly as W single-microbatch
    steps do (scale_window counts windows, steps counter +1 per window)."""
    windows, n = 3, 2
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)

    def run(accum_steps):
        from apex_tpu.amp import init_scaler
        init_fn, step_fn = make_train_step(
            _mlp_loss, optax.sgd(1e-4), policy, accum_steps=accum_steps)
        state = init_fn(_mlp_params())
        state = state.replace(
            scaler=init_scaler("dynamic", init_scale=4.0, scale_window=3))
        step = jax.jit(step_fn)
        for i in range(windows):
            if accum_steps == 1:
                x, y = _microbatches(n, seed=i)
                batch = (x.reshape(-1, 4), y.reshape(-1, 2))
            else:
                batch = _microbatches(n, seed=i)
            state, m = step(state, batch)
            assert not bool(m["found_inf"])
        return state.scaler

    acc, single = run(n), run(1)
    assert float(acc.loss_scale) == float(single.loss_scale) == 8.0
    assert int(acc.steps) == int(single.steps) == windows
    assert int(acc.unskipped) == int(single.unskipped)
    assert int(acc.overflows) == int(single.overflows) == 0


def test_accum_model_state_threads_through_scan_and_aux_stacks():
    """model_state flows microbatch→microbatch through the scan carry
    (i+1 sees i's BatchNorm stats — N updates per window), and has_aux
    stacks the per-microbatch aux along a leading N axis."""
    n = 3

    def loss_fn(params, mstate, batch):
        x, y = batch
        pred = x @ params["w"].astype(x.dtype)
        loss = jnp.mean((pred.astype(jnp.float32) - y) ** 2)
        new_ms = {"count": mstate["count"] + 1,
                  "mean": jnp.mean(x.astype(jnp.float32))}
        return loss, (new_ms, {"batch_mean": jnp.mean(y)})

    policy = resolve_policy("O0", verbose=False)
    init_fn, step_fn = make_train_step(loss_fn, optax.sgd(0.1), policy,
                                       has_aux=True, with_model_state=True,
                                       accum_steps=n)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)},
                    model_state={"count": jnp.int32(0),
                                 "mean": jnp.float32(0.0)})
    mb = _microbatches(n)
    new_state, m = jax.jit(step_fn)(state, mb)
    assert int(new_state.model_state["count"]) == n
    assert m["aux"]["batch_mean"].shape == (n,)
    np.testing.assert_allclose(
        np.asarray(m["aux"]["batch_mean"]),
        np.asarray(jnp.mean(mb[1], axis=(1, 2))), rtol=1e-6)


def test_accum_rejects_grad_fn_and_bad_counts():
    policy = resolve_policy("O0", verbose=False)
    with pytest.raises(ValueError, match="accum_steps must be >= 1"):
        make_train_step(_mlp_loss, optax.sgd(0.1), policy, accum_steps=0)
    with pytest.raises(ValueError, match="incompatible with grad_fn"):
        make_train_step(None, optax.sgd(0.1), policy, accum_steps=2,
                        grad_fn=lambda p, b, s: (0.0, p))


def test_accum_one_psum_per_window_trace_time():
    """The acceptance certificate, counter half: with accum_steps=N the
    whole-tree DDP grad reduction is traced ONCE per optimizer window —
    `comm.ddp.allreduce.calls` reads 1 (and leaves == n_params) after the
    jitted window step compiles, because the psum sits after the scan,
    not inside it. (The scheduled-HLO half lives in bench_schedule.py's
    ddp_accum leg.)"""
    import apex_tpu.telemetry as telemetry
    from jax.sharding import Mesh, PartitionSpec as P
    # the hermetic env's jax has no top-level jax.shard_map (the axon
    # toolchain's newer jax does); the compat shim resolves whichever
    # exists and translates check_vma= when needed
    from apex_tpu.utils.compat import shard_map

    old = telemetry.get_registry()
    reg = telemetry.configure(sinks=[])
    try:
        n = 4
        policy = resolve_policy("O2", half_dtype=jnp.bfloat16,
                                verbose=False)
        init_fn, step_fn = make_train_step(_mlp_loss, optax.sgd(0.1),
                                           policy, grad_average_axis="data",
                                           accum_steps=n)
        state = init_fn(_mlp_params())
        mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
        x, y = _microbatches(n)
        fn = shard_map(step_fn, mesh=mesh,
                       in_specs=(P(), (P(None, "data"), P(None, "data"))),
                       out_specs=(P(), P()))
        jax.jit(fn)(state, (x, y))
        assert reg.counters["comm.ddp.allreduce.calls"] == 1.0
        assert reg.counters["comm.ddp.allreduce.leaves"] == 3.0
    finally:
        telemetry.set_registry(old)


def test_training_converges_o2_vs_o0():
    """Convergence-parity smoke (the L1 bar scaled down): O2 loss tracks O0."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 4), jnp.float32)
    w_true = jnp.asarray(rng.randn(4, 2), jnp.float32)
    y = x @ w_true
    losses = {}
    for lvl in ("O0", "O2"):
        policy = resolve_policy(lvl, half_dtype=jnp.bfloat16, verbose=False)
        init_fn, step_fn = make_train_step(_loss_fn, optax.sgd(0.05), policy)
        state = init_fn({"w": jnp.zeros((4, 2), jnp.float32),
                         "b": jnp.zeros((2,), jnp.float32)})
        step = jax.jit(step_fn)
        for _ in range(60):
            state, m = step(state, (x, y))
        losses[lvl] = float(m["loss"])
    assert losses["O0"] < 0.05
    assert abs(losses["O2"] - losses["O0"]) < 0.05
