"""End-to-end amp step semantics — the observable order apex tests check
(tests/L0/run_amp/test_checkpointing.py, amp_master_params): master weights,
skip-on-overflow with NO optimizer-state advance, scale schedule."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.amp import make_train_step, resolve_policy


def _loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


def _setup(opt_level="O2", half=jnp.float16, **over):
    policy = resolve_policy(opt_level, half_dtype=half, verbose=False, **over)
    opt = optax.sgd(0.1)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    params = {"w": jnp.ones((4, 2), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    state = init_fn(params)
    if state.scaler.dynamic:
        # 2**16 would overflow this toy batch's fp16 grads on step one (real
        # amp behavior: halve until it fits); a small init scale keeps the
        # happy-path tests deterministic. Overflow paths are tested explicitly.
        from apex_tpu.amp import init_scaler
        state = state.replace(scaler=init_scaler("dynamic", init_scale=256.0))
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    return policy, jax.jit(step_fn), state, (x, y)


def test_o2_master_weights_exist_and_params_half():
    policy, step, state, batch = _setup("O2")
    assert state.master_params is not None
    assert state.master_params["w"].dtype == jnp.float32
    assert state.params["w"].dtype == jnp.float16
    new_state, metrics = step(state, batch)
    # params moved and stayed half; masters stayed fp32 and mirror params
    assert new_state.params["w"].dtype == jnp.float16
    assert new_state.master_params["w"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(new_state.params["w"], np.float32),
        np.asarray(new_state.master_params["w"]).astype(np.float16).astype(np.float32))
    assert not bool(metrics["found_inf"])


def test_o0_trains_fp32_no_masters():
    policy, step, state, batch = _setup("O0")
    assert state.master_params is None
    assert state.params["w"].dtype == jnp.float32
    new_state, metrics = step(state, batch)
    assert float(metrics["loss"]) > 0
    assert not np.allclose(np.asarray(new_state.params["w"]),
                           np.asarray(state.params["w"]))


def test_overflow_skips_step_and_halves_scale():
    policy, step, state, batch = _setup("O2")
    x, y = batch
    bad = (x.at[0, 0].set(jnp.float32(1e30)), y)  # overflows f16 grads via loss scale
    new_state, metrics = step(state, bad)
    assert bool(metrics["found_inf"])
    # optimizer state did not advance, params unchanged
    np.testing.assert_array_equal(np.asarray(new_state.master_params["w"]),
                                  np.asarray(state.master_params["w"]))
    np.testing.assert_array_equal(np.asarray(new_state.params["w"], np.float32),
                                  np.asarray(state.params["w"], np.float32))
    assert float(new_state.scaler.loss_scale) == 128.0  # halved from 256
    assert int(new_state.scaler.unskipped) == 0


def test_overflow_freezes_stateful_optimizer_bitwise():
    """Regression for the cond→select skip rewrite: with a STATEFUL
    optimizer (adam mu/nu + count), an overflow step must leave every
    opt-state leaf bitwise frozen — the select path computes the update
    on inf/NaN grads and must discard all of it, count increment
    included. sgd-based overflow tests can't see this (no state leaves)."""
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False,
                            loss_scale=256.0)
    opt = optax.adam(1e-2)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32),
                     "b": jnp.zeros((2,), jnp.float32)})
    step = jax.jit(step_fn)
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    state, m = step(state, (x, y))           # clean step: state advances
    assert not bool(m["found_inf"])
    bad = (x.at[0, 0].set(jnp.float32(1e30)), y)
    new_state, m = step(state, bad)
    assert bool(m["found_inf"])
    before = jax.tree_util.tree_leaves(state.opt_state)
    after = jax.tree_util.tree_leaves(new_state.opt_state)
    assert before and len(before) == len(after)
    for a, b in zip(before, after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(new_state.master_params["w"]),
                                  np.asarray(state.master_params["w"]))


def test_clean_steps_grow_scale():
    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    opt = optax.sgd(1e-4)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    params = {"w": jnp.zeros((4, 2), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    state = init_fn(params)
    x = jnp.ones((8, 4), jnp.float32) * 0.01
    y = jnp.zeros((8, 2), jnp.float32)
    step = jax.jit(step_fn)
    # shrink window via a fresh scaler config
    from apex_tpu.amp import init_scaler
    sc = init_scaler("dynamic", init_scale=1.0, scale_window=3)
    state = state.replace(scaler=sc)
    for _ in range(3):
        state, m = step(state, (x, y))
        assert not bool(m["found_inf"])
    assert float(state.scaler.loss_scale) == 2.0


def test_static_loss_scale_o3():
    policy, step, state, batch = _setup("O3")
    assert state.master_params is None
    assert state.params["w"].dtype == jnp.float16
    new_state, metrics = step(state, batch)
    assert float(new_state.scaler.loss_scale) == 1.0


def test_o3_stateful_optimizer_traces():
    """Regression: O3 (half params, no masters) + momentum must not hit a
    lax.cond branch dtype mismatch — optimizer state stays in param dtype."""
    policy = resolve_policy("O3", half_dtype=jnp.float16, verbose=False)
    opt = optax.sgd(0.1, momentum=0.9)
    init_fn, step_fn = make_train_step(_loss_fn, opt, policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32),
                     "b": jnp.zeros((2,), jnp.float32)})
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    new_state, m = jax.jit(step_fn)(state, (x, y))
    assert new_state.params["w"].dtype == jnp.float16
    assert not bool(m["found_inf"])


def test_o1_casts_batch_to_half_compute():
    """O1 leaves params fp32 but runs compute (and thus batch inputs) in the
    half dtype — the op-table policy's coarse-grained application."""
    policy = resolve_policy("O1", verbose=False)
    seen = {}

    def probe_loss(params, batch):
        x, y = batch
        seen["x_dtype"] = x.dtype
        pred = x @ params["w"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y.astype(jnp.float32)) ** 2)

    init_fn, step_fn = make_train_step(probe_loss, optax.sgd(0.1), policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    assert state.params["w"].dtype == jnp.float32  # O1 keeps model fp32
    state, m = step_fn(state, (jnp.ones((8, 4)), jnp.zeros((8, 2))))
    assert seen["x_dtype"] == jnp.bfloat16


def test_master_params_rejects_optimizer_object():
    import pytest as _pytest
    from apex_tpu import amp as _amp

    with _pytest.raises(TypeError):
        _amp.master_params(optax.sgd(0.1))


def test_training_converges_o2_vs_o0():
    """Convergence-parity smoke (the L1 bar scaled down): O2 loss tracks O0."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 4), jnp.float32)
    w_true = jnp.asarray(rng.randn(4, 2), jnp.float32)
    y = x @ w_true
    losses = {}
    for lvl in ("O0", "O2"):
        policy = resolve_policy(lvl, half_dtype=jnp.bfloat16, verbose=False)
        init_fn, step_fn = make_train_step(_loss_fn, optax.sgd(0.05), policy)
        state = init_fn({"w": jnp.zeros((4, 2), jnp.float32),
                         "b": jnp.zeros((2,), jnp.float32)})
        step = jax.jit(step_fn)
        for _ in range(60):
            state, m = step(state, (x, y))
        losses[lvl] = float(m["loss"])
    assert losses["O0"] < 0.05
    assert abs(losses["O2"] - losses["O0"]) < 0.05
