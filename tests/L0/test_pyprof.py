"""apex_tpu.pyprof tests (reference: apex/pyprof capture→report pipeline)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import pyprof
from apex_tpu.pyprof import StepTimer, annotate, cost_report, trace


def test_cost_report_matmul_flops():
    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    rep = cost_report(lambda a, b: a @ b, a, b)
    # 2*M*N*K flops for the GEMM (XLA may fold a bit; same order required)
    expected = 2 * 128 * 256 * 64
    assert rep["flops"] == pytest.approx(expected, rel=0.5)
    assert rep["bytes_accessed"] > 0
    assert rep["arithmetic_intensity"] > 0
    assert isinstance(rep["raw"], dict)


def test_annotate_inside_jit():
    @jax.jit
    def f(x):
        with annotate("block"):
            return jnp.sin(x) * 2

    y = f(jnp.ones((8,)))
    np.testing.assert_allclose(np.asarray(y), np.sin(1.0) * 2 * np.ones(8),
                               rtol=1e-6)


def test_annotate_disabled():
    pyprof.init(enabled=False)
    try:
        with annotate("nope"):
            x = 1
        assert x == 1
    finally:
        pyprof.init(enabled=True)


def test_trace_writes_files(tmp_path):
    d = os.path.join(tmp_path, "tr")
    with trace(d):
        jax.jit(lambda x: x * 2)(jnp.ones((16,))).block_until_ready()
    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, "trace produced no files"


def test_step_timer_report():
    timer = StepTimer(warmup=2)
    for i in range(7):
        with timer.step(items=4):
            pass
    rep = timer.report()
    assert rep["steps"] == 5
    assert rep["items_per_s"] > 0
    assert rep["p90_s"] >= rep["p50_s"] >= 0
    assert StepTimer().report() == {"steps": 0}


def test_analyze_trace_per_op_table(tmp_path):
    """pyprof.analyze — the pyprof/parse + pyprof/prof stages (P42): a
    captured trace yields per-op rows with occurrences, time, and XLA's
    flop/byte accounting; pyprof.report formats them."""
    d = os.path.join(tmp_path, "tr")

    @jax.jit
    def f(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((64, 64)); w = jnp.ones((64, 64))
    f(x, w).block_until_ready()          # compile outside the capture
    n_steps = 3
    with trace(d):
        for _ in range(n_steps):
            f(x, w).block_until_ready()

    rows = pyprof.analyze(d)
    assert rows, "no ops extracted from the trace"
    for r in rows:
        assert r["occurrences"] >= 1
        assert r["total_ms"] >= 0.0
        assert r["mean_ms"] == pytest.approx(
            r["total_ms"] / r["occurrences"])
    # shares sum to ~100%
    assert sum(r["pct_time"] for r in rows) == pytest.approx(100.0, abs=1.0)
    # rows sorted by total time, descending
    times = [r["total_ms"] for r in rows]
    assert times == sorted(times, reverse=True)
    # the dominant op repeated once per step
    assert max(r["occurrences"] for r in rows) >= n_steps
    # the matmul's flops are visible somewhere in the table (2*M*N*K,
    # counted once per step) — only asserted when the backend emits
    # device-lane cost args (hlo_category rows)
    if any(r["category"] for r in rows):
        total_flops = sum(r["flops"] for r in rows)
        assert total_flops >= 2 * 64 * 64 * 64 * n_steps * 0.5
    # top= truncates
    assert len(pyprof.analyze(d, top=2)) <= 2
    # report renders every row plus a 2-line header
    txt = pyprof.report(rows)
    assert len(txt.splitlines()) == len(rows) + 2
    assert "op" in txt.splitlines()[0]


def test_analyze_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no profile runs"):
        pyprof.analyze(os.path.join(tmp_path, "nothing_here"))


def test_pyprof_cli_renders_table(tmp_path, capsys):
    """python -m apex_tpu.pyprof <dir> — the reference's
    `python -m pyprof.prof` entry point over the captured dump."""
    from apex_tpu.pyprof.__main__ import main as cli

    d = os.path.join(tmp_path, "tr")
    with trace(d):
        jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64))) \
            .block_until_ready()
    assert cli([d, "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "op" in out.splitlines()[0] and len(out.splitlines()) >= 3
    assert cli([d, "--json"]) == 0
    import json as _json
    rows = [_json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert rows and all("occurrences" in r for r in rows)
    with pytest.raises(SystemExit, match="no profile runs"):
        cli([os.path.join(tmp_path, "missing")])


def _write_trace_dump(tmp_path, trace_events):
    """Lay out a chrome-trace dump in the plugins/profile/<run>/ layout
    that jax.profiler writes, so device_busy/analyze read it like a real
    capture."""
    import gzip
    import json as _json

    run = os.path.join(tmp_path, "plugins", "profile", "run1")
    os.makedirs(run, exist_ok=True)
    path = os.path.join(run, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        _json.dump({"traceEvents": trace_events}, f)
    return str(tmp_path)


def test_device_busy_span_and_occupancy(tmp_path):
    """pyprof.device_busy — the device-time anchor bench.py's headline
    rides on: span is last-end minus first-start on the busiest device
    lane, busy is the leaf-op occupancy, host lanes are ignored."""
    evs = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        # device lane: two leaf ops with a 2us bubble between them
        {"ph": "X", "pid": 7, "tid": 1, "ts": 10.0, "dur": 4.0,
         "name": "fusion.1", "args": {"hlo_category": "convolution"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 16.0, "dur": 4.0,
         "name": "fusion.2", "args": {"hlo_category": "fusion"}},
        # host lane must not count
        {"ph": "X", "pid": 9, "tid": 1, "ts": 0.0, "dur": 100.0,
         "name": "python_loop"},
    ]
    d = pyprof.device_busy(_write_trace_dump(tmp_path, evs))
    assert d["span_ms"] == pytest.approx(10.0 / 1e3)   # 10..20us
    assert d["busy_ms"] == pytest.approx(8.0 / 1e3)    # 4 + 4
    assert d["n_events"] == 2
    assert d["n_lanes"] == 1


def test_device_busy_reads_the_busiest_lane_only(tmp_path):
    """Chrome dumps split one device into mirrored sub-lanes ("XLA Ops",
    "Steps", copy streams); summing across them would double-count, so
    device_busy reads only the lane with the most leaf-op time."""
    evs = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0 XLA Ops"}},
        {"ph": "M", "pid": 8, "name": "process_name",
         "args": {"name": "/device:TPU:0 Steps"}},
        # ops lane: 8us of work over a 10us span
        {"ph": "X", "pid": 7, "tid": 1, "ts": 10.0, "dur": 4.0,
         "name": "fusion.1", "args": {"hlo_category": "fusion"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 16.0, "dur": 4.0,
         "name": "fusion.2", "args": {"hlo_category": "fusion"}},
        # steps lane mirrors the same execution as one big span
        {"ph": "X", "pid": 8, "tid": 1, "ts": 10.0, "dur": 10.0,
         "name": "step0", "args": {"hlo_category": "step"}},
    ]
    d = pyprof.device_busy(_write_trace_dump(tmp_path, evs))
    assert d["busy_ms"] == pytest.approx(10.0 / 1e3)   # busiest lane wins
    assert d["span_ms"] == pytest.approx(10.0 / 1e3)
    assert d["busy_ms"] <= d["span_ms"] * 1.001        # duty <= 1 here
    assert d["n_events"] == 1
    assert d["n_lanes"] == 2


def test_device_busy_degraded_mode_drops_parents(tmp_path):
    """Without hlo_category annotations the leaf-span sweep applies: a
    region wrapper enclosing its ops must not double busy time."""
    evs = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 3, "tid": 1, "ts": 0.0, "dur": 10.0,
         "name": "jit_step"},
        {"ph": "X", "pid": 3, "tid": 1, "ts": 1.0, "dur": 3.0,
         "name": "op_a"},
        {"ph": "X", "pid": 3, "tid": 1, "ts": 6.0, "dur": 2.0,
         "name": "op_b"},
    ]
    d = pyprof.device_busy(_write_trace_dump(tmp_path, evs))
    assert d["busy_ms"] == pytest.approx(5.0 / 1e3)    # 3 + 2, not 15
    # span covers the LEAF ops' window (1..8), not the dropped wrapper
    assert d["span_ms"] == pytest.approx(7.0 / 1e3)


def test_device_busy_no_device_lanes_is_zero(tmp_path):
    """Host-only dumps (CPU smoke runs) return zeros so callers fall
    back to wall clock instead of dividing by a bogus span."""
    evs = [
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 9, "tid": 1, "ts": 0.0, "dur": 50.0,
         "name": "python_loop"},
    ]
    d = pyprof.device_busy(_write_trace_dump(tmp_path, evs))
    assert d == {"busy_ms": 0.0, "span_ms": 0.0,
                 "n_events": 0, "n_lanes": 0}


def test_step_device_throughput_observation_only():
    """pyprof.step_device_throughput — the recipes' --prof-device
    engine: times a copied state (donation can't invalidate the
    caller's buffers), returns None instead of raising on any failure,
    rejects nonpositive n."""
    from apex_tpu.pyprof import step_device_throughput

    @jax.jit
    def step(state, batch):
        new = jax.tree_util.tree_map(lambda x: x + batch.sum(), state)
        return new, {"loss": batch.sum()}

    donating = jax.jit(step, donate_argnums=(0,))
    state = {"w": jnp.ones((128, 128))}
    batch = jnp.ones((4, 8))
    r = step_device_throughput(donating, state, batch, 2, items_per_step=4)
    if r is not None:   # CPU dumps usually carry device lanes; if not, None
        assert r["items_per_s"] > 0
        assert r["ms_per_step"] > 0
        assert r["duty"] > 0
    # the caller's state must still be alive (profiling used a copy)
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)

    assert step_device_throughput(donating, state, batch, 0, 4) is None
    assert step_device_throughput(donating, state, batch, -3, 4) is None

    def exploding(state, batch):
        raise RuntimeError("boom")

    assert step_device_throughput(exploding, state, batch, 2, 4) is None


def test_device_throughput_line_rendering():
    """pyprof.device_throughput_line — the recipes' shared --prof-device
    rendering: None when off, its own diagnostic for negative N, the n/a
    line when no reading is possible, a formatted reading otherwise."""
    from apex_tpu.pyprof import device_throughput_line

    @jax.jit
    def step(state, batch):
        return jax.tree_util.tree_map(lambda x: x + batch.sum(), state), {}

    state = {"w": jnp.ones((64,))}
    batch = jnp.ones((4,))
    assert device_throughput_line(step, state, batch, 0, 4, "u/s") is None
    line = device_throughput_line(step, state, batch, -2, 4, "u/s")
    assert line == "device throughput: n/a (--prof-device -2 ignored)"

    def exploding(state, batch):
        raise RuntimeError("boom")

    line = device_throughput_line(exploding, state, batch, 2, 4, "u/s")
    assert line.startswith("device throughput: n/a")

    line = device_throughput_line(step, state, batch, 2, 4, "u/s")
    assert line.startswith("device throughput: ")
    if "n/a" not in line:        # CPU dumps usually carry device lanes
        assert "u/s" in line and "ms/step" in line and "duty" in line


def test_leaf_spans_drop_enclosing_parents():
    """Degraded-mode aggregation (no cost-annotated device ops) must not
    double-count: a span enclosing another on the same lane is a parent
    and is dropped; disjoint and cross-lane spans survive."""
    from apex_tpu.pyprof import _leaf_spans

    parent = {"pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0, "name": "jit_f"}
    child1 = {"pid": 1, "tid": 1, "ts": 1.0, "dur": 3.0, "name": "op_a"}
    child2 = {"pid": 1, "tid": 1, "ts": 5.0, "dur": 4.0, "name": "op_b"}
    after = {"pid": 1, "tid": 1, "ts": 11.0, "dur": 2.0, "name": "op_c"}
    other_lane = {"pid": 2, "tid": 1, "ts": 0.0, "dur": 10.0,
                  "name": "op_d"}
    out = _leaf_spans([parent, child1, child2, after, other_lane])
    names = sorted(e["name"] for e in out)
    assert names == ["op_a", "op_b", "op_c", "op_d"], names
    # nested-in-nested: only the innermost survives
    mid = {"pid": 3, "tid": 0, "ts": 0.0, "dur": 8.0, "name": "mid"}
    inner = {"pid": 3, "tid": 0, "ts": 2.0, "dur": 2.0, "name": "inner"}
    outer = {"pid": 3, "tid": 0, "ts": 0.0, "dur": 10.0, "name": "outer"}
    out = _leaf_spans([outer, mid, inner])
    assert [e["name"] for e in out] == ["inner"]


def test_leaf_spans_identical_intervals_are_siblings():
    """Two same-(ts, dur) ops on one lane are both counted — equal
    intervals are repeat ops, not parent/child."""
    from apex_tpu.pyprof import _leaf_spans

    twin_a = {"pid": 1, "tid": 1, "ts": 5.0, "dur": 2.0, "name": "op"}
    twin_b = {"pid": 1, "tid": 1, "ts": 5.0, "dur": 2.0, "name": "op"}
    out = _leaf_spans([twin_a, twin_b])
    assert len(out) == 2

    # and a custom lane key keeps independent files from nesting
    host_a = {"pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0, "name": "a"}
    host_b = {"pid": 1, "tid": 1, "ts": 2.0, "dur": 4.0, "name": "b"}
    lanes = {id(host_a): 0, id(host_b): 1}
    out = _leaf_spans([host_a, host_b],
                      lane_of=lambda e: (lanes[id(e)], e.get("pid")))
    assert len(out) == 2, "cross-file spans must not nest"


def test_leaf_spans_twin_parents_both_dropped():
    """ADVICE r4: when two identical-(ts, dur) spans BOTH enclose a
    child, both are parents and both must be dropped — not just the
    most-recently-pushed twin."""
    from apex_tpu.pyprof import _leaf_spans

    twin_a = {"pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0, "name": "tw_a"}
    twin_b = {"pid": 1, "tid": 1, "ts": 0.0, "dur": 10.0, "name": "tw_b"}
    child = {"pid": 1, "tid": 1, "ts": 2.0, "dur": 3.0, "name": "child"}
    out = _leaf_spans([twin_a, twin_b, child])
    assert [e["name"] for e in out] == ["child"]
