"""apex_tpu.pyprof tests (reference: apex/pyprof capture→report pipeline)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import pyprof
from apex_tpu.pyprof import StepTimer, annotate, cost_report, trace


def test_cost_report_matmul_flops():
    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    rep = cost_report(lambda a, b: a @ b, a, b)
    # 2*M*N*K flops for the GEMM (XLA may fold a bit; same order required)
    expected = 2 * 128 * 256 * 64
    assert rep["flops"] == pytest.approx(expected, rel=0.5)
    assert rep["bytes_accessed"] > 0
    assert rep["arithmetic_intensity"] > 0
    assert isinstance(rep["raw"], dict)


def test_annotate_inside_jit():
    @jax.jit
    def f(x):
        with annotate("block"):
            return jnp.sin(x) * 2

    y = f(jnp.ones((8,)))
    np.testing.assert_allclose(np.asarray(y), np.sin(1.0) * 2 * np.ones(8),
                               rtol=1e-6)


def test_annotate_disabled():
    pyprof.init(enabled=False)
    try:
        with annotate("nope"):
            x = 1
        assert x == 1
    finally:
        pyprof.init(enabled=True)


def test_trace_writes_files(tmp_path):
    d = os.path.join(tmp_path, "tr")
    with trace(d):
        jax.jit(lambda x: x * 2)(jnp.ones((16,))).block_until_ready()
    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, "trace produced no files"


def test_step_timer_report():
    timer = StepTimer(warmup=2)
    for i in range(7):
        with timer.step(items=4):
            pass
    rep = timer.report()
    assert rep["steps"] == 5
    assert rep["items_per_s"] > 0
    assert rep["p90_s"] >= rep["p50_s"] >= 0
    assert StepTimer().report() == {"steps": 0}
