"""Paged KV cache — block-table pool + copy-on-write sharing, hermetic.

The acceptance bar from the block-table issue, as tests:

- the paged kernels (``paged_decode_attention`` /
  ``paged_prefill_attention``) match their jnp oracles, and the oracles
  are BITWISE identical to the contiguous references over the gathered
  page view (same math, indirected storage);
- the paged engine is token-exact against the contiguous baseline
  (greedy, identical geometry) over a mixed hit/miss/evict request
  stream with prompt lengths below / at / straddling page boundaries;
- a prefix-cache hit on the paged path performs ZERO KV data movement:
  the engine compiles exactly THREE programs (chunk prefill + decode +
  monolithic prefill) across a stream that includes hits — the
  contiguous layout's fourth (row-copy) program never traces, pinned by
  trace counters and by ``copy_kv`` refusing to run at all;
- copy-on-write refcount pinning: a shared page is never freed while
  any slot or prefix entry references it, and the first write past a
  shared prefix lands on a freshly allocated page (never the donor's);
- pool-exhaustion degradation: admission blocks (requests queue, FIFO
  holds, ``serving.pool.admit_blocked`` counts) instead of failing
  mid-decode, prefix entries are LRU-evicted under reservation
  pressure, and the engine constructor refuses pools too small for one
  ``max_len`` request — so the drain loop can never deadlock;
- the ``serving.pool.*`` telemetry gauges (pages_in_use / pages_free /
  cow_shares / fragmentation) land in the registry every step.

Everything runs on CPU with a tiny model at policy O0 (exact fp32);
the paged kernels take their interpret/reference paths here (Mosaic
lowering is the tests/tpu tier's job).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.kernels.decode_attention import (
    decode_attention_reference, gather_pages, paged_decode_attention,
    paged_decode_attention_reference)
from apex_tpu.kernels.prefill_attention import (
    paged_prefill_attention, paged_prefill_attention_reference,
    prefill_attention_reference)
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, PagedKVCache, PagePool, Request,
                              Scheduler)

pytestmark = pytest.mark.serving

VOCAB = 101
CHUNK = 8     # engine chunk_len == page_len below: every chunk is 1 page


# ------------------------------------------------------------ page pool
def test_page_pool_alloc_share_release_refcounts():
    pool = PagePool(num_pages=5, page_len=8)
    assert pool.free_pages == 4 and pool.pages_in_use == 0   # page 0 = sentinel
    a, b = pool.alloc(), pool.alloc()
    assert a != b and 0 not in (a, b)
    assert pool.pages_in_use == 2 and pool.cow_shares == 0
    pool.share([a])                       # second reader: COW share
    assert pool.cow_shares == 1
    pool.release([a])                     # first reader gone: page lives
    assert pool.pages_in_use == 2 and pool.cow_shares == 0
    pool.release([a, b])                  # last readers: both freed
    assert pool.pages_in_use == 0 and pool.free_pages == 4
    with pytest.raises(ValueError, match="already free"):
        pool.release([a])
    with pytest.raises(ValueError, match="cannot share"):
        pool.share([a])
    with pytest.raises(ValueError, match="out of range"):
        pool.share([0])                   # the sentinel is never shared


def test_page_pool_reservation_ledger():
    pool = PagePool(num_pages=6, page_len=4)      # 5 usable
    assert pool.available == 5
    assert pool.reserve(3)
    assert pool.available == 2 and pool.free_pages == 5
    assert not pool.reserve(3)                    # over-promise refused
    assert pool.reserve(2) and pool.available == 0
    # a reserved alloc draws the ledger down with the page
    p = pool.alloc(reserved=True)
    assert p is not None and pool.reserved_total == 4
    pool.unreserve(4)
    assert pool.available == pool.free_pages == 4
    # exhaustion returns None, never raises
    for _ in range(4):
        assert pool.alloc() is not None
    assert pool.alloc() is None
    assert pool.pages_for(0) == 0 and pool.pages_for(1) == 1
    assert pool.pages_for(4) == 1 and pool.pages_for(5) == 2


def test_page_pool_fragmentation_and_validation():
    pool = PagePool(num_pages=4, page_len=8)
    # 2 slots, 3 pages allocated, 20/24 positions valid
    assert pool.fragmentation([12, 8], [2, 1]) == pytest.approx(1 - 20 / 24)
    assert pool.fragmentation([], []) == 0.0
    with pytest.raises(ValueError, match="sentinel"):
        PagePool(num_pages=1, page_len=8)
    with pytest.raises(ValueError, match="page_len"):
        PagePool(num_pages=4, page_len=0)
    with pytest.raises(ValueError, match="sentinel"):
        PagedKVCache.create(layers=1, num_pages=1, heads=1, page_len=8,
                            head_dim=4)


def test_paged_kv_cache_geometry():
    c = PagedKVCache.create(layers=2, num_pages=5, heads=3, page_len=16,
                            head_dim=8, dtype=jnp.bfloat16)
    assert (c.layers, c.num_pages, c.heads, c.page_len, c.head_dim) \
        == (2, 5, 3, 16, 8)
    assert c.dtype == jnp.bfloat16
    assert c.nbytes() == 2 * 5 * 3 * 16 * 8 * 2 * 2


# -------------------------------------------------------- paged kernels
def test_paged_decode_kernel_matches_oracle_and_contiguous_reference():
    rng = np.random.default_rng(0)
    B, H, D, PL, NP, MAXP = 3, 2, 16, 128, 7, 4
    scale = 1.0 / D ** 0.5
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.float32)
    pt = jnp.asarray(rng.integers(0, NP, size=(B, MAXP)), jnp.int32)
    # below / at / straddling page boundaries, plus 0 (dead slot) + full
    for L in ([5, 128, 130], [0, 200, 512], [1, 127, 129]):
        lengths = jnp.asarray(L, jnp.int32)
        ref = paged_decode_attention_reference(q, kp, vp, pt, lengths,
                                               scale=scale)
        out = paged_decode_attention(q, kp, vp, pt, lengths,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-6)
        # the oracle IS the contiguous reference over the gathered view
        # — bitwise, which is what makes paged-vs-contiguous engine
        # parity a storage claim rather than a numerics claim
        kg, vg = gather_pages(kp, pt), gather_pages(vp, pt)
        contig = decode_attention_reference(q, kg, vg, lengths,
                                            scale=scale)
        assert (np.asarray(ref) == np.asarray(contig)).all()
    # rows with length 0 return exactly zero (dead serving slots)
    out = paged_decode_attention(q, kp, vp, pt,
                                 jnp.asarray([0, 3, 0], jnp.int32),
                                 interpret=True)
    assert (np.asarray(out)[[0, 2]] == 0).all()


def test_paged_decode_kernel_bf16_and_fallback():
    rng = np.random.default_rng(1)
    B, H, D, PL, NP, MAXP = 2, 2, 16, 128, 5, 2
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.bfloat16)
    kp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.bfloat16)
    pt = jnp.asarray(rng.integers(0, NP, size=(B, MAXP)), jnp.int32)
    lengths = jnp.asarray([100, 256], jnp.int32)
    ref = paged_decode_attention_reference(q, kp, vp, pt, lengths,
                                           scale=0.25)
    out = paged_decode_attention(q, kp, vp, pt, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)
    # unaligned page_len (not a lane multiple) falls back to the oracle
    out_fb = paged_decode_attention(q[:, :, :], kp[:, :, :24],
                                    vp[:, :, :24], pt,
                                    jnp.asarray([10, 40], jnp.int32))
    assert out_fb.shape == (B, H, D)
    with pytest.raises(ValueError, match="page_table"):
        paged_decode_attention(q, kp, vp, pt[0], lengths)
    with pytest.raises(ValueError, match="lengths"):
        paged_decode_attention(q, kp, vp, pt, lengths[:1])
    with pytest.raises(ValueError, match="pools"):
        paged_decode_attention(q, kp, vp[:, :1], pt, lengths)


def test_paged_prefill_kernel_matches_oracle_across_offsets():
    rng = np.random.default_rng(2)
    B, H, C, D, PL, NP, MAXP = 2, 2, 16, 16, 128, 7, 4
    scale = 1.0 / D ** 0.5
    q = jnp.asarray(rng.normal(size=(B, H, C, D)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(NP, H, PL, D)), jnp.float32)
    pt = jnp.asarray(rng.integers(0, NP, size=(B, MAXP)), jnp.int32)
    for offs in ([0, 0], [128, 200], [496, 3]):
        off = jnp.asarray(offs, jnp.int32)
        ref = paged_prefill_attention_reference(q, kp, vp, pt, off,
                                                scale=scale)
        out = paged_prefill_attention(q, kp, vp, pt, off, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-6)
        kg, vg = gather_pages(kp, pt), gather_pages(vp, pt)
        contig = prefill_attention_reference(q, kg, vg, off, scale=scale)
        assert (np.asarray(ref) == np.asarray(contig)).all()
    # q-block override exercises the multi-q-block grid
    q2 = jnp.asarray(rng.normal(size=(B, H, 256, D)), jnp.float32)
    off = jnp.asarray([128, 200], jnp.int32)
    ref = paged_prefill_attention_reference(q2, kp, vp, pt, off,
                                            scale=scale)
    out = paged_prefill_attention(q2, kp, vp, pt, off, block_q=64,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-6)
    with pytest.raises(ValueError, match="offsets"):
        paged_prefill_attention(q, kp, vp, pt, off[:1])


# ------------------------------------------------------------ engines
def _tiny_lm(max_seq_len=64, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, paged, pool=2, slots=3, seed=5,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine_pair(lm_and_params):
    """One paged engine + one contiguous engine, identical geometry —
    the parity pair (jit caches warm across the module)."""
    return (_mk_engine(lm_and_params, paged=True),
            _mk_engine(lm_and_params, paged=False))


def test_paged_engine_geometry_and_defaults(engine_pair):
    ep, ec = engine_pair
    assert ep.paged and not ec.paged
    assert ep.page_len == CHUNK           # min(chunk, 128) -> chunk
    assert ep.max_pages == 64 // CHUNK
    # default pool budget == the contiguous layout's rows (+ sentinel)
    assert ep.num_pages == (3 + 2) * ep.max_pages + 1
    assert ep.pool.free_pages == ep.num_pages - 1


def test_paged_engine_validation(lm_and_params):
    with pytest.raises(ValueError, match="divide chunk_len"):
        _mk_engine(lm_and_params, paged=True, page_len=5)
    with pytest.raises(ValueError, match="cannot hold even one"):
        _mk_engine(lm_and_params, paged=True, num_pages=4)
    eng = _mk_engine(lm_and_params, paged=True, pool=0)
    assert eng.prefix_cache is None
    with pytest.raises(RuntimeError, match="retired"):
        eng.copy_kv(0, 1, 8)
    with pytest.raises(RuntimeError, match="prefix cache"):
        eng.retain_prefix(0, [1] * 8)
    with pytest.raises(ValueError, match="page-aligned"):
        eng.prefill_chunk(0, [1, 2], 3)
    ec = _mk_engine(lm_and_params, paged=False, pool=0)
    with pytest.raises(RuntimeError, match="paged=False"):
        ec.release_slot(0)
    with pytest.raises(RuntimeError, match="paged=False"):
        ec.pages_required(8, 4)


def _boundary_cases():
    """(prompt_a, prompt_b, expected_reuse) with shared-prefix lengths
    below / at / straddling page boundaries (page_len == CHUNK == 8) and
    spanning two pages — the same sweep test_prefix_cache runs on the
    contiguous layout."""
    rng = np.random.default_rng(42)
    out = []
    for pre_len, want in [(5, 0), (8, 8), (13, 8), (16, 16)]:
        pre = list(rng.integers(1, VOCAB, size=pre_len))
        out.append((pre + list(rng.integers(1, VOCAB, size=3)),
                    pre + list(rng.integers(1, VOCAB, size=3)), want))
    return out


def test_paged_token_exact_vs_contiguous_over_hit_miss_evict_stream(
        engine_pair, lm_and_params):
    """THE acceptance pin: greedy tokens from the paged engine (with
    copy-on-write prefix retention on) match the contiguous baseline
    (same geometry, retention on) request-for-request across a stream
    that drives misses, hits, boundary-length prompts and (on the
    1-row contiguous pool of test_prefix_cache's sibling sweep)
    evictions — and both match one teacher-forcing recompute."""
    m, params = lm_and_params
    ep, ec = engine_pair
    ep.reset(clear_prefixes=True)
    ec.reset(clear_prefixes=True)
    sp = Scheduler(ep, retain_prefixes=True)
    sc = Scheduler(ec, retain_prefixes=True)
    for prompt_a, prompt_b, want_reuse in _boundary_cases():
        for prompt in (prompt_a, prompt_b):
            (rp,) = sp.run([Request(prompt=list(prompt),
                                    max_new_tokens=5)])
            (rc,) = sc.run([Request(prompt=list(prompt),
                                    max_new_tokens=5)])
            assert rp.output_tokens == rc.output_tokens, \
                f"paged diverged from contiguous (prompt len {len(prompt)})"
            assert rp.reused_tokens == rc.reused_tokens
            assert rp.chunks == rc.chunks
        assert rp.reused_tokens == want_reuse
        # teacher-forcing recompute re-derives every greedy step
        seq = jnp.asarray([list(prompt_b) + rp.output_tokens], jnp.int32)
        full = m.apply({"params": params}, seq, train=False)
        want = np.asarray(jnp.argmax(full[0], axis=-1))
        for i, tok in enumerate(rp.output_tokens):
            assert tok == int(want[len(prompt_b) - 1 + i]), \
                f"recompute divergence at token {i}"


def test_exactly_three_compiled_programs_with_zero_copy_hits(
        engine_pair):
    """The re-derived program pin: the same hit/miss stream that pins
    FOUR programs on the contiguous engine (chunk + decode + monolithic
    + row-copy) pins THREE here — a prefix hit is host bookkeeping plus
    the existing programs, never a copy dispatch. copy_traces stays 0
    across the whole module (every earlier test rode these engines)."""
    ep, _ = engine_pair
    ep.reset(clear_prefixes=True)
    sched = Scheduler(ep, retain_prefixes=True)
    rng = np.random.default_rng(1)
    pre = list(rng.integers(1, VOCAB, size=16))
    sched.run([Request(prompt=pre + [7, 8], max_new_tokens=3)])   # miss
    (hit,) = sched.run([Request(prompt=pre + [9], max_new_tokens=3)])
    assert hit.reused_tokens == 16
    ep.prefill(0, [5, 9, 2])          # the monolithic baseline compiles
    assert (ep.chunk_traces, ep.decode_traces, ep.prefill_traces,
            ep.copy_traces) == (1, 1, 1, 0)
    assert ep.compiled_programs == 3
    assert ep._jit_copy is None       # the program object never exists


def test_cow_shared_page_never_freed_while_referenced(engine_pair):
    """Copy-on-write refcount pinning, observed at the page level: the
    donor entry's pages are shared into the hitting slot's table (one
    page, >= 2 readers, ZERO copies); releasing either reader alone
    keeps the page resident; write-after-share lands on a FRESH page —
    the donor's pages are never written by the borrower."""
    ep, _ = engine_pair
    ep.reset(clear_prefixes=True)
    sched = Scheduler(ep, retain_prefixes=True)
    rng = np.random.default_rng(9)
    pre = list(rng.integers(1, VOCAB, size=8))     # exactly one page
    sched.run([Request(prompt=pre + [1], max_new_tokens=2)])
    stats = ep.pool_stats()
    assert stats["pages_in_use"] == 1              # the retained page
    assert stats["cow_shares"] == 0
    # b hits pre and stays live (manual stepping)
    b = Request(prompt=pre + [2, 3], max_new_tokens=50)
    sched.submit(b)
    while b.status != "running":
        sched.step()
    assert b.reused_tokens == 8
    shared = int(ep._page_table[ [s for s, r in
                                  enumerate(sched._running)
                                  if r is b][0], 0])
    assert ep.pool.refcount[shared] == 2           # entry + b's slot
    assert ep.pool_stats()["cow_shares"] == 1
    # write-after-share: b's tail page (holding its unique tokens and
    # decode writes) is NOT the shared page
    slot = [s for s, r in enumerate(sched._running) if r is b][0]
    tail = int(ep._page_table[slot, 1])
    assert tail != shared and ep.pool.refcount[tail] == 1
    # evicting the donor entry mid-flight is harmless: the page's slot
    # refcount keeps it resident
    assert ep.prefix_cache.evict_lru()
    assert ep.pool.refcount[shared] == 1
    while sched.pending:
        sched.step()
    assert b.status == "finished"
    # last reader gone: page freed NOW (immediate reclamation)
    assert ep.pool.refcount[shared] == 0
    assert ep.pool_stats()["pages_in_use"] == 0


def test_pool_exhaustion_queues_admissions_and_degrades_gracefully(
        lm_and_params):
    """A pool sized for ONE max-budget request at a time: three such
    requests serve back-to-back (admission blocks on reservation, FIFO
    holds, admit_blocked counts) — exhaustion is a queueing signal,
    never a mid-decode failure. Prefix entries give way under pressure
    (LRU eviction at reservation time)."""
    # max_len 64, page 8 -> 8 pages/request worst case; 9 usable pages
    eng = _mk_engine(lm_and_params, paged=True, pool=2, slots=3,
                     num_pages=10)
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(eng, retain_prefixes=True, registry=reg)
    rng = np.random.default_rng(4)
    reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                    max_new_tokens=56) for _ in range(3)]
    done = sched.run(reqs)
    assert len(done) == 3
    assert all(r.status == "finished" for r in reqs)
    snap = reg.snapshot()
    assert snap["counters"].get("serving.pool.admit_blocked", 0) > 0
    # the first request's retained prefix was evicted to make room
    # for a later reservation (pressure valve) — pool back to empty
    sched_stats = eng.pool_stats()
    assert sched_stats["pages_reserved"] == 0
    assert eng.prefix_cache.evictions >= 1
    # direct (scheduler-less) overcommit fails loudly, not silently
    eng.reset(clear_prefixes=True)
    eng.prefill_chunked(0, list(rng.integers(1, VOCAB, size=24)))
    eng.prefill_chunked(1, list(rng.integers(1, VOCAB, size=24)))
    with pytest.raises(RuntimeError, match="pool exhausted"):
        # 9 usable pages; two 24-token prompts hold 6, a third needs 3
        # more for its padded window plus decode growth past it
        eng.prefill_chunked(2, list(rng.integers(1, VOCAB, size=24)))
        for _ in range(60):
            eng.decode_step([1, 1, 1], [True, True, True],
                            [0.0, 0.0, 0.0])


def test_cold_start_paths_keep_the_admission_reservation(lm_and_params):
    """Regression (review finding): every cold-start release inside an
    admitted request — the first chunk's offset-0 branch AND the
    monolithic prefill — must pass keep_reservation, or the admission
    promise silently evaporates and a later admission can steal the
    pages, resurrecting the mid-decode exhaustion the reservation
    design exists to prevent."""
    eng = _mk_engine(lm_and_params, paged=True, pool=0, slots=2)
    assert eng.try_reserve_slot(0, 5)
    assert eng.pool.reserved_total == 5
    eng.prefill_chunk(0, [1, 2, 3], 0)            # offset-0 cold start
    # one page drawn FROM the reservation, the rest still promised
    assert int(eng._slot_reserved[0]) == 4
    assert eng.pool.reserved_total == 4
    eng.release_slot(0)
    assert eng.pool.reserved_total == 0
    assert eng.try_reserve_slot(1, 5)
    eng.prefill(1, [1, 2, 3])                     # monolithic cold start
    assert int(eng._slot_reserved[1]) == 5 - eng.pool.pages_for(
        eng.prefill_len)
    assert eng.pool.reserved_total == int(eng._slot_reserved[1])
    eng.release_slot(1)


def test_paged_pool_telemetry_gauges_and_request_records(engine_pair):
    ep, _ = engine_pair
    ep.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    ep.set_registry(reg)
    sched = Scheduler(ep, retain_prefixes=True, registry=reg)
    rng = np.random.default_rng(11)
    pre = list(rng.integers(1, VOCAB, size=16))
    reqs = [Request(prompt=pre + [1], max_new_tokens=3),
            Request(prompt=pre + [2, 3], max_new_tokens=3)]
    try:
        sched.run([reqs[0]])
        sched.run([reqs[1]])
    finally:
        ep.set_registry(None)
    snap = reg.snapshot()
    g = snap["gauges"]
    for key in ("serving.pool.pages_in_use", "serving.pool.pages_free",
                "serving.pool.cow_shares", "serving.pool.fragmentation"):
        assert key in g, f"missing gauge {key}"
    assert g["serving.pool.pages_in_use"] >= 0
    assert 0.0 <= g["serving.pool.fragmentation"] <= 1.0
    c = snap["counters"]
    assert c["serving.prefix.hits"] == 1
    assert c["serving.prefix.tokens_reused"] == 16
    recs = {rec["uid"]: rec for rec in reg.records
            if rec.get("tag") == "serving.request"}
    assert recs[reqs[0].uid]["reused_tokens"] == 0
    assert recs[reqs[1].uid]["reused_tokens"] == 16


def test_paged_reset_keeps_warm_prefix_pages_unless_cleared(engine_pair):
    ep, _ = engine_pair
    ep.reset(clear_prefixes=True)
    sched = Scheduler(ep, retain_prefixes=True)
    pre = list(np.random.default_rng(13).integers(1, VOCAB, size=8))
    sched.run([Request(prompt=pre + [1], max_new_tokens=2)])
    ep.reset()                    # warm: the entry keeps its page
    assert ep.pool_stats()["pages_in_use"] == 1
    (r,) = Scheduler(ep, retain_prefixes=True).run(
        [Request(prompt=pre + [2], max_new_tokens=2)])
    assert r.reused_tokens == 8, "reset() must not drop warm prefixes"
    ep.reset(clear_prefixes=True)
    assert ep.pool_stats()["pages_in_use"] == 0
    assert ep.prefix_cache.size == 0


def test_logical_requests_outlive_physical_rows(lm_and_params):
    """The capacity unlock in miniature: a pool holding the bytes of
    THREE contiguous rows serves a 9-request short-prompt stream
    through 3 slots with room to spare, because each request only ever
    holds the pages it uses and frees them at completion — the
    contiguous layout would spend 3 full rows regardless of length."""
    eng = _mk_engine(lm_and_params, paged=True, pool=0, slots=3,
                     num_pages=3 * 8 + 1)
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(eng, registry=reg)
    rng = np.random.default_rng(8)
    reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=4)),
                    max_new_tokens=3) for _ in range(9)]
    done = sched.run(reqs)
    assert len(done) == 9 and all(r.status == "finished" for r in reqs)
    # worst-case page use per request: 1 page (4+3 tokens < page 8),
    # but the reservation is chunk-padded — still far under a row
    assert eng.pool_stats()["pages_in_use"] == 0
    snap = reg.snapshot()
    assert snap["counters"].get("serving.pool.admit_blocked", 0) == 0
