"""Fused LN/RMSNorm kernel tests — mirrors
tests/L0/run_fused_layer_norm/test_fused_layer_norm.py: fused op vs composed
reference (torch.nn.LayerNorm oracle where available) with dtype-dependent
tolerances; Pallas path exercised via interpret=True on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import (layer_norm, layer_norm_reference, rms_norm,
                              rms_norm_reference)

SHAPES = [(4, 256), (3, 5, 384), (16, 128)]


def _rand(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape), dtype)


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 1e-2)])
def test_layer_norm_forward_vs_reference(shape, dtype, tol):
    h = shape[-1]
    x = _rand(shape, dtype)
    w = _rand((h,), dtype, 1) * 0.5 + 1.0
    b = _rand((h,), dtype, 2) * 0.1
    out = layer_norm(x, w, b, interpret=True)
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=tol, rtol=tol)


def test_layer_norm_vs_torch_oracle():
    import torch

    h = 256
    x = _rand((8, h), jnp.float32)
    w = _rand((h,), jnp.float32, 1)
    b = _rand((h,), jnp.float32, 2)
    out = layer_norm(x, w, b, interpret=True)
    tx = torch.tensor(np.asarray(x))
    tln = torch.nn.LayerNorm(h)
    with torch.no_grad():
        tln.weight.copy_(torch.tensor(np.asarray(w)))
        tln.bias.copy_(torch.tensor(np.asarray(b)))
    ref = tln(tx).detach().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("rms", [False, True])
def test_grads_match_reference(rms):
    h = 256
    x = _rand((6, h), jnp.float32)
    w = _rand((h,), jnp.float32, 1) * 0.3 + 1.0
    b = _rand((h,), jnp.float32, 2) * 0.2

    if rms:
        def fused(x, w):
            return jnp.sum(rms_norm(x, w, interpret=True) ** 2)

        def ref(x, w):
            return jnp.sum(rms_norm_reference(x, w) ** 2)

        args = (x, w)
    else:
        def fused(x, w, b):
            return jnp.sum(layer_norm(x, w, b, interpret=True) ** 2)

        def ref(x, w, b):
            return jnp.sum(layer_norm_reference(x, w, b) ** 2)

        args = (x, w, b)

    g_fused = jax.grad(fused, argnums=tuple(range(len(args))))(*args)
    g_ref = jax.grad(ref, argnums=tuple(range(len(args))))(*args)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-4, rtol=2e-4)


def test_non_affine_variant():
    x = _rand((4, 128), jnp.float32)
    out = layer_norm(x, interpret=True)
    ref = layer_norm_reference(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    g = jax.grad(lambda x: jnp.sum(layer_norm(x, interpret=True) ** 2))(x)
    gr = jax.grad(lambda x: jnp.sum(layer_norm_reference(x) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=2e-4)


def test_unaligned_hidden_falls_back():
    # H=100 not lane-aligned: jnp fallback path must be numerically identical
    x = _rand((4, 100), jnp.float32)
    w = jnp.ones((100,), jnp.float32)
    b = jnp.zeros((100,), jnp.float32)
    out = layer_norm(x, w, b)
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_rms_norm_forward():
    x = _rand((5, 384), jnp.bfloat16)
    w = _rand((384,), jnp.bfloat16, 1) * 0.2 + 1.0
    out = rms_norm(x, w, interpret=True)
    ref = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=1e-2,
                               rtol=1e-2)


def test_odd_row_counts_padded_correctly():
    # 7 rows: exercises row padding/slicing
    x = _rand((7, 128), jnp.float32)
    out = layer_norm(x, interpret=True)
    ref = layer_norm_reference(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("rms", [False, True])
@pytest.mark.parametrize("affine", [True, False])
def test_memory_efficient_grads_match_default(rms, affine):
    """memory_efficient=True (save y, reconstruct xhat=(y-beta)/gamma —
    apex's flag) must compute the SAME gradients as the default
    save-x path, through the Pallas bwd (interpret) and jnp fallback."""
    h = 256
    x = _rand((6, h), jnp.float32)
    w = (_rand((h,), jnp.float32, 1) * 0.3 + 1.0) if affine else None
    b = (_rand((h,), jnp.float32, 2) * 0.2) if (affine and not rms) else None

    def run(me, interpret):
        if rms:
            fn = lambda x, w: jnp.sum(  # noqa: E731
                rms_norm(x, w, interpret=interpret,
                         memory_efficient=me) ** 2)
            args = (x, w) if affine else (x, None)
        else:
            fn = lambda x, w, b: jnp.sum(  # noqa: E731
                layer_norm(x, w, b, interpret=interpret,
                           memory_efficient=me) ** 2)
            args = (x, w, b) if affine else (x, None, None)
        nargs = 1 if not affine else (2 if rms else 3)
        return jax.grad(fn, argnums=tuple(range(nargs)))(*args)

    for interpret in (True, False):   # Pallas path and jnp fallback
        g_me = run(True, interpret)
        g_df = run(False, interpret)
        for gm, gd in zip(g_me, g_df):
            np.testing.assert_allclose(np.asarray(gm), np.asarray(gd),
                                       atol=2e-4, rtol=2e-4)


def test_memory_efficient_module_flag():
    """The modules expose apex's memory_efficient flag and train the
    same direction as the default."""
    from apex_tpu.normalization import FusedLayerNorm

    x = _rand((4, 128), jnp.float32)
    m = FusedLayerNorm(128, memory_efficient=True)
    params = m.init(jax.random.PRNGKey(0), x)
    y, ref = m.apply(params, x), FusedLayerNorm(128).apply(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)
    g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
    gr = jax.grad(lambda p: jnp.sum(
        FusedLayerNorm(128).apply(p, x) ** 2))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_memory_efficient_weight_without_bias():
    """Weight-only affine (bias=None) through BOTH mem_eff backwards —
    the jnp fallback used to crash on beta.astype (review r5)."""
    h = 256
    x = _rand((6, h), jnp.float32)
    w = _rand((h,), jnp.float32, 1) * 0.3 + 1.0

    for interpret in (True, False):
        g_me = jax.grad(lambda x, w: jnp.sum(layer_norm(
            x, w, None, interpret=interpret,
            memory_efficient=True) ** 2), argnums=(0, 1))(x, w)
        g_df = jax.grad(lambda x, w: jnp.sum(layer_norm(
            x, w, None, interpret=interpret) ** 2), argnums=(0, 1))(x, w)
        for a, b in zip(g_me, g_df):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)
