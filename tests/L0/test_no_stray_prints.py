"""Library output discipline: no bare ``print(`` statements inside
``apex_tpu/`` outside the CLI entry points.

Telemetry sinks and ``apex_tpu.get_logger`` are the sanctioned output
paths — a library that prints can't be silenced, redirected, or parsed
(the reference apex prints freely; this port routes everything through
logging/telemetry). Only the CLI ``__main__.py`` modules, whose job IS
stdout, may print.
"""

import os
import re

import pytest

pytestmark = pytest.mark.telemetry

#: the sanctioned CLI entry points, relative to apex_tpu/
CLI_ALLOWLIST = {
    os.path.join("pyprof", "__main__.py"),
    os.path.join("telemetry", "__main__.py"),
    os.path.join("parallel", "multiproc.py"),
}

# statement-position print: start of line (any indent) — excludes
# docstring examples (">>> print("), methods (.print_exc), and comments
_PRINT_RE = re.compile(r"^\s*print\(", re.MULTILINE)


def _package_root():
    import apex_tpu
    return os.path.dirname(os.path.abspath(apex_tpu.__file__))


def test_no_bare_print_outside_cli_entry_points():
    root = _package_root()
    offenders = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel in CLI_ALLOWLIST:
                continue
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for m in _PRINT_RE.finditer(src):
                line_no = src.count("\n", 0, m.start()) + 1
                offenders.append(f"{rel}:{line_no}")
    assert not offenders, (
        "bare print( in library code (use apex_tpu.get_logger or a "
        "telemetry sink; only CLI __main__ modules may print): "
        + ", ".join(offenders))


def test_allowlist_entries_exist():
    """The allowlist must not rot: every sanctioned path is a real file."""
    root = _package_root()
    for rel in CLI_ALLOWLIST:
        assert os.path.isfile(os.path.join(root, rel)), rel
